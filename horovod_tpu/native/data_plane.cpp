#include "data_plane.h"

#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <thread>

#include "profiler.h"
#include "shm_transport.h"
#include "socket_util.h"
#include "timeline.h"

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

namespace hvdtpu {

namespace {

// --- fp16 / bf16 conversion (reference: horovod/common/half.{h,cc}) ---------

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {
      // subnormal: normalize
      exp = 127 - 15 + 1;
      while ((mant & 0x400u) == 0) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3ffu;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1f) {
    bits = sign | 0x7f800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t FloatToHalf(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (exp >= 0x1f) {
    // NaN must stay NaN (nonzero mantissa); inf and overflow saturate.
    if (((bits >> 23) & 0xffu) == 0xffu && mant != 0)
      return static_cast<uint16_t>(sign | 0x7e00u);
    return static_cast<uint16_t>(sign | 0x7c00u);
  }
  if (exp <= 0) {
    // Subnormal result. Round-to-nearest-EVEN on the dropped bits (the old
    // round-half-up biased every exact tie upward, e.g. 2^-25 -> 2^-24
    // instead of 0), matching IEEE 754 and the F16C hardware path.
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint16_t h = static_cast<uint16_t>(sign | (mant >> shift));
    uint32_t rem = mant & ((1u << shift) - 1u);
    uint32_t half = 1u << (shift - 1);
    if (rem > half || (rem == half && (h & 1u))) h++;
    return h;
  }
  // Normal result: round-to-nearest-even on the 13 dropped mantissa bits.
  // A mantissa carry correctly rolls into the exponent (and 65520+ to inf).
  uint16_t h = static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (h & 1u))) h++;
  return h;
}

inline float Bf16ToFloat(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t FloatToBf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  // NaN first: the rounding add below would carry its mantissa into the
  // exponent (NaN -> inf) or even the sign bit (0x7fffffff -> -0.0).
  if ((bits & 0x7fffffffu) > 0x7f800000u)
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);  // quiet NaN
  // round-to-nearest-even
  uint32_t rounded = bits + 0x7fffu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>(rounded >> 16);
}

// --- reduction kernels ------------------------------------------------------
// The op is resolved ONCE per buffer (functor template parameter), never per
// element, and the inner loops carry __restrict__ so -O3 can vectorize them.

struct SumOp {
  template <typename T> T operator()(T a, T b) const { return a + b; }
};
struct MinOp {
  template <typename T> T operator()(T a, T b) const { return std::min(a, b); }
};
struct MaxOp {
  template <typename T> T operator()(T a, T b) const { return std::max(a, b); }
};
struct ProdOp {
  template <typename T> T operator()(T a, T b) const { return a * b; }
};

template <typename T, typename Op>
void ReduceLoop(T* __restrict__ dst, const T* __restrict__ src, int64_t count,
                Op op) {
  for (int64_t i = 0; i < count; ++i) dst[i] = op(dst[i], src[i]);
}

template <typename T>
void ReduceTyped(T* dst, const T* src, int64_t count, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:
    case ReduceOp::ADASUM:
      ReduceLoop(dst, src, count, SumOp{});
      break;
    case ReduceOp::MIN:
      ReduceLoop(dst, src, count, MinOp{});
      break;
    case ReduceOp::MAX:
      ReduceLoop(dst, src, count, MaxOp{});
      break;
    case ReduceOp::PRODUCT:
      ReduceLoop(dst, src, count, ProdOp{});
      break;
  }
}

#if defined(__x86_64__)
// Fused fp16 convert+add+convert, 8 lanes per step (F16C). The hardware
// conversions are full IEEE round-to-nearest-even including subnormals, so
// this is bit-identical to the scalar HalfToFloat/FloatToHalf path for
// numeric values (NaNs stay NaN but may carry a different payload).
__attribute__((target("avx2,f16c")))
void HalfSumF16C(uint16_t* __restrict__ dst, const uint16_t* __restrict__ src,
                 int64_t count) {
  int64_t i = 0;
  for (; i + 8 <= count; i += 8) {
    __m256 a = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(dst + i)));
    __m256 b = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i)));
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm256_cvtps_ph(_mm256_add_ps(a, b),
                                     _MM_FROUND_TO_NEAREST_INT));
  }
  for (; i < count; ++i) {
    dst[i] = FloatToHalf(HalfToFloat(dst[i]) + HalfToFloat(src[i]));
  }
}

// Fused bf16 convert+add+convert, 8 lanes per step: widen by shift, add as
// f32, round-to-nearest-even back by integer arithmetic (same formula as
// the scalar FloatToBf16, including the NaN-quieting blend).
__attribute__((target("avx2")))
void Bf16SumAvx2(uint16_t* __restrict__ dst, const uint16_t* __restrict__ src,
                 int64_t count) {
  const __m256i vexpmask = _mm256_set1_epi32(0x7fffffff);
  const __m256i vinf = _mm256_set1_epi32(0x7f800000);
  const __m256i vbias = _mm256_set1_epi32(0x7fff);
  const __m256i vone = _mm256_set1_epi32(1);
  const __m256i vquiet = _mm256_set1_epi32(0x0040);
  int64_t i = 0;
  for (; i + 8 <= count; i += 8) {
    __m256i a = _mm256_slli_epi32(
        _mm256_cvtepu16_epi32(
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(dst + i))), 16);
    __m256i b = _mm256_slli_epi32(
        _mm256_cvtepu16_epi32(
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i))), 16);
    __m256i s = _mm256_castps_si256(_mm256_add_ps(_mm256_castsi256_ps(a),
                                                  _mm256_castsi256_ps(b)));
    // round-to-nearest-even: bits + 0x7fff + ((bits >> 16) & 1)
    __m256i rounded = _mm256_srli_epi32(
        _mm256_add_epi32(_mm256_add_epi32(s, vbias),
                         _mm256_and_si256(_mm256_srli_epi32(s, 16), vone)),
        16);
    // NaN sum (|bits| > inf): quiet NaN instead of letting the rounding add
    // carry the mantissa into the exponent/sign.
    __m256i nan_mask = _mm256_cmpgt_epi32(_mm256_and_si256(s, vexpmask), vinf);
    __m256i quieted = _mm256_or_si256(_mm256_srli_epi32(s, 16), vquiet);
    __m256i out32 = _mm256_blendv_epi8(rounded, quieted, nan_mask);
    // pack the low words of the 8 lanes back to 8 x u16 (packus after
    // clamping is safe: values are already <= 0xffff)
    __m256i packed = _mm256_packus_epi32(out32, out32);
    __m128i lo = _mm256_castsi256_si128(packed);
    __m128i hi = _mm256_extracti128_si256(packed, 1);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm_unpacklo_epi64(lo, hi));
  }
  for (; i < count; ++i) {
    dst[i] = FloatToBf16(Bf16ToFloat(dst[i]) + Bf16ToFloat(src[i]));
  }
}

bool HaveF16C() {
  // gcc 10's __builtin_cpu_supports has no "f16c"; read CPUID leaf 1 ECX
  // bit 29 directly.
  static const bool ok = [] {
    unsigned eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
    return (ecx & (1u << 29)) != 0 && __builtin_cpu_supports("avx2") != 0;
  }();
  return ok;
}

bool HaveAvx2() {
  static const bool ok = __builtin_cpu_supports("avx2");
  return ok;
}
#endif  // __x86_64__

// Half-precision buffers reduce through float in ONE pass: convert, combine,
// convert back per element (vectorized 8-wide for the SUM hot path), instead
// of a per-element op dispatch.
template <typename Op>
void ReduceHalfLoop(uint16_t* __restrict__ dst, const uint16_t* __restrict__ src,
                    int64_t count, Op op) {
  for (int64_t i = 0; i < count; ++i) {
    dst[i] = FloatToHalf(op(HalfToFloat(dst[i]), HalfToFloat(src[i])));
  }
}

template <typename Op>
void ReduceBf16Loop(uint16_t* __restrict__ dst, const uint16_t* __restrict__ src,
                    int64_t count, Op op) {
  for (int64_t i = 0; i < count; ++i) {
    dst[i] = FloatToBf16(op(Bf16ToFloat(dst[i]), Bf16ToFloat(src[i])));
  }
}

void ReduceHalf(uint16_t* dst, const uint16_t* src, int64_t count,
                ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:
    case ReduceOp::ADASUM:
#if defined(__x86_64__)
      if (HaveF16C()) {
        HalfSumF16C(dst, src, count);
        return;
      }
#endif
      ReduceHalfLoop(dst, src, count, SumOp{});
      break;
    case ReduceOp::MIN:
      ReduceHalfLoop(dst, src, count, MinOp{});
      break;
    case ReduceOp::MAX:
      ReduceHalfLoop(dst, src, count, MaxOp{});
      break;
    case ReduceOp::PRODUCT:
      ReduceHalfLoop(dst, src, count, ProdOp{});
      break;
  }
}

void ReduceBf16(uint16_t* dst, const uint16_t* src, int64_t count,
                ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:
    case ReduceOp::ADASUM:
#if defined(__x86_64__)
      if (HaveAvx2()) {
        Bf16SumAvx2(dst, src, count);
        return;
      }
#endif
      ReduceBf16Loop(dst, src, count, SumOp{});
      break;
    case ReduceOp::MIN:
      ReduceBf16Loop(dst, src, count, MinOp{});
      break;
    case ReduceOp::MAX:
      ReduceBf16Loop(dst, src, count, MaxOp{});
      break;
    case ReduceOp::PRODUCT:
      ReduceBf16Loop(dst, src, count, ProdOp{});
      break;
  }
}

}  // namespace

float HalfToFloatPublic(uint16_t h) { return HalfToFloat(h); }
uint16_t FloatToHalfPublic(float f) { return FloatToHalf(f); }
float Bf16ToFloatPublic(uint16_t h) { return Bf16ToFloat(h); }
uint16_t FloatToBf16Public(float f) { return FloatToBf16(f); }

void ReduceBuffer(void* dst, const void* src, int64_t count, DataType dtype,
                  ReduceOp op) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, op);
      break;
    case DataType::FLOAT64:
      ReduceTyped(static_cast<double*>(dst), static_cast<const double*>(src),
                  count, op);
      break;
    case DataType::INT32:
      ReduceTyped(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
                  count, op);
      break;
    case DataType::INT64:
      ReduceTyped(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
                  count, op);
      break;
    case DataType::UINT8:
      ReduceTyped(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                  count, op);
      break;
    case DataType::INT8:
      ReduceTyped(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                  count, op);
      break;
    case DataType::BOOL: {
      uint8_t* d = static_cast<uint8_t*>(dst);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      // bool: SUM/MAX == OR, MIN/PRODUCT == AND
      if (op == ReduceOp::MIN || op == ReduceOp::PRODUCT) {
        for (int64_t i = 0; i < count; ++i) d[i] = d[i] && s[i];
      } else {
        for (int64_t i = 0; i < count; ++i) d[i] = d[i] || s[i];
      }
      break;
    }
    case DataType::FLOAT16:
      ReduceHalf(static_cast<uint16_t*>(dst),
                 static_cast<const uint16_t*>(src), count, op);
      break;
    case DataType::BFLOAT16:
      ReduceBf16(static_cast<uint16_t*>(dst),
                 static_cast<const uint16_t*>(src), count, op);
      break;
  }
}

DataPlane::DataPlane(int rank, int size)
    : rank_(rank), size_(size), fds_(size, -1), transports_(size) {
  world_group_.resize(size);
  for (int r = 0; r < size; ++r) world_group_[r] = r;
  local_group_ = {rank};
  leaders_ = {0};
  own_metrics_.reset(new Metrics());
  set_metrics(own_metrics_.get());
}

void DataPlane::set_metrics(Metrics* m) {
  metrics_ = m;
  raw_bytes_total_ = metrics_->GetCounter(
      "hvdtpu_allreduce_raw_bytes_total",
      "Allreduce payload bytes this rank would have sent uncompressed");
  wire_bytes_total_ = metrics_->GetCounter(
      "hvdtpu_allreduce_wire_bytes_total",
      "Allreduce payload bytes this rank actually sent on the wire");
  zc_sends_total_ = metrics_->GetCounter(
      "hvdtpu_zerocopy_sends_total",
      "Large TCP sends completed through the zero-copy lane "
      "(MSG_ZEROCOPY/io_uring), completions drained");
  zc_fallbacks_total_ = metrics_->GetCounter(
      "hvdtpu_zerocopy_fallbacks_total",
      "Large TCP sends that wanted the zero-copy lane but took the copy "
      "path (failed probe, kernel-copied auto-disable, runtime decline)");
  zc_sends_published_ = 0;
  zc_fallbacks_published_ = 0;
}

void DataPlane::PublishZeroCopyCounters() {
  if (tcp_lanes_.empty()) return;
  int64_t sends = 0, fallbacks = 0;
  for (TcpTransport* t : tcp_lanes_) {
    sends += t->zerocopy_sends();
    fallbacks += t->zerocopy_fallbacks();
  }
  if (sends > zc_sends_published_) {
    zc_sends_total_->Add(sends - zc_sends_published_);
    zc_sends_published_ = sends;
  }
  if (fallbacks > zc_fallbacks_published_) {
    zc_fallbacks_total_->Add(fallbacks - zc_fallbacks_published_);
    zc_fallbacks_published_ = fallbacks;
  }
}

DataPlane::~DataPlane() { Shutdown(); }

Status DataPlane::Listen() {
  listen_fd_ = TcpListen(0, size_ + 4, &port_);
  if (listen_fd_ < 0) {
    return Status::Error(StatusCode::ABORTED, "data plane: listen failed");
  }
  return Status::OK();
}

Status DataPlane::Connect(const std::vector<PeerAddr>& peers) {
  // Deterministic, deadlock-free establishment: connect to lower ranks (they
  // are already listening), accept from higher ranks. Rank is identified by a
  // 4-byte hello.
  for (int peer = 0; peer < rank_; ++peer) {
    int fd = TcpConnectRetry(peers[peer].host, peers[peer].port,
                             static_cast<int>(formup_timeout_ms_));
    if (fd < 0) {
      return Status::Error(StatusCode::ABORTED,
                           "data plane: connect to rank " +
                               std::to_string(peer) + " failed");
    }
    int32_t me = rank_;
    if (SendAll(fd, &me, sizeof(me), &io_ctl_) != 0) {
      CloseFd(fd);
      return Status::Error(StatusCode::ABORTED, "data plane: hello failed");
    }
    fds_[peer] = fd;
  }
  for (int expected = 0; expected < size_ - rank_ - 1; ++expected) {
    // Deadline-bounded: a higher rank that died between rendezvous and its
    // data-plane connect must not wedge form-up forever.
    int fd = TcpAcceptTimeout(listen_fd_,
                              static_cast<int>(formup_timeout_ms_));
    if (fd < 0) {
      return Status::Error(StatusCode::ABORTED,
                           "data plane: accept failed (peer missing within "
                           "the form-up timeout?)");
    }
    // Interruptible: a peer whose route blackholes between its connect and
    // its 4-byte hello must trip the no-progress deadline, not wedge
    // form-up forever (HVDTPU_FORMUP_TIMEOUT_SECONDS bounds the accept
    // above; the IoControl deadline bounds the read here).
    int32_t who = -1;
    if (RecvAll(fd, &who, sizeof(who), &io_ctl_) != 0 ||
        who <= rank_ || who >= size_) {
      CloseFd(fd);
      return Status::Error(StatusCode::ABORTED, "data plane: bad hello");
    }
    fds_[who] = fd;
  }

  // Size the inline (send-then-recv, no sender thread) SendRecv fast path
  // from the ACTUAL kernel buffer sizes: a payload at most a quarter of the
  // smallest send/receive buffer on the mesh can never wedge even when both
  // peers send first. Hosts tuned down to the 4 KB tcp_wmem minimum simply
  // get a (correct) tiny threshold instead of a deadlock.
  int64_t lim = 32 * 1024;
  for (int fd : fds_) {
    if (fd < 0) continue;
    int val = 0;
    socklen_t len = sizeof(val);
    if (getsockopt(fd, SOL_SOCKET, SO_SNDBUF, &val, &len) == 0) {
      lim = std::min(lim, static_cast<int64_t>(val) / 4);
    }
    len = sizeof(val);
    if (getsockopt(fd, SOL_SOCKET, SO_RCVBUF, &val, &len) == 0) {
      lim = std::min(lim, static_cast<int64_t>(val) / 4);
    }
  }
  inline_max_bytes_ = std::max<int64_t>(lim, 0);

  // Host topology from the peer table: ranks advertising the same host
  // string form a local group; the lowest rank per host is its leader.
  // (Two names for one machine — "localhost" vs "127.0.0.1" — read as two
  // hosts; the launcher advertises one canonical name per host.)
  local_group_.clear();
  leaders_.clear();
  {
    std::vector<std::string> seen;
    for (int r = 0; r < size_; ++r) {
      if (peers[r].host == peers[rank_].host) local_group_.push_back(r);
      if (std::find(seen.begin(), seen.end(), peers[r].host) == seen.end()) {
        seen.push_back(peers[r].host);
        leaders_.push_back(r);
      }
    }
  }
  return SetupTransports(peers);
}

Status DataPlane::SetupTransports(const std::vector<PeerAddr>& peers) {
  tcp_lanes_.clear();
  auto make_tcp = [&](int peer) {
    auto* t = new TcpTransport(fds_[peer], inline_max_bytes_, &io_ctl_,
                               tcp_zerocopy_);
    tcp_lanes_.push_back(t);
    transports_[peer].reset(t);
  };
  for (int peer = 0; peer < size_; ++peer) {
    if (peer == rank_) continue;
    if (peers[peer].host != peers[rank_].host) {
      make_tcp(peer);
      continue;
    }
    // Same host: negotiate a shared-memory lane over the pair's socket so
    // both sides agree on the outcome — a one-sided fallback (one rank on
    // shm, the other on TCP) would wedge the pair. The handshake runs even
    // with shm disabled locally: the peer may have it on, and its status
    // byte must be consumed either way. Segment names key on the pair's
    // data-plane ports (unique per process while the job lives) + uid.
    const bool creator = rank_ < peer;
    const std::string name =
        "/hvdtpu_" + std::to_string(getuid()) + "_" +
        std::to_string(peers[std::min(rank_, peer)].port) + "_" +
        std::to_string(peers[std::max(rank_, peer)].port);
    std::unique_ptr<ShmTransport> shm;
    uint8_t ok = 0, peer_ok = 0;
    if (creator) {
      if (shm_enabled_) {
        shm = ShmTransport::Create(
            name, shm_ring_bytes_ > 0 ? static_cast<size_t>(shm_ring_bytes_)
                                      : 0);
      }
      ok = shm != nullptr ? 1 : 0;
      if (SendAll(fds_[peer], &ok, 1, &io_ctl_) != 0 ||
          RecvAll(fds_[peer], &peer_ok, 1, &io_ctl_) != 0) {
        return Status::Error(StatusCode::ABORTED,
                             "data plane: shm handshake with rank " +
                                 std::to_string(peer) + " failed");
      }
    } else {
      if (RecvAll(fds_[peer], &peer_ok, 1, &io_ctl_) != 0) {
        return Status::Error(StatusCode::ABORTED,
                             "data plane: shm handshake with rank " +
                                 std::to_string(peer) + " failed");
      }
      if (peer_ok != 0 && shm_enabled_) {
        shm = ShmTransport::Open(name, /*timeout_ms=*/10000);
      }
      ok = shm != nullptr ? 1 : 0;
      if (SendAll(fds_[peer], &ok, 1, &io_ctl_) != 0) {
        return Status::Error(StatusCode::ABORTED,
                             "data plane: shm handshake with rank " +
                                 std::to_string(peer) + " failed");
      }
    }
    if (ok != 0 && peer_ok != 0) {
      // The opener mmap'ed before acking, so the name can leave the shm
      // namespace now: an abnormal death past this point leaks nothing.
      if (creator) shm->Unlink();
      // A SIGKILLed peer can't flip the shared abort flag; the lane polls
      // the pair's (otherwise idle) socket for EOF while waiting instead.
      shm->set_liveness_fd(fds_[peer]);
      shm->set_control(&io_ctl_);
      shm->set_doorbell_batch(doorbell_batch_);
      // NUMA placement (HVDTPU_SHM_NUMA): each side pins its inbound ring
      // to its own node — probed no-op on single-node hosts.
      shm->ApplyNumaPolicy(shm_numa_);
      transports_[peer] = std::move(shm);
    } else {
      shm.reset();  // creator side aborts + unlinks in the destructor
      if (shm_enabled_) {
        fprintf(stderr,
                "[hvdtpu %d] WARNING: shm transport to same-host rank %d "
                "unavailable; falling back to TCP\n",
                rank_, peer);
      }
      make_tcp(peer);
    }
  }
  return Status::OK();
}

int DataPlane::shm_lane_count() const {
  int shm = 0;
  for (const auto& t : transports_) {
    if (t != nullptr && std::strcmp(t->kind(), "shm") == 0) ++shm;
  }
  return shm;
}

void DataPlane::ShmOccupancy(
    std::vector<std::pair<int, int64_t>>* out) const {
  out->clear();
  for (size_t peer = 0; peer < transports_.size(); ++peer) {
    const auto& t = transports_[peer];
    if (t != nullptr && std::strcmp(t->kind(), "shm") == 0) {
      out->emplace_back(static_cast<int>(peer), t->OccupancyBytes());
    }
  }
}

bool DataPlane::zerocopy_active() const {
  for (TcpTransport* t : tcp_lanes_) {
    if (t->zerocopy_enabled()) return true;
  }
  return false;
}

const std::string& DataPlane::transport_label() {
  // Rebuilt per call (a handful of times per op): the tcp-zc tag is live —
  // an AUTO lane that found the kernel copying anyway has downgraded
  // itself, and the per-op histogram/timeline labels must say so.
  int shm = 0, tcp = 0;
  bool zc = false;
  for (const auto& t : transports_) {
    if (t == nullptr) continue;
    if (std::strcmp(t->kind(), "shm") == 0) {
      ++shm;
    } else {
      ++tcp;
      if (std::strcmp(t->kind(), "tcp-zc") == 0) zc = true;
    }
  }
  const char* tcp_tag = zc ? "tcp-zc" : "tcp";
  if (shm > 0 && tcp > 0) {
    transport_label_ = std::string("shm+") + tcp_tag;
  } else if (shm > 0) {
    transport_label_ = "shm";
  } else if (tcp > 0) {
    transport_label_ = tcp_tag;
  } else {
    transport_label_ = "local";
  }
  return transport_label_;
}

void DataPlane::Shutdown() {
  // Transports first: the shm lanes flip their shared abort flag and wake
  // any same-host peer still blocked in a ring op before the name goes.
  tcp_lanes_.clear();  // raw views into transports_: drop before the owners
  for (auto& t : transports_) t.reset();
  for (int& fd : fds_) {
    CloseFd(fd);
    fd = -1;
  }
  CloseFd(listen_fd_);
  listen_fd_ = -1;
}

void DataPlane::Abort() {
  if (flight_ != nullptr && !io_ctl_.is_aborted()) {
    const int64_t now = Timeline::SteadyAbsUs();
    flight_->Record(FlightEvent::ABORT, -1, 0, failed_peer_, -1, now, now, 0,
                    0);
  }
  io_ctl_.aborted.store(1, std::memory_order_release);
  for (auto& t : transports_) {
    if (t != nullptr) t->Abort();  // shm: flag + futex wake; tcp: no-op
  }
  // Half-close (not close: fds stay owned until Shutdown) so a peer blocked
  // mid-transfer sees EOF at once and cascades its own abort.
  for (int fd : fds_) {
    if (fd >= 0) shutdown(fd, SHUT_RDWR);
  }
}

Status DataPlane::FailLane(int peer, const char* what) {
  if (failed_peer_ < 0) failed_peer_ = peer;
  if (flight_ != nullptr) {
    // The forensic money shot: which lane died, pinned on which peer. The
    // post-mortem verdict votes across every surviving rank's FAIL_DETECT
    // records to name the dead rank.
    const int64_t now = Timeline::SteadyAbsUs();
    flight_->Record(FlightEvent::FAIL_DETECT, -1, 0, peer, -1, now, now, 0,
                    0);
  }
  io_ctl_.MarkPeerFailed();
  Abort();
  return Status::Error(StatusCode::ABORTED,
                       "data plane: " + std::string(what) + " with rank " +
                           std::to_string(peer) +
                           " failed (peer death or liveness deadline)");
}

void DataPlane::BeginOpTrace() {
  trace_hop_seq_ = 0;
  trace_op_ = tracer_ != nullptr && tracer_->Initialized() &&
              trace_sampler_.SampleOp();
  // The flight ring and the perf-attribution accumulators want every hop;
  // the sampled JSON tracer only its share.
  rec_hops_ = trace_op_ || flight_ != nullptr || perf_on_;
  ResetOpPhaseAccum();
}

void DataPlane::ResetOpPhaseAccum() {
  op_wait_us_ = 0;
  op_wire_us_ = 0;
  op_reduce_us_ = 0;
  op_codec_us_ = 0;
  op_slow_peer_ = -1;
  op_slow_peer_wait_us_ = 0;
}

namespace {

// Map a TraceHop span name onto its flight-record tag. The strings are the
// handful of literals the data plane emits; first-character dispatch keeps
// this branchy-but-trivial on the hop path.
FlightEvent FlightHopEvent(const char* name) {
  switch (name[0]) {
    case 'S':
      return name[4] == 'R' ? FlightEvent::SENDRECV : FlightEvent::SEND;
    case 'R':
      return name[2] == 'C' ? FlightEvent::RECV : FlightEvent::REDUCE;
    case 'Q':
      return FlightEvent::QUANTIZE;
    case 'D':
      return FlightEvent::DEQUANTIZE;
    default:
      return FlightEvent::NONE;
  }
}

}  // namespace

void DataPlane::TraceHop(const char* name, int send_peer, int recv_peer,
                         int64_t bytes, int64_t t0_us, int64_t wait0_us) {
  if (!rec_hops_) return;
  const int64_t t1_us = Timeline::SteadyAbsUs();
  const int64_t wait_us = io_ctl_.WaitUs() - wait0_us;
  const int lane_peer = recv_peer >= 0 ? recv_peer : send_peer;
  const char* lane =
      lane_peer >= 0 && lane_peer < size_ && transports_[lane_peer] != nullptr
          ? transports_[lane_peer]->kind()
          : "local";
  const FlightEvent fev = FlightHopEvent(name);
  // Perf-attribution phase buckets (perfstats.h): every hop of every op —
  // plain integer adds, no strings, no branches beyond this switch.
  switch (fev) {
    case FlightEvent::SEND:
    case FlightEvent::RECV:
    case FlightEvent::SENDRECV: {
      op_wait_us_ += wait_us;
      const int64_t wire = t1_us - t0_us - wait_us;
      op_wire_us_ += wire > 0 ? wire : 0;
      if (lane_peer >= 0 && wait_us > op_slow_peer_wait_us_) {
        op_slow_peer_wait_us_ = wait_us;
        op_slow_peer_ = lane_peer;
      }
      break;
    }
    case FlightEvent::REDUCE:
      op_reduce_us_ += t1_us - t0_us;
      break;
    case FlightEvent::QUANTIZE:
    case FlightEvent::DEQUANTIZE:
      op_codec_us_ += t1_us - t0_us;
      break;
    default:
      break;
  }
  if (flight_ != nullptr) {
    flight_->Record(fev, /*name_id=*/-1, bytes, send_peer,
                    recv_peer, t0_us, t1_us, wait_us, FlightLaneCode(lane));
  }
  if (!trace_op_) return;
  std::string args = "{\"send_peer\": " + std::to_string(send_peer) +
                     ", \"recv_peer\": " + std::to_string(recv_peer) +
                     ", \"bytes\": " + std::to_string(bytes) +
                     ", \"lane\": \"" + lane + "\"" +
                     ", \"algo\": \"" + last_algo_label_ + "\"" +
                     ", \"hier\": " + (hier_active() ? "1" : "0") +
                     ", \"compression\": \"" +
                     WireCompressionName(op_comp_) + "\"" +
                     ", \"seg\": " + std::to_string(trace_hop_seq_++) +
                     ", \"wait_us\": " + std::to_string(wait_us) + "}";
  tracer_->Span("hops", name, t0_us, t1_us, args);
}

void DataPlane::MaybeChaosOp() {
  if (chaos_.action == ChaosSpec::Action::NONE || chaos_.op_index <= 0) {
    return;
  }
  if (++chaos_ops_ == chaos_.op_index) FireChaos(/*peer_hint=*/-1);
}

void DataPlane::MaybeChaosHop(int send_peer, int recv_peer) {
  if (chaos_.action == ChaosSpec::Action::NONE || chaos_.hop_index <= 0) {
    return;
  }
  if (++chaos_hops_ == chaos_.hop_index) {
    FireChaos(recv_peer >= 0 ? recv_peer : send_peer);
  }
}

void DataPlane::FireChaos(int peer_hint) {
  const ChaosSpec::Action action = chaos_.action;
  chaos_.action = ChaosSpec::Action::NONE;  // one-shot
  switch (action) {
    case ChaosSpec::Action::KILL:
      fprintf(stderr, "[hvdtpu %d] CHAOS: SIGKILL (op %lld, hop %lld)\n",
              rank_, static_cast<long long>(chaos_ops_),
              static_cast<long long>(chaos_hops_));
      raise(SIGKILL);
      return;  // unreachable
    case ChaosSpec::Action::HANG:
      fprintf(stderr, "[hvdtpu %d] CHAOS: hanging the collective thread "
                      "(op %lld, hop %lld)\n",
              rank_, static_cast<long long>(chaos_ops_),
              static_cast<long long>(chaos_hops_));
      // Wedged on purpose, ignoring every abort signal: this simulates a
      // livelocked rank, which only the PEERS' deadlines can detect.
      for (;;) {
        std::this_thread::sleep_for(std::chrono::milliseconds(100));
      }
    case ChaosSpec::Action::DELAY:
      fprintf(stderr, "[hvdtpu %d] CHAOS: delaying %lld ms\n", rank_,
              static_cast<long long>(chaos_.delay_ms));
      std::this_thread::sleep_for(
          std::chrono::milliseconds(chaos_.delay_ms));
      return;
    case ChaosSpec::Action::CORRUPT:
      // Deferred: the byte flips AFTER this op's reduction completes (see
      // Allreduce) so the corruption lands in the post-allreduce output
      // the divergence probe fingerprints — a pre-reduce flip would just
      // change the (still bitwise-consistent) sum on every rank.
      fprintf(stderr,
              "[hvdtpu %d] CHAOS: corrupting this op's output (op %lld)\n",
              rank_, static_cast<long long>(chaos_ops_));
      corrupt_pending_ = true;
      return;
    case ChaosSpec::Action::DROP: {
      // An op trigger has no hop peer yet (peer_hint == -1): blackhole the
      // ring neighbor so `drop@op=N` injects a real partition instead of
      // consuming the one-shot as a silent no-op.
      int victim = chaos_.peer >= 0 ? chaos_.peer : peer_hint;
      if (victim < 0 || victim == rank_ || victim >= size_) {
        victim = (rank_ + 1) % size_;
      }
      blackholed_peer_ = victim;
      fprintf(stderr, "[hvdtpu %d] CHAOS: blackholing lane to rank %d\n",
              rank_, blackholed_peer_);
      return;
    }
    case ChaosSpec::Action::NONE:
      return;
  }
}

Status DataPlane::BlackholeWait(int peer) {
  // A dropped lane is SILENT: no bytes move and no EOF ever arrives, like a
  // switch eating the flow. The op parks here until the plane aborts (a
  // peer detected the partition) or our own read deadline declares the
  // lane dead.
  const double t0 = MonoSeconds();
  for (;;) {
    if (io_ctl_.is_aborted()) {
      return Status::Error(StatusCode::ABORTED,
                           "data plane: aborted during a blackholed "
                           "exchange with rank " + std::to_string(peer));
    }
    const double now = MonoSeconds();
    if (io_ctl_.read_deadline_secs > 0 &&
        now - t0 > io_ctl_.read_deadline_secs) {
      return FailLane(peer, "blackholed exchange");
    }
    std::this_thread::sleep_for(
        std::chrono::milliseconds(io_ctl_.detect_slice_ms));
  }
}

Status DataPlane::SendTo(int peer, const void* buf, int64_t bytes,
                         const char* what) {
  MaybeChaosHop(peer, -1);
  if (io_ctl_.is_aborted()) {
    return Status::Error(StatusCode::ABORTED,
                         "data plane: aborted after a peer failure");
  }
  if (blackholed_peer_ >= 0 && peer == blackholed_peer_) {
    return BlackholeWait(peer);
  }
  // Sampling-profiler phase tag (profiler.h): samples landing inside this
  // hop fold under WIRE — the same region the op_wire_us_ accumulator
  // measures (wait slices re-tag themselves WAIT inside the transports).
  ProfPhaseScope prof_phase(PerfPhase::WIRE);
  const int64_t t0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  const int64_t w0 = rec_hops_ ? io_ctl_.WaitUs() : 0;
  if (bytes > 0 &&
      transports_[peer]->Send(buf, static_cast<size_t>(bytes)) != 0) {
    return FailLane(peer, what);
  }
  TraceHop("SEND", peer, -1, bytes, t0, w0);
  return Status::OK();
}

Status DataPlane::RecvFrom(int peer, void* buf, int64_t bytes,
                           const char* what) {
  MaybeChaosHop(-1, peer);
  if (io_ctl_.is_aborted()) {
    return Status::Error(StatusCode::ABORTED,
                         "data plane: aborted after a peer failure");
  }
  if (blackholed_peer_ >= 0 && peer == blackholed_peer_) {
    return BlackholeWait(peer);
  }
  ProfPhaseScope prof_phase(PerfPhase::WIRE);
  const int64_t t0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  const int64_t w0 = rec_hops_ ? io_ctl_.WaitUs() : 0;
  if (bytes > 0 &&
      transports_[peer]->Recv(buf, static_cast<size_t>(bytes)) != 0) {
    return FailLane(peer, what);
  }
  TraceHop("RECV", -1, peer, bytes, t0, w0);
  return Status::OK();
}

Status DataPlane::Exchange(int send_peer, const void* send_buf,
                           int64_t send_bytes, int recv_peer, void* recv_buf,
                           int64_t recv_bytes, int64_t segment_bytes,
                           const SegmentFn& on_segment, size_t view_align) {
  MaybeChaosHop(send_peer, recv_peer);
  if (io_ctl_.is_aborted()) {
    return Status::Error(StatusCode::ABORTED,
                         "data plane: aborted after a peer failure");
  }
  if (blackholed_peer_ >= 0 && (send_peer == blackholed_peer_ ||
                                recv_peer == blackholed_peer_)) {
    return BlackholeWait(blackholed_peer_);
  }
  // WIRE for the whole exchange; the segment callbacks (reduction) re-tag
  // their slices REDUCE and the transports' wait slices re-tag WAIT, so a
  // profiler sample always names the innermost active phase.
  ProfPhaseScope prof_phase(PerfPhase::WIRE);
  const int64_t t0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  const int64_t w0 = rec_hops_ ? io_ctl_.WaitUs() : 0;
  const int64_t hop_bytes = send_bytes + recv_bytes;
  const size_t seg =
      segment_bytes > 0 ? static_cast<size_t>(segment_bytes) : 0;
  if (send_peer == recv_peer) {
    // Same peer: the transport's own full-duplex exchange (interleaved ring
    // pump for shm; inline/concurrent/segmented socket path for TCP).
    if (transports_[send_peer]->SendRecv(
            send_buf, static_cast<size_t>(send_bytes), recv_buf,
            static_cast<size_t>(recv_bytes), seg, view_align,
            on_segment) != 0) {
      return FailLane(send_peer, "exchange");
    }
    TraceHop("SENDRECV", send_peer, recv_peer, hop_bytes, t0, w0);
    return Status::OK();
  }
  Transport* ts = transports_[send_peer].get();
  Transport* tr = transports_[recv_peer].get();
  if (std::strcmp(ts->kind(), "shm") == 0 &&
      std::strcmp(tr->kind(), "shm") == 0) {
    // Both lanes shared memory (ring-neighbor exchange on one host): one
    // thread pumps both rings — no sender thread, in-place receive views.
    auto* stx = static_cast<ShmTransport*>(ts);
    auto* srx = static_cast<ShmTransport*>(tr);
    if (ShmTransport::DuplexPump(stx, send_buf,
                                 static_cast<size_t>(send_bytes), srx,
                                 recv_buf, static_cast<size_t>(recv_bytes),
                                 view_align, on_segment) != 0) {
      // Blame the lane whose liveness probe / deadline actually tripped,
      // not reflexively the receive side: dead_ranks_ and the PR-6
      // re-rendezvous trigger act on this attribution.
      const int suspect = stx->peer_died() && !srx->peer_died()
                              ? send_peer
                              : recv_peer;
      return FailLane(suspect, "exchange");
    }
    TraceHop("SENDRECV", send_peer, recv_peer, hop_bytes, t0, w0);
    return Status::OK();
  }
  auto recv_side = [&]() -> int {
    if (recv_bytes <= 0) return 0;
    if (on_segment) {
      return tr->RecvSegmented(recv_buf, static_cast<size_t>(recv_bytes), seg,
                               view_align, on_segment);
    }
    return tr->Recv(recv_buf, static_cast<size_t>(recv_bytes));
  };
  if (send_bytes <= 0 ||
      ts->InlineSendSafe(static_cast<size_t>(send_bytes))) {
    // The send completes without peer progress (fits the lane's buffering):
    // inline send-then-recv skips the per-call sender thread.
    if (send_bytes > 0 &&
        ts->Send(send_buf, static_cast<size_t>(send_bytes)) != 0) {
      return FailLane(send_peer, "send");
    }
    if (recv_side() != 0) return FailLane(recv_peer, "receive");
    TraceHop("SENDRECV", send_peer, recv_peer, hop_bytes, t0, w0);
    return Status::OK();
  }
  int send_rc = 0;
  std::thread sender(
      [&] { send_rc = ts->Send(send_buf, static_cast<size_t>(send_bytes)); });
  int recv_rc = recv_side();
  sender.join();
  if (send_rc != 0) return FailLane(send_peer, "send");
  if (recv_rc != 0) return FailLane(recv_peer, "receive");
  TraceHop("SENDRECV", send_peer, recv_peer, hop_bytes, t0, w0);
  return Status::OK();
}

namespace {

// Chunk boundaries for a ring over `n` members (chunk c covers
// [starts[c], starts[c+1])).
std::vector<int64_t> ChunkStarts(int64_t count, int n) {
  std::vector<int64_t> starts(n + 1, 0);
  int64_t base = count / n, rem = count % n;
  for (int c = 0; c < n; ++c) {
    starts[c + 1] = starts[c] + base + (c < rem ? 1 : 0);
  }
  return starts;
}

int GroupIndex(const std::vector<int>& group, int rank) {
  for (size_t i = 0; i < group.size(); ++i) {
    if (group[i] == rank) return static_cast<int>(i);
  }
  return -1;
}

}  // namespace

Status DataPlane::Allreduce(void* data, int64_t count, DataType dtype,
                            ReduceOp op) {
  op_raw_bytes_ = 0;
  op_wire_bytes_ = 0;
  last_algo_label_ = "none";
  trace_op_ = false;  // never inherit the previous op's sampling decision
  if (size_ == 1 || count == 0) {
    // No hops will run, but ObserveOp still reads the phase accumulators:
    // a skipped BeginOpTrace must not leak the PREVIOUS op's buckets into
    // this op's perf baseline.
    ResetOpPhaseAccum();
    return Status::OK();
  }
  BeginOpTrace();
  MaybeChaosOp();
  Status st;
  if (hier_active()) {
    st = HierarchicalAllreduce(data, count, dtype, op);
    // Overwrites the leader-phase AllreduceGroup label: the op as a whole
    // took the two-level path.
    last_algo_label_ = "hierarchical";
  } else {
    st = AllreduceGroup(data, count, dtype, op, world_group_);
  }
  raw_bytes_total_->Add(op_raw_bytes_);
  wire_bytes_total_->Add(op_wire_bytes_);
  PublishZeroCopyCounters();
  if (corrupt_pending_ && st.ok()) {
    // Seeded silent data corruption (HVDTPU_CHAOS corrupt@op=N): one byte
    // of THIS rank's reduced output flips, exactly the bitwise divergence
    // the gradcheck fingerprint probe exists to catch (docs/numerics.md).
    corrupt_pending_ = false;
    static_cast<uint8_t*>(data)[0] ^= 0x01;
  }
  return st;
}

Status DataPlane::AllreduceGroup(void* data, int64_t count, DataType dtype,
                                 ReduceOp op, const std::vector<int>& group) {
  if (group.size() <= 1 || count == 0) return Status::OK();
  AllreduceAlgo algo = algo_;
  if (algo == AllreduceAlgo::AUTO) {
    const int64_t bytes = count * static_cast<int64_t>(DataTypeSize(dtype));
    if (bytes <= crossover_bytes_) {
      algo = AllreduceAlgo::RECURSIVE_DOUBLING;
    } else if (sa_auto_ && sa_min_group_ > 0 &&
               static_cast<int>(group.size()) >= sa_min_group_) {
      // Large message, large group: scatter-allgather's 2 rounds of depth
      // beat the ring's 2(gs-1) serialized hops. The static gate is the
      // HVDTPU_ALLREDUCE_SA_GROUP floor; sa_auto_ is the autotuner's pick.
      // The decision depends only on world-agreed values (group size,
      // adopted knobs), so the schedule cannot split across ranks.
      algo = AllreduceAlgo::SCATTER_ALLGATHER;
    } else {
      algo = AllreduceAlgo::RING;
    }
  }
  last_algo_label_ =
      algo == AllreduceAlgo::RECURSIVE_DOUBLING  ? "recursive_doubling"
      : algo == AllreduceAlgo::TREE              ? "tree"
      : algo == AllreduceAlgo::SCATTER_ALLGATHER ? "scatter_allgather"
      : algo == AllreduceAlgo::PARAMETER_SERVER  ? "parameter_server"
                                                 : "ring";
  switch (algo) {
    case AllreduceAlgo::RECURSIVE_DOUBLING:
      if (CompressionActive(dtype, op)) {
        return CompressedRecursiveDoubling(static_cast<float*>(data), count,
                                          group);
      }
      return RecursiveDoublingGroup(data, count, dtype, op, group);
    case AllreduceAlgo::TREE:
      // The tree path stays raw (all ranks resolve the same algo, so the
      // schedule cannot split): its reduce/broadcast edges are one-way and
      // would re-quantize log2(p) times with no bandwidth structure to
      // exploit — compression covers ring + recursive doubling.
      return TreeAllreduceGroup(data, count, dtype, op, group);
    case AllreduceAlgo::SCATTER_ALLGATHER:
      if (CompressionActive(dtype, op)) {
        const int gi = GroupIndex(group, rank_);
        std::vector<int64_t> starts =
            ChunkStarts(count, static_cast<int>(group.size()));
        return CompressedScatterAllgather(static_cast<float*>(data), starts,
                                          group, gi);
      }
      return ScatterAllgatherGroup(data, count, dtype, op, group);
    case AllreduceAlgo::PARAMETER_SERVER:
      if (CompressionActive(dtype, op)) {
        return CompressedParameterServer(static_cast<float*>(data), count,
                                         group, GroupIndex(group, rank_));
      }
      return ParameterServerGroup(data, count, dtype, op, group);
    case AllreduceAlgo::AUTO:
    case AllreduceAlgo::RING:
      break;
  }
  if (CompressionActive(dtype, op)) {
    const int gi = GroupIndex(group, rank_);
    std::vector<int64_t> starts =
        ChunkStarts(count, static_cast<int>(group.size()));
    float* buf = static_cast<float*>(data);
    Status st = CompressedRingReduceScatter(buf, starts, group, gi);
    if (!st.ok()) return st;
    return CompressedRingAllgather(buf, starts, group, gi);
  }
  return RingAllreduceGroup(data, count, dtype, op, group);
}

Status DataPlane::CompressedRingReduceScatter(
    float* buf, const std::vector<int64_t>& starts,
    const std::vector<int>& group, int gi) {
  const WireCompression c = op_comp_;
  const int gs = static_cast<int>(group.size());
  const int right = group[(gi + 1) % gs];
  const int left = group[(gi - 1 + gs) % gs];
  auto chunk_count = [&](int ch) { return starts[ch + 1] - starts[ch]; };
  int64_t max_chunk = 0;
  for (int ch = 0; ch < gs; ++ch) {
    max_chunk = std::max(max_chunk, chunk_count(ch));
  }
  std::vector<uint8_t> send_wire(static_cast<size_t>(WireBytes(c, max_chunk)));
  std::vector<uint8_t> recv_wire(send_wire.size());

  // Same schedule as the raw reduce-scatter: at step s send chunk (gi - s),
  // receive chunk (gi - s - 1) — but each hop ships the quantized form and
  // the receiver dequantizes + accumulates in fp32. Every chunk is
  // compressed exactly once per rank per op, so the error-feedback residual
  // region [starts[c], starts[c+1]) is consumed and rewritten once.
  for (int s = 0; s < gs - 1; ++s) {
    const int send_c = ((gi - s) % gs + gs) % gs;
    const int recv_c = ((gi - s - 1) % gs + gs) % gs;
    const int64_t sc = chunk_count(send_c);
    const int64_t rc = chunk_count(recv_c);
    const int64_t sw = WireBytes(c, sc);
    const int64_t rw = WireBytes(c, rc);
    const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireCompress(c, buf + starts[send_c], sc, send_wire.data(),
                   op_residual_ != nullptr ? op_residual_ + starts[send_c]
                                           : nullptr,
                   nullptr, op_quality_);
    }
    TraceHop("QUANTIZE", -1, -1, sc * 4, qt0, io_ctl_.WaitUs());
    AddOpBytes(sc * 4, sw);
    Status st = Exchange(right, send_wire.data(), sw, left, recv_wire.data(),
                         rw);
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompressAdd(c, recv_wire.data(), rc, buf + starts[recv_c]);
    }
    TraceHop("DEQUANTIZE", -1, -1, rc * 4, dt0, io_ctl_.WaitUs());
  }
  return Status::OK();
}

Status DataPlane::CompressedRingAllgather(float* buf,
                                          const std::vector<int64_t>& starts,
                                          const std::vector<int>& group,
                                          int gi) {
  const WireCompression c = op_comp_;
  const int gs = static_cast<int>(group.size());
  const int right = group[(gi + 1) % gs];
  const int left = group[(gi - 1 + gs) % gs];
  auto chunk_count = [&](int ch) { return starts[ch + 1] - starts[ch]; };
  int64_t max_chunk = 0;
  for (int ch = 0; ch < gs; ++ch) {
    max_chunk = std::max(max_chunk, chunk_count(ch));
  }
  std::vector<uint8_t> cur(static_cast<size_t>(WireBytes(c, max_chunk)));
  std::vector<uint8_t> next(cur.size());

  // The owner quantizes its fully reduced chunk once (residual applied,
  // own copy replaced by the dequantized values); every later hop forwards
  // those wire bytes verbatim, so the whole group decodes identical codes
  // and the final vectors agree bitwise.
  const int own_c = (gi + 1) % gs;
  const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  {
    ProfPhaseScope prof_codec(PerfPhase::CODEC);
    WireCompress(c, buf + starts[own_c], chunk_count(own_c), cur.data(),
                 op_residual_ != nullptr ? op_residual_ + starts[own_c]
                                         : nullptr,
                 buf + starts[own_c], op_quality_);
  }
  TraceHop("QUANTIZE", -1, -1, chunk_count(own_c) * 4, qt0,
           io_ctl_.WaitUs());
  for (int s = 0; s < gs - 1; ++s) {
    const int send_c = ((gi + 1 - s) % gs + gs) % gs;
    const int recv_c = ((gi - s) % gs + gs) % gs;
    const int64_t sw = WireBytes(c, chunk_count(send_c));
    const int64_t rw = WireBytes(c, chunk_count(recv_c));
    AddOpBytes(chunk_count(send_c) * 4, sw);
    Status st = Exchange(right, cur.data(), sw, left, next.data(), rw);
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompress(c, next.data(), chunk_count(recv_c),
                     buf + starts[recv_c]);
    }
    TraceHop("DEQUANTIZE", -1, -1, chunk_count(recv_c) * 4, dt0,
             io_ctl_.WaitUs());
    cur.swap(next);
  }
  return Status::OK();
}

Status DataPlane::CompressedRecursiveDoubling(float* data, int64_t count,
                                              const std::vector<int>& group) {
  const WireCompression c = op_comp_;
  const int gs = static_cast<int>(group.size());
  const int gi = GroupIndex(group, rank_);
  const int64_t raw_bytes = count * 4;
  const int64_t wb = WireBytes(c, count);
  std::vector<uint8_t> send_wire(static_cast<size_t>(wb));
  std::vector<uint8_t> recv_wire(static_cast<size_t>(wb));

  int p = 1;
  while (p * 2 <= gs) p *= 2;
  const int r = gs - p;

  // Fold: extra members ship their contribution quantized (uplink), the
  // partner dequantizes + accumulates.
  if (gi >= p) {
    WireCompress(c, data, count, send_wire.data(), op_residual_, nullptr,
                 op_quality_);
    AddOpBytes(raw_bytes, wb);
    Status st = SendTo(group[gi - p], send_wire.data(), wb, "rd fold send");
    if (!st.ok()) return st;
  } else if (gi < r) {
    Status st = RecvFrom(group[gi + p], recv_wire.data(), wb, "rd fold recv");
    if (!st.ok()) return st;
    WireDecompressAdd(c, recv_wire.data(), count, data);
  }

  if (gi < p) {
    for (int distance = 1; distance < p; distance *= 2) {
      const int peer = group[gi ^ distance];
      // Self-decode into `data`: both sides of the pair end up with
      // deQ(mine) + deQ(theirs) — bitwise identical by commutativity.
      const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
      {
        ProfPhaseScope prof_codec(PerfPhase::CODEC);
        WireCompress(c, data, count, send_wire.data(), op_residual_, data,
                     op_quality_);
      }
      TraceHop("QUANTIZE", -1, -1, raw_bytes, qt0, io_ctl_.WaitUs());
      AddOpBytes(raw_bytes, wb);
      Status st = Exchange(peer, send_wire.data(), wb, peer,
                           recv_wire.data(), wb);
      if (!st.ok()) return st;
      const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
      {
        ProfPhaseScope prof_codec(PerfPhase::CODEC);
        WireDecompressAdd(c, recv_wire.data(), count, data);
      }
      TraceHop("DEQUANTIZE", -1, -1, raw_bytes, dt0, io_ctl_.WaitUs());
    }
  }

  // Unfold: the final vector travels RAW so folded ranks hold exactly the
  // main group's bytes (one uncompressed hop, non-power-of-two worlds only).
  if (gi < r) {
    AddOpBytes(raw_bytes, raw_bytes);
    Status st = SendTo(group[gi + p], data, raw_bytes, "rd unfold send");
    if (!st.ok()) return st;
  } else if (gi >= p) {
    Status st = RecvFrom(group[gi - p], data, raw_bytes, "rd unfold recv");
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::RingReduceScatterPhase(uint8_t* buf,
                                         const std::vector<int64_t>& starts,
                                         size_t elem, DataType dtype,
                                         ReduceOp op,
                                         const std::vector<int>& group,
                                         int gi) {
  const int gs = static_cast<int>(group.size());
  const int right = group[(gi + 1) % gs];
  const int left = group[(gi - 1 + gs) % gs];
  auto chunk_ptr = [&](int c) { return buf + starts[c] * elem; };
  auto chunk_count = [&](int c) { return starts[c + 1] - starts[c]; };
  int64_t max_chunk = 0;
  for (int c = 0; c < gs; ++c) max_chunk = std::max(max_chunk, chunk_count(c));
  // Receive scratch: the shm lane consumes segments in place (zero-copy
  // views), so a shm left-neighbor needs NO landing buffer at all — and
  // the TCP lane gets an uninitialized one (the old value-initialized
  // vector memset a full chunk per op for bytes about to be overwritten).
  const bool recv_lands =
      std::strcmp(transports_[left]->kind(), "shm") != 0;
  std::unique_ptr<uint8_t[]> recv_tmp(
      recv_lands ? new uint8_t[static_cast<size_t>(max_chunk) * elem]
                 : nullptr);

  // Element-aligned pipeline segment.
  int64_t seg = segment_bytes_ - segment_bytes_ % static_cast<int64_t>(elem);
  if (seg <= 0) seg = static_cast<int64_t>(elem);

  // Ring reduce-scatter. After step s, chunk (gi - s - 1) holds the partial
  // sum of s + 2 members; after gs-1 steps, chunk (gi + 1) holds the full
  // reduction on this member (standard ring schedule: send chunk (gi - s),
  // receive + reduce chunk (gi - s - 1)). Chunks of two or more segments
  // stream through the segmented exchange so the reduction of segment k
  // overlaps the transfer of segment k+1.
  for (int s = 0; s < gs - 1; ++s) {
    int send_c = ((gi - s) % gs + gs) % gs;
    int recv_c = ((gi - s - 1) % gs + gs) % gs;
    int64_t send_bytes = chunk_count(send_c) * static_cast<int64_t>(elem);
    int64_t recv_bytes = chunk_count(recv_c) * static_cast<int64_t>(elem);
    AddOpBytes(send_bytes, send_bytes);
    if (recv_bytes > 0) {
      // Segment views reduce straight from the transport's storage: the
      // TCP lane hands recv_tmp-backed views, the shm lane hands in-ring
      // views and skips the staging copy entirely (transport.h SegmentFn).
      // Every non-empty chunk takes this path — chunk sizes are whole
      // element multiples, and sub-segment chunks simply arrive as one
      // view.
      uint8_t* dst = chunk_ptr(recv_c);
      // Tracing: the per-segment reductions interleave with the transfer;
      // the REDUCE child span covers first-to-last with the actual busy
      // time in its args (docs/tracing.md).
      int64_t reduce_first_us = 0, reduce_last_us = 0, reduce_busy_us = 0;
      Status st = Exchange(
          right, chunk_ptr(send_c), send_bytes, left, recv_tmp.get(),
          recv_bytes, seg,
          [&](const uint8_t* data, size_t off, size_t len) {
            ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
            const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
            ReduceBuffer(dst + off, data, static_cast<int64_t>(len / elem),
                         dtype, op);
            if (rec_hops_) {
              const int64_t rt1 = Timeline::SteadyAbsUs();
              if (reduce_first_us == 0) reduce_first_us = rt0;
              reduce_last_us = rt1;
              reduce_busy_us += rt1 - rt0;
            }
          },
          elem);
      if (!st.ok()) return st;
      if (rec_hops_ && reduce_first_us != 0) {
        // Perf attribution: the segmented reduce's actual busy time (the
        // first-to-last span overlaps the wire and would double-count).
        op_reduce_us_ += reduce_busy_us;
        if (flight_ != nullptr) {
          // busy_us in arg: the span is first-to-last segment, the actual
          // reduction time is what the analyzer attributes.
          flight_->Record(FlightEvent::REDUCE, -1, recv_bytes, -1, -1,
                          reduce_first_us, reduce_last_us, reduce_busy_us,
                          0);
        }
        if (trace_op_) {
          tracer_->Span(
              "hops", "REDUCE", reduce_first_us, reduce_last_us,
              "{\"bytes\": " + std::to_string(recv_bytes) +
                  ", \"busy_us\": " + std::to_string(reduce_busy_us) +
                  ", \"seg\": " + std::to_string(trace_hop_seq_++) + "}");
        }
      }
    } else {
      // Empty chunk (count < group size): send-only hop.
      Status st = Exchange(right, chunk_ptr(send_c), send_bytes, left,
                           nullptr, 0);
      if (!st.ok()) return st;
    }
  }
  return Status::OK();
}

Status DataPlane::RingAllgatherPhase(uint8_t* buf,
                                     const std::vector<int64_t>& starts,
                                     size_t elem,
                                     const std::vector<int>& group, int gi) {
  const int gs = static_cast<int>(group.size());
  const int right = group[(gi + 1) % gs];
  const int left = group[(gi - 1 + gs) % gs];
  auto chunk_ptr = [&](int c) { return buf + starts[c] * elem; };
  auto chunk_count = [&](int c) { return starts[c + 1] - starts[c]; };
  // Ring allgather of the reduced chunks (already full-duplex; no
  // per-segment work to overlap).
  for (int s = 0; s < gs - 1; ++s) {
    int send_c = ((gi + 1 - s) % gs + gs) % gs;
    int recv_c = ((gi - s) % gs + gs) % gs;
    const int64_t send_bytes =
        chunk_count(send_c) * static_cast<int64_t>(elem);
    AddOpBytes(send_bytes, send_bytes);
    Status st = Exchange(right, chunk_ptr(send_c), send_bytes,
                         left, chunk_ptr(recv_c),
                         chunk_count(recv_c) * static_cast<int64_t>(elem));
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::RingAllreduceGroup(void* data, int64_t count, DataType dtype,
                                     ReduceOp op,
                                     const std::vector<int>& group) {
  const size_t elem = DataTypeSize(dtype);
  uint8_t* buf = static_cast<uint8_t*>(data);
  const int gi = GroupIndex(group, rank_);
  std::vector<int64_t> starts =
      ChunkStarts(count, static_cast<int>(group.size()));
  Status st = RingReduceScatterPhase(buf, starts, elem, dtype, op, group, gi);
  if (!st.ok()) return st;
  return RingAllgatherPhase(buf, starts, elem, group, gi);
}

Status DataPlane::RecursiveDoublingGroup(void* data, int64_t count,
                                         DataType dtype, ReduceOp op,
                                         const std::vector<int>& group) {
  const size_t elem = DataTypeSize(dtype);
  const int64_t bytes = count * static_cast<int64_t>(elem);
  const int gs = static_cast<int>(group.size());
  const int gi = GroupIndex(group, rank_);
  std::vector<uint8_t> other(static_cast<size_t>(bytes));

  // Largest power-of-two subgroup; the r extra members fold into their
  // partner first and receive the result last (same shape as Adasum).
  int p = 1;
  while (p * 2 <= gs) p *= 2;
  const int r = gs - p;

  if (gi >= p) {
    AddOpBytes(bytes, bytes);
    Status st = SendTo(group[gi - p], data, bytes, "rd fold send");
    if (!st.ok()) return st;
  } else if (gi < r) {
    Status st = RecvFrom(group[gi + p], other.data(), bytes, "rd fold recv");
    if (!st.ok()) return st;
    ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
    const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    ReduceBuffer(data, other.data(), count, dtype, op);
    TraceHop("REDUCE", -1, -1, bytes, rt0, io_ctl_.WaitUs());
  }

  if (gi < p) {
    for (int distance = 1; distance < p; distance *= 2) {
      int peer = group[gi ^ distance];
      AddOpBytes(bytes, bytes);
      Status st = Exchange(peer, data, bytes, peer, other.data(), bytes);
      if (!st.ok()) return st;
      ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
      const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
      ReduceBuffer(data, other.data(), count, dtype, op);
      TraceHop("REDUCE", -1, -1, bytes, rt0, io_ctl_.WaitUs());
    }
  }

  if (gi < r) {
    AddOpBytes(bytes, bytes);
    Status st = SendTo(group[gi + p], data, bytes, "rd unfold send");
    if (!st.ok()) return st;
  } else if (gi >= p) {
    Status st = RecvFrom(group[gi - p], data, bytes, "rd unfold recv");
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::TreeAllreduceGroup(void* data, int64_t count, DataType dtype,
                                     ReduceOp op,
                                     const std::vector<int>& group) {
  const size_t elem = DataTypeSize(dtype);
  const int64_t bytes = count * static_cast<int64_t>(elem);
  const int gs = static_cast<int>(group.size());
  const int gi = GroupIndex(group, rank_);
  std::vector<uint8_t> other(static_cast<size_t>(bytes));

  // Binomial reduce toward member 0: at distance d, members with bit d set
  // send up and leave; the rest absorb a child (if present) and continue.
  for (int d = 1; d < gs; d <<= 1) {
    if (gi & d) {
      AddOpBytes(bytes, bytes);
      Status st = SendTo(group[gi - d], data, bytes, "tree reduce send");
      if (!st.ok()) return st;
      break;
    }
    if (gi + d < gs) {
      Status st =
          RecvFrom(group[gi + d], other.data(), bytes, "tree reduce recv");
      if (!st.ok()) return st;
      ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
      const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
      ReduceBuffer(data, other.data(), count, dtype, op);
      TraceHop("REDUCE", -1, -1, bytes, rt0, io_ctl_.WaitUs());
    }
  }

  // Binomial broadcast back down the same tree (parent first, then forward
  // to children in decreasing-distance order — each edge is one-directional,
  // so plain blocking sends cannot deadlock).
  int top = 1;
  while (top < gs) top <<= 1;
  int lsb = gi == 0 ? top : (gi & -gi);
  if (gi != 0) {
    Status st = RecvFrom(group[gi - lsb], data, bytes, "tree bcast recv");
    if (!st.ok()) return st;
  }
  for (int d = lsb >> 1; d >= 1; d >>= 1) {
    if (gi + d < gs) {
      AddOpBytes(bytes, bytes);
      Status st = SendTo(group[gi + d], data, bytes, "tree bcast send");
      if (!st.ok()) return st;
    }
  }
  return Status::OK();
}

Status DataPlane::ScatterAllgatherGroup(void* data, int64_t count,
                                        DataType dtype, ReduceOp op,
                                        const std::vector<int>& group) {
  const size_t elem = DataTypeSize(dtype);
  uint8_t* buf = static_cast<uint8_t*>(data);
  const int gs = static_cast<int>(group.size());
  const int gi = GroupIndex(group, rank_);
  std::vector<int64_t> starts = ChunkStarts(count, gs);
  auto chunk_ptr = [&](int c) { return buf + starts[c] * elem; };
  auto chunk_bytes = [&](int c) {
    return (starts[c + 1] - starts[c]) * static_cast<int64_t>(elem);
  };
  // Ring-identical chunk ownership: member j owns chunk (j+1) % gs.
  auto owned = [&](int j) { return (j + 1) % gs; };
  const int own_c = owned(gi);
  const int64_t mine = chunk_bytes(own_c);

  // Accumulator for the gs-1 incoming copies of my owned chunk, plus a
  // landing buffer for the segmented exchanges (the shm lanes consume
  // segments in place and leave it untouched; the TCP lanes stage there).
  std::vector<uint8_t> tmp(static_cast<size_t>(mine));
  std::vector<uint8_t> scratch(static_cast<size_t>(mine));
  int64_t seg = segment_bytes_ - segment_bytes_ % static_cast<int64_t>(elem);
  if (seg <= 0) seg = static_cast<int64_t>(elem);

  // Phase 1 — direct-exchange reduce-scatter: at step k, ship peer
  // (gi - k)'s owned slice straight out of MY buffer while receiving peer
  // (gi + k)'s copy of MY owned chunk. The copies arrive from members
  // own_c, own_c+1, ..., gi-1 in that order — exactly the ring
  // reduce-scatter's accumulation order — and my own contribution folds in
  // last, so the reduced chunk is bitwise the ring's (commutative
  // per-application IEEE ops; see data_plane.h).
  for (int k = 1; k < gs; ++k) {
    const int send_i = (gi - k + gs) % gs;
    const int recv_i = (gi + k) % gs;
    const int send_c = owned(send_i);
    const int64_t send_bytes = chunk_bytes(send_c);
    AddOpBytes(send_bytes, send_bytes);
    Status st;
    if (mine == 0) {
      st = Exchange(group[send_i], chunk_ptr(send_c), send_bytes,
                    group[recv_i], nullptr, 0);
    } else if (k == 1) {
      // First copy lands plain: the accumulator starts as x_{own_c}.
      st = Exchange(group[send_i], chunk_ptr(send_c), send_bytes,
                    group[recv_i], tmp.data(), mine);
    } else {
      // Later copies stream through the segmented exchange so the
      // reduction of segment s overlaps the transfer of segment s+1, like
      // the ring reduce-scatter.
      int64_t reduce_first_us = 0, reduce_last_us = 0, reduce_busy_us = 0;
      st = Exchange(
          group[send_i], chunk_ptr(send_c), send_bytes, group[recv_i],
          scratch.data(), mine, seg,
          [&](const uint8_t* d, size_t off, size_t len) {
            ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
            const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
            ReduceBuffer(tmp.data() + off, d,
                         static_cast<int64_t>(len / elem), dtype, op);
            if (rec_hops_) {
              const int64_t rt1 = Timeline::SteadyAbsUs();
              if (reduce_first_us == 0) reduce_first_us = rt0;
              reduce_last_us = rt1;
              reduce_busy_us += rt1 - rt0;
            }
          },
          elem);
      if (st.ok() && rec_hops_ && reduce_first_us != 0) {
        op_reduce_us_ += reduce_busy_us;
        if (flight_ != nullptr) {
          flight_->Record(FlightEvent::REDUCE, -1, mine, -1, -1,
                          reduce_first_us, reduce_last_us, reduce_busy_us,
                          0);
        }
        if (trace_op_) {
          tracer_->Span(
              "hops", "REDUCE", reduce_first_us, reduce_last_us,
              "{\"bytes\": " + std::to_string(mine) +
                  ", \"busy_us\": " + std::to_string(reduce_busy_us) +
                  ", \"seg\": " + std::to_string(trace_hop_seq_++) + "}");
        }
      }
    }
    if (!st.ok()) return st;
  }
  if (mine > 0) {
    // My contribution folds in last, where the ring's final reduce-scatter
    // step puts it: chunk = x_gi OP (x_c OP ... OP x_{gi-1}).
    ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
    const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    ReduceBuffer(chunk_ptr(own_c), tmp.data(), starts[own_c + 1] - starts[own_c],
                 dtype, op);
    TraceHop("REDUCE", -1, -1, mine, rt0, io_ctl_.WaitUs());
  }

  // Phase 2 — direct allgather: every peer gets my reduced chunk straight
  // from its owner (one hop of depth; no store-and-forward reshipping).
  for (int k = 1; k < gs; ++k) {
    const int to_i = (gi + k) % gs;
    const int from_i = (gi - k + gs) % gs;
    const int from_c = owned(from_i);
    AddOpBytes(mine, mine);
    Status st = Exchange(group[to_i], chunk_ptr(own_c), mine, group[from_i],
                         chunk_ptr(from_c), chunk_bytes(from_c));
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::CompressedScatterAllgather(
    float* buf, const std::vector<int64_t>& starts,
    const std::vector<int>& group, int gi) {
  const WireCompression c = op_comp_;
  const int gs = static_cast<int>(group.size());
  auto chunk_count = [&](int ch) { return starts[ch + 1] - starts[ch]; };
  auto owned = [&](int j) { return (j + 1) % gs; };
  const int own_c = owned(gi);
  const int64_t mc = chunk_count(own_c);
  int64_t max_chunk = 0;
  for (int ch = 0; ch < gs; ++ch) {
    max_chunk = std::max(max_chunk, chunk_count(ch));
  }
  std::vector<uint8_t> send_wire(static_cast<size_t>(WireBytes(c, max_chunk)));
  std::vector<uint8_t> recv_wire(send_wire.size());

  // Phase 1: quantize each peer's slice out of MY buffer (error feedback at
  // that region — together with phase 2's own-chunk quantize, every region
  // is compressed exactly once per rank per op) and dequantize-add the
  // incoming copies straight into my owned chunk, which starts as x_gi.
  for (int k = 1; k < gs; ++k) {
    const int send_i = (gi - k + gs) % gs;
    const int recv_i = (gi + k) % gs;
    const int send_c = owned(send_i);
    const int64_t sc = chunk_count(send_c);
    const int64_t sw = WireBytes(c, sc);
    const int64_t rw = WireBytes(c, mc);
    const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireCompress(c, buf + starts[send_c], sc, send_wire.data(),
                   op_residual_ != nullptr ? op_residual_ + starts[send_c]
                                           : nullptr,
                   nullptr, op_quality_);
    }
    TraceHop("QUANTIZE", -1, -1, sc * 4, qt0, io_ctl_.WaitUs());
    AddOpBytes(sc * 4, sw);
    Status st = Exchange(group[send_i], send_wire.data(), sw, group[recv_i],
                         recv_wire.data(), rw);
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompressAdd(c, recv_wire.data(), mc, buf + starts[own_c]);
    }
    TraceHop("DEQUANTIZE", -1, -1, mc * 4, dt0, io_ctl_.WaitUs());
  }

  // Phase 2: the owner quantizes its fully reduced chunk ONCE (residual
  // applied, own copy replaced by the dequantized values) and the direct
  // rotation ships those same wire bytes to every peer — the whole group
  // decodes identical codes, so the final vectors agree bitwise.
  const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  {
    ProfPhaseScope prof_codec(PerfPhase::CODEC);
    WireCompress(c, buf + starts[own_c], mc, send_wire.data(),
                 op_residual_ != nullptr ? op_residual_ + starts[own_c]
                                         : nullptr,
                 buf + starts[own_c], op_quality_);
  }
  TraceHop("QUANTIZE", -1, -1, mc * 4, qt0, io_ctl_.WaitUs());
  const int64_t ow = WireBytes(c, mc);
  for (int k = 1; k < gs; ++k) {
    const int to_i = (gi + k) % gs;
    const int from_i = (gi - k + gs) % gs;
    const int from_c = owned(from_i);
    const int64_t rc = chunk_count(from_c);
    const int64_t rw = WireBytes(c, rc);
    AddOpBytes(mc * 4, ow);
    Status st = Exchange(group[to_i], send_wire.data(), ow, group[from_i],
                         recv_wire.data(), rw);
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompress(c, recv_wire.data(), rc, buf + starts[from_c]);
    }
    TraceHop("DEQUANTIZE", -1, -1, rc * 4, dt0, io_ctl_.WaitUs());
  }
  return Status::OK();
}

Status DataPlane::ParameterServerGroup(void* data, int64_t count,
                                       DataType dtype, ReduceOp op,
                                       const std::vector<int>& group) {
  const size_t elem = DataTypeSize(dtype);
  const int64_t bytes = count * static_cast<int64_t>(elem);
  const int gs = static_cast<int>(group.size());
  const int gi = GroupIndex(group, rank_);

  if (gi != 0) {
    AddOpBytes(bytes, bytes);
    Status st = SendTo(group[0], data, bytes, "ps gather send");
    if (!st.ok()) return st;
    return RecvFrom(group[0], data, bytes, "ps bcast recv");
  }
  // Root: absorb every worker's vector in rank order (the same sequential
  // one-directional drain as the tree reduce — no cycle, no deadlock),
  // then broadcast the single reduced buffer. One reducer, one buffer:
  // cross-rank bitwise equality is trivial.
  std::vector<uint8_t> other(static_cast<size_t>(bytes));
  for (int j = 1; j < gs; ++j) {
    Status st = RecvFrom(group[j], other.data(), bytes, "ps gather recv");
    if (!st.ok()) return st;
    ProfPhaseScope prof_reduce(PerfPhase::REDUCE);
    const int64_t rt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    ReduceBuffer(data, other.data(), count, dtype, op);
    TraceHop("REDUCE", -1, -1, bytes, rt0, io_ctl_.WaitUs());
  }
  for (int j = 1; j < gs; ++j) {
    AddOpBytes(bytes, bytes);
    Status st = SendTo(group[j], data, bytes, "ps bcast send");
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::CompressedParameterServer(float* buf, int64_t count,
                                            const std::vector<int>& group,
                                            int gi) {
  const WireCompression c = op_comp_;
  const int gs = static_cast<int>(group.size());
  const int64_t raw_bytes = count * 4;
  const int64_t wb = WireBytes(c, count);
  std::vector<uint8_t> wire(static_cast<size_t>(wb));

  if (gi != 0) {
    // Quantized uplink with error feedback at the worker...
    const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireCompress(c, buf, count, wire.data(), op_residual_, nullptr,
                   op_quality_);
    }
    TraceHop("QUANTIZE", -1, -1, raw_bytes, qt0, io_ctl_.WaitUs());
    AddOpBytes(raw_bytes, wb);
    Status st = SendTo(group[0], wire.data(), wb, "ps gather send");
    if (!st.ok()) return st;
    // ...then decode the root's single quantized broadcast: every rank
    // sees the same codes (quantize-once-at-owner).
    st = RecvFrom(group[0], wire.data(), wb, "ps bcast recv");
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompress(c, wire.data(), count, buf);
    }
    TraceHop("DEQUANTIZE", -1, -1, raw_bytes, dt0, io_ctl_.WaitUs());
    return Status::OK();
  }
  std::vector<uint8_t> peer_wire(static_cast<size_t>(wb));
  for (int j = 1; j < gs; ++j) {
    Status st = RecvFrom(group[j], peer_wire.data(), wb, "ps gather recv");
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompressAdd(c, peer_wire.data(), count, buf);
    }
    TraceHop("DEQUANTIZE", -1, -1, raw_bytes, dt0, io_ctl_.WaitUs());
  }
  // The root quantizes the reduced vector ONCE (self-decoding its own
  // copy) and ships the identical wire bytes to every worker.
  const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  {
    ProfPhaseScope prof_codec(PerfPhase::CODEC);
    WireCompress(c, buf, count, wire.data(), op_residual_, buf, op_quality_);
  }
  TraceHop("QUANTIZE", -1, -1, raw_bytes, qt0, io_ctl_.WaitUs());
  for (int j = 1; j < gs; ++j) {
    AddOpBytes(raw_bytes, wb);
    Status st = SendTo(group[j], wire.data(), wb, "ps bcast send");
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::HierarchicalAllreduce(void* data, int64_t count,
                                        DataType dtype, ReduceOp op) {
  // Two-level allreduce (reference analog: Horovod's hierarchical NCCL+MPI
  // path, with the fork's SHM lanes carrying the intra-node stages):
  //   1. intra-host ring reduce-scatter over the (shm) local lanes — the
  //      reduction compute parallelizes across the host's ranks;
  //   2. reduced chunks gather to the host leader (lowest local rank);
  //   3. leaders run the flat ring/recursive-doubling over TCP;
  //   4. chunks scatter back from the leader;
  //   5. intra-host ring allgather completes every member's vector.
  // With a single host, stages 2-4 vanish and this is the all-shm ring.
  const std::vector<int>& local = local_group_;
  const int L = static_cast<int>(local.size());
  const int li = GroupIndex(local, rank_);
  const size_t elem = DataTypeSize(dtype);
  uint8_t* buf = static_cast<uint8_t*>(data);
  const bool cross = leaders_.size() > 1;

  std::vector<int64_t> starts = ChunkStarts(count, L);
  auto chunk_ptr = [&](int c) { return buf + starts[c] * elem; };
  auto chunk_bytes = [&](int c) {
    return (starts[c + 1] - starts[c]) * static_cast<int64_t>(elem);
  };
  // Chunk owned by local member j after the reduce-scatter phase.
  auto owned = [&](int j) { return (j + 1) % L; };

  if (L > 1) {
    Status st = RingReduceScatterPhase(buf, starts, elem, dtype, op, local, li);
    if (!st.ok()) return st;
  }
  if (cross) {
    if (L > 1) {
      if (li == 0) {
        for (int j = 1; j < L; ++j) {
          int c = owned(j);
          Status st = RecvFrom(local[j], chunk_ptr(c), chunk_bytes(c),
                               "hier leader gather");
          if (!st.ok()) return st;
        }
      } else {
        int c = owned(li);
        AddOpBytes(chunk_bytes(c), chunk_bytes(c));
        Status st = SendTo(local[0], chunk_ptr(c), chunk_bytes(c),
                           "hier leader gather");
        if (!st.ok()) return st;
      }
    }
    if (li == 0) {
      // The leader phase inherits the op's compression: the cross-host hop
      // is the slow link the reference fork quantizes (intra-host shm
      // stages stay dense).
      Status st = AllreduceGroup(data, count, dtype, op, leaders_);
      if (!st.ok()) return st;
    }
    if (L > 1) {
      if (li == 0) {
        for (int j = 1; j < L; ++j) {
          int c = owned(j);
          AddOpBytes(chunk_bytes(c), chunk_bytes(c));
          Status st = SendTo(local[j], chunk_ptr(c), chunk_bytes(c),
                             "hier leader scatter");
          if (!st.ok()) return st;
        }
      } else {
        int c = owned(li);
        Status st = RecvFrom(local[0], chunk_ptr(c), chunk_bytes(c),
                             "hier leader scatter");
        if (!st.ok()) return st;
      }
    }
  }
  if (L > 1) {
    Status st = RingAllgatherPhase(buf, starts, elem, local, li);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::Allgatherv(const void* in, int64_t in_bytes,
                             const std::vector<int64_t>& block_bytes,
                             ByteBuf* out) {
  op_raw_bytes_ = 0;
  op_wire_bytes_ = 0;
  last_algo_label_ = "none";
  trace_op_ = false;
  std::vector<int64_t> offsets(size_ + 1, 0);
  for (int r = 0; r < size_; ++r) offsets[r + 1] = offsets[r] + block_bytes[r];
  out->resize(static_cast<size_t>(offsets[size_]));
  memcpy(out->data() + offsets[rank_], in, static_cast<size_t>(in_bytes));
  if (size_ == 1 || offsets[size_] == 0) {
    // No hops; see Allreduce — ObserveOp still reads the accumulators.
    ResetOpPhaseAccum();
    return Status::OK();
  }
  BeginOpTrace();
  MaybeChaosOp();
  Status st;
  if (op_comp_ != WireCompression::NONE) {
    // Compression requires the ring: quantize-once owner codes only stay
    // identical world-wide when every hop forwards them verbatim. The core
    // arms the mode for fp32 payloads only (EffectiveCompression).
    last_algo_label_ = "ring";
    st = CompressedRingAllgatherv(offsets, block_bytes, out->data());
  } else if (offsets[size_] > crossover_bytes_) {
    // Bandwidth path: store-and-forward over neighbor lanes only — big
    // gathers ride the shm/zero-copy neighbor lanes instead of opening all
    // n-1 TCP streams at once.
    last_algo_label_ = "ring";
    st = RingAllgathervPhase(offsets, block_bytes, out->data());
  } else {
    // Latency path: direct pairwise rotation — step k sends my block to
    // rank (rank+k), receives the block of rank (rank-k); every block
    // travels exactly one hop.
    last_algo_label_ = "direct";
    st = Status::OK();
    for (int k = 1; k < size_; ++k) {
      int to = (rank_ + k) % size_;
      int from = (rank_ - k + size_) % size_;
      AddOpBytes(in_bytes, in_bytes);
      st = Exchange(to, in, in_bytes, from,
                    out->data() + offsets[from], block_bytes[from]);
      if (!st.ok()) break;
    }
  }
  raw_bytes_total_->Add(op_raw_bytes_);
  wire_bytes_total_->Add(op_wire_bytes_);
  PublishZeroCopyCounters();
  if (corrupt_pending_ && st.ok() && !out->empty()) {
    // Seeded SDC (HVDTPU_CHAOS corrupt@op=N): flip one byte of the gathered
    // output — the divergence probe fingerprints allgather results too.
    corrupt_pending_ = false;
    out->data()[0] ^= 0x01;
  }
  return st;
}

Status DataPlane::RingAllgathervPhase(const std::vector<int64_t>& offsets,
                                      const std::vector<int64_t>& block_bytes,
                                      uint8_t* out) {
  const int right = (rank_ + 1) % size_;
  const int left = (rank_ - 1 + size_) % size_;
  // Standard ring allgather generalized to ragged blocks: at step s forward
  // block (rank - s) — own block first, then whatever just arrived — and
  // receive block (rank - s - 1) straight into its slot.
  for (int s = 0; s < size_ - 1; ++s) {
    const int send_b = ((rank_ - s) % size_ + size_) % size_;
    const int recv_b = ((rank_ - s - 1) % size_ + size_) % size_;
    AddOpBytes(block_bytes[send_b], block_bytes[send_b]);
    Status st = Exchange(right, out + offsets[send_b], block_bytes[send_b],
                         left, out + offsets[recv_b], block_bytes[recv_b]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::CompressedRingAllgatherv(
    const std::vector<int64_t>& offsets,
    const std::vector<int64_t>& block_bytes, uint8_t* out) {
  const WireCompression c = op_comp_;
  const int right = (rank_ + 1) % size_;
  const int left = (rank_ - 1 + size_) % size_;
  auto block_count = [&](int b) {
    return block_bytes[b] / static_cast<int64_t>(sizeof(float));
  };
  int64_t max_count = 0;
  for (int b = 0; b < size_; ++b) {
    max_count = std::max(max_count, block_count(b));
  }
  std::vector<uint8_t> cur(static_cast<size_t>(WireBytes(c, max_count)));
  std::vector<uint8_t> next(cur.size());

  // Quantize-once at the owner, exactly like the compressed ring
  // allreduce's allgather phase: my block's codes are produced here (no
  // error-feedback residual — an allgather payload is a value, not a
  // gradient stream) with self-decode, so my own copy holds the same lossy
  // values every receiver will decode; each later hop forwards the codes
  // verbatim and the gathered vectors agree bitwise world-wide.
  float* own = reinterpret_cast<float*>(out + offsets[rank_]);
  const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
  {
    ProfPhaseScope prof_codec(PerfPhase::CODEC);
    WireCompress(c, own, block_count(rank_), cur.data(), nullptr, own,
                 op_quality_);
  }
  TraceHop("QUANTIZE", -1, -1, block_bytes[rank_], qt0, io_ctl_.WaitUs());
  for (int s = 0; s < size_ - 1; ++s) {
    const int send_b = ((rank_ - s) % size_ + size_) % size_;
    const int recv_b = ((rank_ - s - 1) % size_ + size_) % size_;
    const int64_t sw = WireBytes(c, block_count(send_b));
    const int64_t rw = WireBytes(c, block_count(recv_b));
    AddOpBytes(block_bytes[send_b], sw);
    Status st = Exchange(right, cur.data(), sw, left, next.data(), rw);
    if (!st.ok()) return st;
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompress(c, next.data(), block_count(recv_b),
                     reinterpret_cast<float*>(out + offsets[recv_b]));
    }
    TraceHop("DEQUANTIZE", -1, -1, block_bytes[recv_b], dt0,
             io_ctl_.WaitUs());
    cur.swap(next);
  }
  return Status::OK();
}

Status DataPlane::BinomialBroadcastSchedule(void* buf, int64_t wire_bytes,
                                            int64_t raw_per_send, int root) {
  // MPICH binomial schedule on virtual ranks (vr 0 = root): receive from
  // vr minus its lowest set bit, then forward down the descending masks —
  // every rank is live after ⌈log2 n⌉ rounds and forwards at most that many
  // copies, vs the flat root shipping n-1 serialized full payloads.
  const int vr = (rank_ - root + size_) % size_;
  int mask = 1;
  while (mask < size_) {
    if (vr & mask) {
      const int src = (rank_ - mask + size_) % size_;
      Status st = RecvFrom(src, buf, wire_bytes, "broadcast recv");
      if (!st.ok()) return st;
      break;
    }
    mask <<= 1;
  }
  mask >>= 1;
  while (mask > 0) {
    if (vr + mask < size_) {
      const int dst = (rank_ + mask) % size_;
      AddOpBytes(raw_per_send, wire_bytes);
      Status st = SendTo(dst, buf, wire_bytes, "broadcast send");
      if (!st.ok()) return st;
    }
    mask >>= 1;
  }
  return Status::OK();
}

Status DataPlane::FlatBroadcastSchedule(void* buf, int64_t wire_bytes,
                                        int64_t raw_per_send, int root) {
  if (rank_ == root) {
    for (int r = 0; r < size_; ++r) {
      if (r == rank_) continue;
      AddOpBytes(raw_per_send, wire_bytes);
      Status st = SendTo(r, buf, wire_bytes, "broadcast send");
      if (!st.ok()) return st;
    }
    return Status::OK();
  }
  return RecvFrom(root, buf, wire_bytes, "broadcast recv");
}

Status DataPlane::CompressedBroadcast(float* data, int64_t count, int root,
                                      bool flat) {
  // Quantize ONCE at the root with self-decode (the PR-18 owner-codes
  // pattern; no error-feedback residual — a broadcast payload is a value,
  // not a gradient stream), forward the codes verbatim, decode everywhere:
  // every rank ends up decoding the identical byte stream, so the broadcast
  // is bitwise identical world-wide even under int4.
  const WireCompression c = op_comp_;
  const int64_t raw = count * static_cast<int64_t>(sizeof(float));
  std::vector<uint8_t> codes(static_cast<size_t>(WireBytes(c, count)));
  if (rank_ == root) {
    const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireCompress(c, data, count, codes.data(), nullptr, data, op_quality_);
    }
    TraceHop("QUANTIZE", -1, -1, raw, qt0, io_ctl_.WaitUs());
  }
  Status st =
      flat ? FlatBroadcastSchedule(codes.data(), WireBytes(c, count), raw,
                                   root)
           : BinomialBroadcastSchedule(codes.data(), WireBytes(c, count), raw,
                                       root);
  if (!st.ok()) return st;
  if (rank_ != root) {
    // Decode AFTER the forwards: children must see the owner's codes
    // verbatim, never a re-quantization of this rank's decoded copy.
    const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireDecompress(c, codes.data(), count, data);
    }
    TraceHop("DEQUANTIZE", -1, -1, raw, dt0, io_ctl_.WaitUs());
  }
  return Status::OK();
}

Status DataPlane::Broadcast(void* data, int64_t bytes, int root) {
  op_raw_bytes_ = 0;
  op_wire_bytes_ = 0;
  last_algo_label_ = "none";
  trace_op_ = false;
  if (size_ == 1 || bytes == 0) {
    ResetOpPhaseAccum();  // ObserveOp reads the accumulators regardless
    return Status::OK();
  }
  BeginOpTrace();
  MaybeChaosOp();
  // Latency floor: at or below bcast_flat_max_ the root's n-1 direct sends
  // beat the tree's serialized store-and-forward rounds (one hop of depth
  // per peer vs ⌈log2 n⌉ handoffs of a payload too small to pipeline).
  const bool flat = bytes <= bcast_flat_max_;
  Status st;
  if (op_comp_ != WireCompression::NONE) {
    // The core arms compression for fp32 payloads only (EffectiveCompression),
    // so the element count is exact.
    last_algo_label_ = flat ? "bcast_flat" : "bcast_tree";
    st = CompressedBroadcast(static_cast<float*>(data),
                             bytes / static_cast<int64_t>(sizeof(float)),
                             root, flat);
  } else if (flat) {
    last_algo_label_ = "bcast_flat";
    st = FlatBroadcastSchedule(data, bytes, bytes, root);
  } else {
    last_algo_label_ = "bcast_tree";
    st = BinomialBroadcastSchedule(data, bytes, bytes, root);
  }
  raw_bytes_total_->Add(op_raw_bytes_);
  wire_bytes_total_->Add(op_wire_bytes_);
  PublishZeroCopyCounters();
  if (corrupt_pending_ && st.ok()) {
    // Seeded SDC (HVDTPU_CHAOS corrupt@op=N): flip one byte of this rank's
    // broadcast output — the divergence probe fingerprints broadcast
    // results too (every rank holds bitwise-identical bytes).
    corrupt_pending_ = false;
    static_cast<uint8_t*>(data)[0] ^= 0x01;
  }
  return st;
}

Status DataPlane::CompressedAlltoallv(const float* in,
                                      const std::vector<int64_t>& send_off,
                                      const std::vector<int64_t>& recv_off,
                                      uint8_t* out) {
  // Every block travels exactly one hop, so the sender quantizes it once
  // for its single receiver — no forwarding discipline needed for
  // determinism. The self block rides the same quantize/self-decode
  // roundtrip (straight into `out`), so every block a rank holds is
  // uniformly lossy: symmetric inputs still produce world-bitwise outputs.
  const WireCompression c = op_comp_;
  const int64_t felem = static_cast<int64_t>(sizeof(float));
  auto scount = [&](int r) { return (send_off[r + 1] - send_off[r]) / felem; };
  auto rcount = [&](int r) { return (recv_off[r + 1] - recv_off[r]) / felem; };
  int64_t max_count = 0;
  for (int r = 0; r < size_; ++r) {
    max_count = std::max(max_count, std::max(scount(r), rcount(r)));
  }
  std::vector<uint8_t> scodes(static_cast<size_t>(WireBytes(c, max_count)));
  std::vector<uint8_t> rcodes(scodes.size());
  if (scount(rank_) > 0) {
    const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
    {
      ProfPhaseScope prof_codec(PerfPhase::CODEC);
      WireCompress(c, in + send_off[rank_] / felem, scount(rank_),
                   scodes.data(), nullptr,
                   reinterpret_cast<float*>(out + recv_off[rank_]),
                   op_quality_);
    }
    TraceHop("QUANTIZE", -1, -1, scount(rank_) * felem, qt0,
             io_ctl_.WaitUs());
  }
  for (int k = 1; k < size_; ++k) {
    const int to = (rank_ + k) % size_;
    const int from = (rank_ - k + size_) % size_;
    const int64_t sw = WireBytes(c, scount(to));
    const int64_t rw = WireBytes(c, rcount(from));
    if (scount(to) > 0) {
      const int64_t qt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
      {
        ProfPhaseScope prof_codec(PerfPhase::CODEC);
        WireCompress(c, in + send_off[to] / felem, scount(to), scodes.data(),
                     nullptr, nullptr, op_quality_);
      }
      TraceHop("QUANTIZE", -1, -1, scount(to) * felem, qt0, io_ctl_.WaitUs());
    }
    AddOpBytes(scount(to) * felem, scount(to) > 0 ? sw : 0);
    Status st = Exchange(to, scodes.data(), scount(to) > 0 ? sw : 0, from,
                         rcodes.data(), rcount(from) > 0 ? rw : 0);
    if (!st.ok()) return st;
    if (rcount(from) > 0) {
      const int64_t dt0 = rec_hops_ ? Timeline::SteadyAbsUs() : 0;
      {
        ProfPhaseScope prof_codec(PerfPhase::CODEC);
        WireDecompress(c, rcodes.data(), rcount(from),
                       reinterpret_cast<float*>(out + recv_off[from]));
      }
      TraceHop("DEQUANTIZE", -1, -1, rcount(from) * felem, dt0,
               io_ctl_.WaitUs());
    }
  }
  return Status::OK();
}

Status DataPlane::Alltoallv(const void* in,
                            const std::vector<int64_t>& send_bytes,
                            const std::vector<int64_t>& recv_bytes,
                            ByteBuf* out) {
  op_raw_bytes_ = 0;
  op_wire_bytes_ = 0;
  last_algo_label_ = "none";
  trace_op_ = false;
  std::vector<int64_t> send_off(size_ + 1, 0), recv_off(size_ + 1, 0);
  for (int r = 0; r < size_; ++r) {
    send_off[r + 1] = send_off[r] + send_bytes[r];
    recv_off[r + 1] = recv_off[r] + recv_bytes[r];
  }
  out->resize(static_cast<size_t>(recv_off[size_]));
  const uint8_t* src = static_cast<const uint8_t*>(in);
  if (size_ == 1) {
    memcpy(out->data(), src + send_off[rank_],
           static_cast<size_t>(send_bytes[rank_]));
    ResetOpPhaseAccum();  // ObserveOp reads the accumulators regardless
    return Status::OK();
  }
  BeginOpTrace();
  MaybeChaosOp();
  last_algo_label_ = "pairwise";
  Status st;
  if (op_comp_ != WireCompression::NONE) {
    st = CompressedAlltoallv(reinterpret_cast<const float*>(in), send_off,
                             recv_off, out->data());
  } else {
    memcpy(out->data() + recv_off[rank_], src + send_off[rank_],
           static_cast<size_t>(send_bytes[rank_]));
    st = Status::OK();
    for (int k = 1; k < size_; ++k) {
      int to = (rank_ + k) % size_;
      int from = (rank_ - k + size_) % size_;
      AddOpBytes(send_bytes[to], send_bytes[to]);
      st = Exchange(to, src + send_off[to], send_bytes[to], from,
                    out->data() + recv_off[from], recv_bytes[from]);
      if (!st.ok()) break;
    }
  }
  raw_bytes_total_->Add(op_raw_bytes_);
  wire_bytes_total_->Add(op_wire_bytes_);
  PublishZeroCopyCounters();
  if (corrupt_pending_ && st.ok() && !out->empty()) {
    // Seeded SDC in this rank's routed output (docs/numerics.md).
    corrupt_pending_ = false;
    out->data()[0] ^= 0x01;
  }
  return st;
}

namespace {

template <typename T>
void AdasumCombine(T* mine, const T* other, int64_t count, bool i_am_lower) {
  double dot = 0, mine2 = 0, theirs2 = 0;
  for (int64_t i = 0; i < count; ++i) {
    dot += static_cast<double>(mine[i]) * static_cast<double>(other[i]);
    mine2 += static_cast<double>(mine[i]) * static_cast<double>(mine[i]);
    theirs2 += static_cast<double>(other[i]) * static_cast<double>(other[i]);
  }
  double na2 = i_am_lower ? mine2 : theirs2;
  double nb2 = i_am_lower ? theirs2 : mine2;
  double a_coeff = na2 == 0 ? 1.0 : 1.0 - dot / (2.0 * na2);
  double b_coeff = nb2 == 0 ? 1.0 : 1.0 - dot / (2.0 * nb2);
  double my_coeff = i_am_lower ? a_coeff : b_coeff;
  double their_coeff = i_am_lower ? b_coeff : a_coeff;
  for (int64_t i = 0; i < count; ++i) {
    mine[i] = static_cast<T>(my_coeff * static_cast<double>(mine[i]) +
                             their_coeff * static_cast<double>(other[i]));
  }
}

template <typename T>
void AddInto(T* dst, const T* src, int64_t count) {
  for (int64_t i = 0; i < count; ++i) dst[i] += src[i];
}

}  // namespace

Status DataPlane::AdasumAllreduce(void* data, int64_t count, DataType dtype) {
  op_raw_bytes_ = 0;
  op_wire_bytes_ = 0;
  last_algo_label_ = "adasum";
  trace_op_ = false;
  if (dtype != DataType::FLOAT32 && dtype != DataType::FLOAT64) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "Adasum supports float32/float64 only, got " +
                             std::string(DataTypeName(dtype)));
  }
  if (size_ == 1 || count == 0) {
    ResetOpPhaseAccum();  // ObserveOp reads the accumulators regardless
    return Status::OK();
  }
  BeginOpTrace();
  MaybeChaosOp();
  const size_t elem = DataTypeSize(dtype);
  const int64_t bytes = count * static_cast<int64_t>(elem);
  std::vector<uint8_t> other(static_cast<size_t>(bytes));

  int p = 1;
  while (p * 2 <= size_) p *= 2;
  const int r = size_ - p;

  auto exchange = [&](int peer) -> Status {
    AddOpBytes(bytes, bytes);
    return Exchange(peer, data, bytes, peer, other.data(), bytes);
  };
  auto combine = [&](bool lower) {
    if (dtype == DataType::FLOAT32) {
      AdasumCombine(static_cast<float*>(data),
                    reinterpret_cast<const float*>(other.data()), count, lower);
    } else {
      AdasumCombine(static_cast<double*>(data),
                    reinterpret_cast<const double*>(other.data()), count,
                    lower);
    }
  };

  // Fold extra ranks (>= p) into their partner by plain addition.
  if (rank_ >= p) {
    AddOpBytes(bytes, bytes);
    Status st = SendTo(rank_ - p, data, bytes, "adasum fold send");
    if (!st.ok()) return st;
  } else if (rank_ < r) {
    Status st = RecvFrom(rank_ + p, other.data(), bytes, "adasum fold recv");
    if (!st.ok()) return st;
    if (dtype == DataType::FLOAT32) {
      AddInto(static_cast<float*>(data),
              reinterpret_cast<const float*>(other.data()), count);
    } else {
      AddInto(static_cast<double*>(data),
              reinterpret_cast<const double*>(other.data()), count);
    }
  }

  if (rank_ < p) {
    for (int distance = 1; distance < p; distance *= 2) {
      int peer = rank_ ^ distance;
      Status st = exchange(peer);
      if (!st.ok()) return st;
      combine((rank_ & distance) == 0);
    }
  }

  // Broadcast the result to the folded ranks.
  if (rank_ < r) {
    AddOpBytes(bytes, bytes);
    Status st = SendTo(rank_ + p, data, bytes, "adasum unfold send");
    if (!st.ok()) return st;
  } else if (rank_ >= p) {
    Status st = RecvFrom(rank_ - p, data, bytes, "adasum unfold recv");
    if (!st.ok()) return st;
  }
  raw_bytes_total_->Add(op_raw_bytes_);
  wire_bytes_total_->Add(op_wire_bytes_);
  PublishZeroCopyCounters();
  return Status::OK();
}

Status DataPlane::ReduceScatter(const void* in, int64_t count, DataType dtype,
                                ReduceOp op, ByteBuf* out) {
  op_raw_bytes_ = 0;
  op_wire_bytes_ = 0;
  last_algo_label_ = "none";
  trace_op_ = false;
  const size_t elem = DataTypeSize(dtype);
  if (size_ == 1) {
    out->resize(static_cast<size_t>(count) * elem);
    memcpy(out->data(), in, out->size());
    ResetOpPhaseAccum();  // ObserveOp reads the accumulators regardless
    return Status::OK();
  }
  if (count == 0) {
    out->clear();
    ResetOpPhaseAccum();
    return Status::OK();
  }
  BeginOpTrace();
  MaybeChaosOp();
  // The ring reduces in place: stage the input in a full-length scratch
  // (the caller's buffer is const and may be the user's pinned array).
  std::vector<uint8_t> work(static_cast<size_t>(count) * elem);
  memcpy(work.data(), in, work.size());
  // The ring's reduce-scatter phase with PUBLIC chunk ownership: the phase
  // leaves member gi owning chunk (gi+1) % gs, so run it over the rotated
  // group [1, 2, ..., n-1, 0]. Rank r sits at group index (r-1+n)%n and
  // therefore owns chunk r — while its physical ring neighbors (right =
  // r+1, left = r-1) are exactly the flat ring's, so the segmented
  // exchanges, shm in-place views and zero-copy lanes are reused unchanged.
  std::vector<int> rot(size_);
  for (int i = 0; i < size_; ++i) rot[i] = (i + 1) % size_;
  const int gi = (rank_ - 1 + size_) % size_;
  std::vector<int64_t> starts = ChunkStarts(count, size_);
  last_algo_label_ = "ring";
  Status st;
  if (CompressionActive(dtype, op)) {
    st = CompressedRingReduceScatter(reinterpret_cast<float*>(work.data()),
                                     starts, rot, gi);
  } else {
    st = RingReduceScatterPhase(work.data(), starts, elem, dtype, op, rot,
                                gi);
  }
  if (st.ok()) {
    out->assign(
        work.begin() + starts[rank_] * static_cast<int64_t>(elem),
        work.begin() + starts[rank_ + 1] * static_cast<int64_t>(elem));
  }
  raw_bytes_total_->Add(op_raw_bytes_);
  wire_bytes_total_->Add(op_wire_bytes_);
  PublishZeroCopyCounters();
  if (corrupt_pending_ && st.ok() && !out->empty()) {
    // Seeded SDC in this rank's reduced chunk (docs/numerics.md).
    corrupt_pending_ = false;
    out->data()[0] ^= 0x01;
  }
  return st;
}

}  // namespace hvdtpu
