#include "data_plane.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <thread>

#include "socket_util.h"

namespace hvdtpu {

namespace {

// --- fp16 / bf16 conversion (reference: horovod/common/half.{h,cc}) ---------

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {
      // subnormal: normalize
      exp = 127 - 15 + 1;
      while ((mant & 0x400u) == 0) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3ffu;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1f) {
    bits = sign | 0x7f800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t FloatToHalf(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (exp >= 0x1f) {
    // NaN must stay NaN (nonzero mantissa); inf and overflow saturate.
    if (((bits >> 23) & 0xffu) == 0xffu && mant != 0)
      return static_cast<uint16_t>(sign | 0x7e00u);
    return static_cast<uint16_t>(sign | 0x7c00u);
  }
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint16_t h = static_cast<uint16_t>(sign | (mant >> shift));
    // round-to-nearest
    if ((mant >> (shift - 1)) & 1u) h++;
    return h;
  }
  uint16_t h = static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
  if (mant & 0x1000u) h++;  // round
  return h;
}

inline float Bf16ToFloat(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t FloatToBf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  // NaN first: the rounding add below would carry its mantissa into the
  // exponent (NaN -> inf) or even the sign bit (0x7fffffff -> -0.0).
  if ((bits & 0x7fffffffu) > 0x7f800000u)
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);  // quiet NaN
  // round-to-nearest-even
  uint32_t rounded = bits + 0x7fffu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>(rounded >> 16);
}

template <typename T>
inline T Combine(T a, T b, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:
    case ReduceOp::ADASUM:
      return a + b;
    case ReduceOp::MIN:
      return std::min(a, b);
    case ReduceOp::MAX:
      return std::max(a, b);
    case ReduceOp::PRODUCT:
      return a * b;
  }
  return a;
}

template <typename T>
void ReduceTyped(T* dst, const T* src, int64_t count, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:
    case ReduceOp::ADASUM:
      for (int64_t i = 0; i < count; ++i) dst[i] += src[i];
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < count; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < count; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < count; ++i) dst[i] *= src[i];
      break;
  }
}

}  // namespace

float HalfToFloatPublic(uint16_t h) { return HalfToFloat(h); }
uint16_t FloatToHalfPublic(float f) { return FloatToHalf(f); }
float Bf16ToFloatPublic(uint16_t h) { return Bf16ToFloat(h); }
uint16_t FloatToBf16Public(float f) { return FloatToBf16(f); }

void ReduceBuffer(void* dst, const void* src, int64_t count, DataType dtype,
                  ReduceOp op) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, op);
      break;
    case DataType::FLOAT64:
      ReduceTyped(static_cast<double*>(dst), static_cast<const double*>(src),
                  count, op);
      break;
    case DataType::INT32:
      ReduceTyped(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
                  count, op);
      break;
    case DataType::INT64:
      ReduceTyped(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
                  count, op);
      break;
    case DataType::UINT8:
      ReduceTyped(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                  count, op);
      break;
    case DataType::INT8:
      ReduceTyped(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                  count, op);
      break;
    case DataType::BOOL: {
      uint8_t* d = static_cast<uint8_t*>(dst);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      // bool: SUM/MAX == OR, MIN/PRODUCT == AND
      if (op == ReduceOp::MIN || op == ReduceOp::PRODUCT) {
        for (int64_t i = 0; i < count; ++i) d[i] = d[i] && s[i];
      } else {
        for (int64_t i = 0; i < count; ++i) d[i] = d[i] || s[i];
      }
      break;
    }
    case DataType::FLOAT16: {
      uint16_t* d = static_cast<uint16_t*>(dst);
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToHalf(
            Combine(HalfToFloat(d[i]), HalfToFloat(s[i]), op));
      }
      break;
    }
    case DataType::BFLOAT16: {
      uint16_t* d = static_cast<uint16_t*>(dst);
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToBf16(
            Combine(Bf16ToFloat(d[i]), Bf16ToFloat(s[i]), op));
      }
      break;
    }
  }
}

DataPlane::DataPlane(int rank, int size)
    : rank_(rank), size_(size), fds_(size, -1) {}

DataPlane::~DataPlane() { Shutdown(); }

Status DataPlane::Listen() {
  listen_fd_ = TcpListen(0, size_ + 4, &port_);
  if (listen_fd_ < 0) {
    return Status::Error(StatusCode::ABORTED, "data plane: listen failed");
  }
  return Status::OK();
}

Status DataPlane::Connect(const std::vector<PeerAddr>& peers) {
  // Deterministic, deadlock-free establishment: connect to lower ranks (they
  // are already listening), accept from higher ranks. Rank is identified by a
  // 4-byte hello.
  for (int peer = 0; peer < rank_; ++peer) {
    int fd = TcpConnectRetry(peers[peer].host, peers[peer].port, 30000);
    if (fd < 0) {
      return Status::Error(StatusCode::ABORTED,
                           "data plane: connect to rank " +
                               std::to_string(peer) + " failed");
    }
    int32_t me = rank_;
    if (SendAll(fd, &me, sizeof(me)) != 0) {
      CloseFd(fd);
      return Status::Error(StatusCode::ABORTED, "data plane: hello failed");
    }
    fds_[peer] = fd;
  }
  for (int expected = 0; expected < size_ - rank_ - 1; ++expected) {
    int fd = TcpAccept(listen_fd_);
    if (fd < 0) {
      return Status::Error(StatusCode::ABORTED, "data plane: accept failed");
    }
    int32_t who = -1;
    if (RecvAll(fd, &who, sizeof(who)) != 0 || who <= rank_ || who >= size_) {
      CloseFd(fd);
      return Status::Error(StatusCode::ABORTED, "data plane: bad hello");
    }
    fds_[who] = fd;
  }
  return Status::OK();
}

void DataPlane::Shutdown() {
  for (int& fd : fds_) {
    CloseFd(fd);
    fd = -1;
  }
  CloseFd(listen_fd_);
  listen_fd_ = -1;
}

Status DataPlane::SendRecv(int send_fd, const void* send_buf,
                           int64_t send_bytes, int recv_fd, void* recv_buf,
                           int64_t recv_bytes) {
  // Concurrent send+recv so large payloads can't deadlock on socket buffers.
  int send_rc = 0;
  std::thread sender([&] {
    if (send_bytes > 0) {
      send_rc = SendAll(send_fd, send_buf, static_cast<size_t>(send_bytes));
    }
  });
  int recv_rc = 0;
  if (recv_bytes > 0) {
    recv_rc = RecvAll(recv_fd, recv_buf, static_cast<size_t>(recv_bytes));
  }
  sender.join();
  if (send_rc != 0 || recv_rc != 0) {
    return Status::Error(StatusCode::ABORTED, "data plane: transfer failed");
  }
  return Status::OK();
}

Status DataPlane::Allreduce(void* data, int64_t count, DataType dtype,
                            ReduceOp op) {
  if (size_ == 1 || count == 0) return Status::OK();
  const size_t elem = DataTypeSize(dtype);
  uint8_t* buf = static_cast<uint8_t*>(data);
  const int right = (rank_ + 1) % size_;
  const int left = (rank_ - 1 + size_) % size_;

  // Chunk boundaries (chunk c covers [starts[c], starts[c+1])).
  std::vector<int64_t> starts(size_ + 1, 0);
  int64_t base = count / size_, rem = count % size_;
  for (int c = 0; c < size_; ++c) {
    starts[c + 1] = starts[c] + base + (c < rem ? 1 : 0);
  }
  auto chunk_ptr = [&](int c) { return buf + starts[c] * elem; };
  auto chunk_count = [&](int c) { return starts[c + 1] - starts[c]; };
  int64_t max_chunk = base + (rem > 0 ? 1 : 0);
  std::vector<uint8_t> recv_tmp(static_cast<size_t>(max_chunk) * elem);

  // Phase 1: ring reduce-scatter. After step s, chunk (rank - s - 1) holds
  // the partial sum of s + 2 ranks; after size-1 steps, chunk (rank + 1)
  // holds the full reduction on this rank... (standard ring schedule: send
  // chunk (rank - s), receive + reduce chunk (rank - s - 1)).
  for (int s = 0; s < size_ - 1; ++s) {
    int send_c = ((rank_ - s) % size_ + size_) % size_;
    int recv_c = ((rank_ - s - 1) % size_ + size_) % size_;
    Status st = SendRecv(fds_[right], chunk_ptr(send_c),
                         chunk_count(send_c) * static_cast<int64_t>(elem),
                         fds_[left], recv_tmp.data(),
                         chunk_count(recv_c) * static_cast<int64_t>(elem));
    if (!st.ok()) return st;
    ReduceBuffer(chunk_ptr(recv_c), recv_tmp.data(), chunk_count(recv_c),
                 dtype, op);
  }

  // Phase 2: ring allgather of the reduced chunks.
  for (int s = 0; s < size_ - 1; ++s) {
    int send_c = ((rank_ + 1 - s) % size_ + size_) % size_;
    int recv_c = ((rank_ - s) % size_ + size_) % size_;
    Status st = SendRecv(fds_[right], chunk_ptr(send_c),
                         chunk_count(send_c) * static_cast<int64_t>(elem),
                         fds_[left], chunk_ptr(recv_c),
                         chunk_count(recv_c) * static_cast<int64_t>(elem));
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::Allgatherv(const void* in, int64_t in_bytes,
                             const std::vector<int64_t>& block_bytes,
                             std::vector<uint8_t>* out) {
  std::vector<int64_t> offsets(size_ + 1, 0);
  for (int r = 0; r < size_; ++r) offsets[r + 1] = offsets[r] + block_bytes[r];
  out->resize(static_cast<size_t>(offsets[size_]));
  memcpy(out->data() + offsets[rank_], in, static_cast<size_t>(in_bytes));
  if (size_ == 1) return Status::OK();
  // Pairwise rotation: step k sends my block to rank (rank+k), receives the
  // block of rank (rank-k).
  for (int k = 1; k < size_; ++k) {
    int to = (rank_ + k) % size_;
    int from = (rank_ - k + size_) % size_;
    Status st = SendRecv(fds_[to], in, in_bytes, fds_[from],
                         out->data() + offsets[from], block_bytes[from]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status DataPlane::Broadcast(void* data, int64_t bytes, int root) {
  if (size_ == 1 || bytes == 0) return Status::OK();
  if (rank_ == root) {
    for (int r = 0; r < size_; ++r) {
      if (r == rank_) continue;
      if (SendAll(fds_[r], data, static_cast<size_t>(bytes)) != 0) {
        return Status::Error(StatusCode::ABORTED, "broadcast send failed");
      }
    }
  } else {
    if (RecvAll(fds_[root], data, static_cast<size_t>(bytes)) != 0) {
      return Status::Error(StatusCode::ABORTED, "broadcast recv failed");
    }
  }
  return Status::OK();
}

Status DataPlane::Alltoallv(const void* in,
                            const std::vector<int64_t>& send_bytes,
                            const std::vector<int64_t>& recv_bytes,
                            std::vector<uint8_t>* out) {
  std::vector<int64_t> send_off(size_ + 1, 0), recv_off(size_ + 1, 0);
  for (int r = 0; r < size_; ++r) {
    send_off[r + 1] = send_off[r] + send_bytes[r];
    recv_off[r + 1] = recv_off[r] + recv_bytes[r];
  }
  out->resize(static_cast<size_t>(recv_off[size_]));
  const uint8_t* src = static_cast<const uint8_t*>(in);
  memcpy(out->data() + recv_off[rank_], src + send_off[rank_],
         static_cast<size_t>(send_bytes[rank_]));
  for (int k = 1; k < size_; ++k) {
    int to = (rank_ + k) % size_;
    int from = (rank_ - k + size_) % size_;
    Status st = SendRecv(fds_[to], src + send_off[to], send_bytes[to],
                         fds_[from], out->data() + recv_off[from],
                         recv_bytes[from]);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

namespace {

template <typename T>
void AdasumCombine(T* mine, const T* other, int64_t count, bool i_am_lower) {
  double dot = 0, mine2 = 0, theirs2 = 0;
  for (int64_t i = 0; i < count; ++i) {
    dot += static_cast<double>(mine[i]) * static_cast<double>(other[i]);
    mine2 += static_cast<double>(mine[i]) * static_cast<double>(mine[i]);
    theirs2 += static_cast<double>(other[i]) * static_cast<double>(other[i]);
  }
  double na2 = i_am_lower ? mine2 : theirs2;
  double nb2 = i_am_lower ? theirs2 : mine2;
  double a_coeff = na2 == 0 ? 1.0 : 1.0 - dot / (2.0 * na2);
  double b_coeff = nb2 == 0 ? 1.0 : 1.0 - dot / (2.0 * nb2);
  double my_coeff = i_am_lower ? a_coeff : b_coeff;
  double their_coeff = i_am_lower ? b_coeff : a_coeff;
  for (int64_t i = 0; i < count; ++i) {
    mine[i] = static_cast<T>(my_coeff * static_cast<double>(mine[i]) +
                             their_coeff * static_cast<double>(other[i]));
  }
}

template <typename T>
void AddInto(T* dst, const T* src, int64_t count) {
  for (int64_t i = 0; i < count; ++i) dst[i] += src[i];
}

}  // namespace

Status DataPlane::AdasumAllreduce(void* data, int64_t count, DataType dtype) {
  if (dtype != DataType::FLOAT32 && dtype != DataType::FLOAT64) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "Adasum supports float32/float64 only, got " +
                             std::string(DataTypeName(dtype)));
  }
  if (size_ == 1 || count == 0) return Status::OK();
  const size_t elem = DataTypeSize(dtype);
  const int64_t bytes = count * static_cast<int64_t>(elem);
  std::vector<uint8_t> other(static_cast<size_t>(bytes));

  int p = 1;
  while (p * 2 <= size_) p *= 2;
  const int r = size_ - p;

  auto exchange = [&](int peer) -> Status {
    return SendRecv(fds_[peer], data, bytes, fds_[peer], other.data(), bytes);
  };
  auto combine = [&](bool lower) {
    if (dtype == DataType::FLOAT32) {
      AdasumCombine(static_cast<float*>(data),
                    reinterpret_cast<const float*>(other.data()), count, lower);
    } else {
      AdasumCombine(static_cast<double*>(data),
                    reinterpret_cast<const double*>(other.data()), count,
                    lower);
    }
  };

  // Fold extra ranks (>= p) into their partner by plain addition.
  if (rank_ >= p) {
    if (SendAll(fds_[rank_ - p], data, static_cast<size_t>(bytes)) != 0) {
      return Status::Error(StatusCode::ABORTED, "adasum fold send failed");
    }
  } else if (rank_ < r) {
    if (RecvAll(fds_[rank_ + p], other.data(), static_cast<size_t>(bytes)) !=
        0) {
      return Status::Error(StatusCode::ABORTED, "adasum fold recv failed");
    }
    if (dtype == DataType::FLOAT32) {
      AddInto(static_cast<float*>(data),
              reinterpret_cast<const float*>(other.data()), count);
    } else {
      AddInto(static_cast<double*>(data),
              reinterpret_cast<const double*>(other.data()), count);
    }
  }

  if (rank_ < p) {
    for (int distance = 1; distance < p; distance *= 2) {
      int peer = rank_ ^ distance;
      Status st = exchange(peer);
      if (!st.ok()) return st;
      combine((rank_ & distance) == 0);
    }
  }

  // Broadcast the result to the folded ranks.
  if (rank_ < r) {
    if (SendAll(fds_[rank_ + p], data, static_cast<size_t>(bytes)) != 0) {
      return Status::Error(StatusCode::ABORTED, "adasum unfold send failed");
    }
  } else if (rank_ >= p) {
    if (RecvAll(fds_[rank_ - p], data, static_cast<size_t>(bytes)) != 0) {
      return Status::Error(StatusCode::ABORTED, "adasum unfold recv failed");
    }
  }
  return Status::OK();
}

Status DataPlane::ReduceScatter(const void* in, int64_t count, DataType dtype,
                                ReduceOp op, std::vector<uint8_t>* out) {
  // Simple implementation on top of ring allreduce: reduce a copy, keep my
  // chunk. (A dedicated reduce-scatter would halve traffic; the coordinator
  // only dispatches small eager tensors here — the compiled path owns the hot
  // loop.)
  const size_t elem = DataTypeSize(dtype);
  std::vector<uint8_t> tmp(static_cast<size_t>(count) * elem);
  memcpy(tmp.data(), in, tmp.size());
  Status st = Allreduce(tmp.data(), count, dtype, op);
  if (!st.ok()) return st;
  int64_t chunk = count / size_;
  out->assign(tmp.begin() + rank_ * chunk * static_cast<int64_t>(elem),
              tmp.begin() + (rank_ + 1) * chunk * static_cast<int64_t>(elem));
  return Status::OK();
}

}  // namespace hvdtpu
