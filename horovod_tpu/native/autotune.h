// Online autotuning of background-loop parameters.
//
// Reference: horovod/common/parameter_manager.{h,cc} (ParameterManager,
// BayesianParameter h:186) + horovod/common/optim/bayesian_optimization.{h,cc}
// and gaussian_process.{h,cc}. The reference jointly tunes the tensor-fusion
// threshold and cycle time with Bayesian optimization (Gaussian process +
// expected improvement, maximized with Eigen/LBFGS), scoring each sample by
// observed bytes/sec, and broadcasts winning parameters from the coordinator
// (Controller::SynchronizeParameters, controller.cc:34-48).
//
// This rebuild keeps the same structure — warmup, scored samples,
// GP + expected improvement over the 2-D (cycle time, fusion threshold)
// space, freeze at the best point after a sample budget — with a hand-rolled
// Cholesky-based GP (the space is 2-D and samples are few, so Eigen/LBFGS
// buys nothing; EI is maximized over a deterministic candidate sweep).
#pragma once

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace hvdtpu {

// Exact RBF-kernel GP regression on normalized inputs in [0,1]^d.
class GaussianProcess {
 public:
  explicit GaussianProcess(double noise = 1e-4, double length_scale = 0.25)
      : noise_(noise), length_scale_(length_scale) {}

  void Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y);
  // Posterior mean and stddev at a point (y is internally standardized).
  void Predict(const std::vector<double>& x, double* mu, double* sigma) const;
  bool fitted() const { return fitted_; }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double noise_;
  double length_scale_;
  bool fitted_ = false;
  std::vector<std::vector<double>> x_;
  std::vector<double> alpha_;       // (K + noise I)^-1 y_std
  std::vector<double> chol_;        // lower-triangular Cholesky factor, n x n
  double y_mean_ = 0.0, y_std_ = 1.0;
  size_t n_ = 0;
};

// Expected-improvement Bayesian optimizer over [0,1]^d.
class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(int dim, double noise = 1e-4)
      : dim_(dim), gp_(noise) {}

  void AddSample(const std::vector<double>& x, double y);
  // Next point to evaluate: argmax EI over a deterministic candidate sweep
  // (grid + jittered points from an LCG; the reference uses LBFGS restarts).
  std::vector<double> NextSample();
  std::vector<double> BestSample() const;  // argmax of observed y
  size_t num_samples() const { return xs_.size(); }

 private:
  int dim_;
  GaussianProcess gp_;
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  uint64_t rng_ = 0x9e3779b97f4a7c15ull;  // deterministic across ranks/runs
};

// Tunes cycle time, fusion threshold, the response-cache on/off switch,
// the allreduce ring/latency-algorithm crossover size, and the hierarchical
// (two-level) allreduce on/off switch
// online, scored by bytes/sec. Coordinator-only; winning values are
// broadcast to workers by the core (reference: ParameterManager lives in
// HorovodGlobalState and is driven from the background loop,
// operations.cc:615-643; the cache switch mirrors the reference's
// CategoricalParameter dimensions, parameter_manager.h:165/:225 —
// represented here as a thresholded third GP dimension).
// Concurrency contract: a ParameterManager lives on rank 0 and is touched
// only from the core's background loop (Initialize runs on the user thread
// strictly before that loop starts) — no locks, no annotations needed. The
// core republishes adopted values into its own GUARDED_BY(mu_) fields /
// PARAMS frames; nothing reads this object cross-thread.
class ParameterManager {
 public:
  struct Params {
    double cycle_time_ms;
    int64_t fusion_threshold;
    bool cache_enabled;
    // AUTO-algorithm crossover: allreduces at or below this many bytes take
    // the latency algorithm (recursive doubling), larger ones the pipelined
    // ring (data_plane.h AllreduceAlgo).
    int64_t algo_crossover;
    // Scatter-allgather for large tensors under AUTO (data_plane.h
    // sa_auto): a categorical on/off switch over whether big-message
    // dispatch prefers SCATTER_ALLGATHER to RING once the group clears
    // the sa_min_group floor.
    bool sa_enabled;
    // Hierarchical two-level allreduce (data_plane.h HierMode::AUTO): a
    // categorical on/off dimension like the cache switch (reference analog:
    // hierarchical_allreduce in BayesianParameter, parameter_manager.h:186).
    bool hier_enabled;
    // Wire compression under HVDTPU_COMPRESSION=auto (compressed.h
    // WireCompression): a 3-way categorical dimension over {none, fp16,
    // int8} — int4 is excluded from the automatic menu (its accuracy cost
    // is a modelling decision, not a throughput knob; force it explicitly).
    int32_t wire_compression;
  };

  // tune_crossover: include the algo crossover as an extra GP dimension
  // only when the data plane is in AUTO mode — with a pinned algorithm the
  // coordinate cannot affect the score and would just dilute the sample
  // budget; the value is then held constant at algo_crossover. tune_sa:
  // include the scatter-allgather switch only when the algorithm is AUTO
  // and the world clears the sa_min_group floor (otherwise the coordinate
  // is inert). tune_hier: include the hierarchical switch only when
  // HVDTPU_ALLREDUCE_HIER=auto AND the topology is non-trivial (multiple
  // hosts, multi-rank hosts). tune_compression: include the
  // wire-compression categorical only when HVDTPU_COMPRESSION=auto — with
  // a pinned mode the coordinate is inert and would dilute the sample
  // budget, like the crossover/hier gates.
  void Initialize(double cycle_time_ms, int64_t fusion_threshold,
                  bool cache_enabled, int64_t algo_crossover,
                  bool tune_crossover, bool sa_enabled, bool tune_sa,
                  bool hier_enabled, bool tune_hier,
                  int32_t wire_compression, bool tune_compression,
                  const std::string& log_path,
                  int warmup_samples, int cycles_per_sample, int max_samples,
                  double gp_noise);
  ~ParameterManager();

  bool active() const { return active_; }

  // Record bytes moved by one nonempty background cycle (callers must skip
  // zero-byte cycles — they would dilute the bytes/sec score with idle/app
  // time). Each tuning step scores the median of kScoresPerStep samples
  // (reference: parameter_manager.cc tunes on the median of several samples).
  // Returns true when the tuned parameters changed (caller re-reads
  // Current() and broadcasts).
  bool Update(int64_t bytes, double now_secs);
  Params Current() const { return current_; }

 private:
  void SetFromVector(const std::vector<double>& x);
  std::vector<double> ToVector(const Params& p) const;
  void LogSample(double score);

  bool active_ = false;
  bool frozen_ = false;
  bool tune_crossover_ = true;
  bool tune_sa_ = false;
  bool tune_hier_ = false;
  bool tune_compression_ = false;
  Params current_{1.0, 64 << 20, true, 32 << 10, true, false, 0};
  BayesianOptimizer opt_{4};
  int warmup_samples_ = 3;
  int cycles_per_sample_ = 50;
  int max_samples_ = 30;
  int warmup_left_ = 3;
  int cycle_count_ = 0;
  int64_t bytes_acc_ = 0;
  double sample_start_ = 0.0;
  static constexpr int kScoresPerStep = 3;
  std::vector<double> step_scores_;
  FILE* log_ = nullptr;
};

}  // namespace hvdtpu
