#include "shm_transport.h"

#include <fcntl.h>
#include <linux/futex.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <climits>
#include <cstring>
#include <new>
#include <thread>

namespace hvdtpu {

namespace {

// Shared (cross-process) futex wait/wake. The protocol never RELIES on wake
// delivery — every wait carries a timeout and re-checks the ring cursors —
// so futex here is purely a power/latency optimization over spinning.
int FutexWait(std::atomic<uint32_t>* addr, uint32_t expected, int timeout_ms) {
  timespec ts{timeout_ms / 1000, (timeout_ms % 1000) * 1000000L};
  return static_cast<int>(
      syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
              expected, &ts, nullptr, 0));
}

void FutexWake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, INT_MAX,
          nullptr, nullptr, 0);
}

constexpr uint32_t kMagic = 0x48565453u;  // "HVTS"
constexpr int kSpinIters = 4096;
constexpr int kWaitSliceMs = 100;

}  // namespace

// Single-producer/single-consumer byte ring. head/tail are free-running
// byte cursors (never wrapped); the data offset is cursor % ring_bytes.
// The producer's release-store of head (and the consumer's acquire-load)
// carries the happens-before for the bytes it covers; symmetrically tail
// hands regions back to the producer for reuse.
struct alignas(64) ShmRing {
  std::atomic<uint64_t> head;       // producer cursor
  char pad0[64 - sizeof(std::atomic<uint64_t>)];
  std::atomic<uint64_t> tail;       // consumer cursor
  char pad1[64 - sizeof(std::atomic<uint64_t>)];
  std::atomic<uint32_t> head_seq;   // futex word: bumped on head advance
  std::atomic<uint32_t> head_waiters;
  char pad2[64 - 2 * sizeof(std::atomic<uint32_t>)];
  std::atomic<uint32_t> tail_seq;   // futex word: bumped on tail advance
  std::atomic<uint32_t> tail_waiters;
  char pad3[64 - 2 * sizeof(std::atomic<uint32_t>)];
};

struct ShmTransport::Segment {
  uint32_t magic;
  std::atomic<uint32_t> ready;    // creator sets once initialized
  std::atomic<uint32_t> aborted;  // either side sets on shutdown/error
  uint32_t reserved;
  uint64_t ring_bytes;
  ShmRing rings[2];  // [0]: creator -> opener, [1]: opener -> creator
  // Data areas follow: ring 0 bytes, then ring 1 bytes.
  uint8_t* data(int ring) {
    return reinterpret_cast<uint8_t*>(this + 1) +
           static_cast<size_t>(ring) * ring_bytes;
  }
};

ShmTransport::ShmTransport(std::string name, Segment* seg, size_t map_bytes,
                           bool creator)
    : name_(std::move(name)),
      seg_(seg),
      map_bytes_(map_bytes),
      ring_bytes_(seg->ring_bytes),
      creator_(creator),
      out_ring_(creator ? 0 : 1) {
  out_data_ = seg_->data(out_ring_);
  in_data_ = seg_->data(1 - out_ring_);
}

std::unique_ptr<ShmTransport> ShmTransport::Create(const std::string& name,
                                                   size_t ring_bytes) {
  if (ring_bytes == 0) ring_bytes = kDefaultShmRingBytes;
  int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0 && errno == EEXIST) {
    // Stale segment from a crashed prior job that happened to reuse our
    // ports: reclaim the name.
    shm_unlink(name.c_str());
    fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  }
  if (fd < 0) return nullptr;
  size_t map_bytes = sizeof(Segment) + 2 * ring_bytes;
  if (ftruncate(fd, static_cast<off_t>(map_bytes)) != 0) {
    close(fd);
    shm_unlink(name.c_str());
    return nullptr;
  }
  void* mem = mmap(nullptr, map_bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name.c_str());
    return nullptr;
  }
  auto* seg = new (mem) Segment();
  for (ShmRing& r : seg->rings) {
    r.head.store(0, std::memory_order_relaxed);
    r.tail.store(0, std::memory_order_relaxed);
    r.head_seq.store(0, std::memory_order_relaxed);
    r.head_waiters.store(0, std::memory_order_relaxed);
    r.tail_seq.store(0, std::memory_order_relaxed);
    r.tail_waiters.store(0, std::memory_order_relaxed);
  }
  seg->aborted.store(0, std::memory_order_relaxed);
  seg->ring_bytes = ring_bytes;
  seg->magic = kMagic;
  seg->ready.store(1, std::memory_order_release);
  return std::unique_ptr<ShmTransport>(
      new ShmTransport(name, seg, map_bytes, /*creator=*/true));
}

std::unique_ptr<ShmTransport> ShmTransport::Open(const std::string& name,
                                                 int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    int fd = shm_open(name.c_str(), O_RDWR, 0600);
    if (fd >= 0) {
      struct stat st;
      if (fstat(fd, &st) == 0 &&
          st.st_size >= static_cast<off_t>(sizeof(Segment))) {
        size_t map_bytes = static_cast<size_t>(st.st_size);
        void* mem = mmap(nullptr, map_bytes, PROT_READ | PROT_WRITE,
                         MAP_SHARED, fd, 0);
        close(fd);
        if (mem == MAP_FAILED) return nullptr;
        auto* seg = static_cast<Segment*>(mem);
        while (!(seg->magic == kMagic &&
                 seg->ready.load(std::memory_order_acquire) == 1)) {
          if (std::chrono::steady_clock::now() >= deadline) {
            munmap(mem, map_bytes);
            return nullptr;
          }
          std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
        if (sizeof(Segment) + 2 * seg->ring_bytes > map_bytes) {
          munmap(mem, map_bytes);
          return nullptr;
        }
        return std::unique_ptr<ShmTransport>(
            new ShmTransport(name, seg, map_bytes, /*creator=*/false));
      }
      close(fd);
    }
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

ShmTransport::~ShmTransport() {
  if (seg_ != nullptr) {
    Abort();  // release any peer still blocked on our rings
    if (creator_) Unlink();
    munmap(seg_, map_bytes_);
    seg_ = nullptr;
  }
}

void ShmTransport::Abort() {
  if (seg_ == nullptr) return;
  seg_->aborted.store(1, std::memory_order_release);
  for (ShmRing& r : seg_->rings) {
    r.head_seq.fetch_add(1, std::memory_order_release);
    r.tail_seq.fetch_add(1, std::memory_order_release);
    FutexWake(&r.head_seq);
    FutexWake(&r.tail_seq);
  }
}

void ShmTransport::Unlink() {
  if (!unlinked_) {
    unlinked_ = true;
    shm_unlink(name_.c_str());  // ENOENT is fine (already gone)
  }
}

size_t ShmTransport::TrySend(const uint8_t* buf, size_t len) {
  ShmRing& r = seg_->rings[out_ring_];
  uint64_t head = r.head.load(std::memory_order_relaxed);  // sole producer
  uint64_t tail = r.tail.load(std::memory_order_acquire);
  size_t free_space = ring_bytes_ - static_cast<size_t>(head - tail);
  if (free_space == 0) return 0;
  size_t off = static_cast<size_t>(head % ring_bytes_);
  size_t chunk = std::min({free_space, len, ring_bytes_ - off});
  memcpy(out_data_ + off, buf, chunk);
  r.head.store(head + chunk, std::memory_order_release);
  r.head_seq.fetch_add(1, std::memory_order_seq_cst);
  if (r.head_waiters.load(std::memory_order_seq_cst) != 0) {
    FutexWake(&r.head_seq);
  }
  return chunk;
}

size_t ShmTransport::TryRecv(uint8_t* buf, size_t len) {
  ShmRing& r = seg_->rings[1 - out_ring_];
  uint64_t tail = r.tail.load(std::memory_order_relaxed);  // sole consumer
  uint64_t head = r.head.load(std::memory_order_acquire);
  size_t avail = static_cast<size_t>(head - tail);
  if (avail == 0) return 0;
  size_t off = static_cast<size_t>(tail % ring_bytes_);
  size_t chunk = std::min({avail, len, ring_bytes_ - off});
  memcpy(buf, in_data_ + off, chunk);
  r.tail.store(tail + chunk, std::memory_order_release);
  r.tail_seq.fetch_add(1, std::memory_order_seq_cst);
  if (r.tail_waiters.load(std::memory_order_seq_cst) != 0) {
    FutexWake(&r.tail_seq);
  }
  return chunk;
}

bool ShmTransport::PeerDead() {
  if (liveness_fd_ < 0) return false;
  pollfd pfd{liveness_fd_, POLLIN, 0};
  if (poll(&pfd, 1, 0) <= 0) return false;
  if ((pfd.revents & (POLLERR | POLLHUP | POLLNVAL)) == 0) {
    // POLLIN on an idle pair socket: EOF or stray bytes — peek to decide.
    char b;
    ssize_t n = recv(liveness_fd_, &b, 1, MSG_PEEK | MSG_DONTWAIT);
    if (n > 0 || (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))) {
      return false;
    }
  }
  if (ctl_ != nullptr) ctl_->MarkPeerFailed();  // break the WHOLE plane
  Abort();  // wake our own other-direction waiters too
  return true;
}

bool ShmTransport::AbortedNow() const {
  return seg_->aborted.load(std::memory_order_acquire) != 0 ||
         (ctl_ != nullptr && ctl_->is_aborted());
}

int ShmTransport::WaitSliceMs() const {
  if (ctl_ == nullptr) return kWaitSliceMs;
  int64_t s = ctl_->detect_slice_ms;
  return static_cast<int>(s < 1 ? 1 : (s > kWaitSliceMs ? kWaitSliceMs : s));
}

bool ShmTransport::DeadlineExpired(double last_progress) {
  if (ctl_ == nullptr || ctl_->read_deadline_secs <= 0) return false;
  if (MonoSeconds() - last_progress <= ctl_->read_deadline_secs) return false;
  // Peer alive (no EOF on the liveness socket) but the ring hasn't moved
  // past the deadline: a hung peer. Fail the plane instead of waiting out
  // the coordinator's (possibly never-running) stall inspector.
  ctl_->MarkPeerFailed();
  Abort();
  return true;
}

void ShmTransport::WaitOutboundSpace() {
  ShmRing& r = seg_->rings[out_ring_];
  uint64_t head = r.head.load(std::memory_order_relaxed);
  for (int i = 0; i < kSpinIters; ++i) {
    if (r.tail.load(std::memory_order_acquire) + ring_bytes_ != head ||
        AbortedNow()) {
      return;
    }
  }
  if (PeerDead()) return;
  uint32_t seq = r.tail_seq.load(std::memory_order_seq_cst);
  r.tail_waiters.fetch_add(1, std::memory_order_seq_cst);
  if (r.tail.load(std::memory_order_seq_cst) + ring_bytes_ == head &&
      !AbortedNow()) {
    FutexWait(&r.tail_seq, seq, WaitSliceMs());
  }
  r.tail_waiters.fetch_sub(1, std::memory_order_seq_cst);
}

void ShmTransport::WaitInboundData() {
  ShmRing& r = seg_->rings[1 - out_ring_];
  uint64_t tail = r.tail.load(std::memory_order_relaxed);
  for (int i = 0; i < kSpinIters; ++i) {
    if (r.head.load(std::memory_order_acquire) != tail ||
        AbortedNow()) {
      return;
    }
  }
  if (PeerDead()) return;
  uint32_t seq = r.head_seq.load(std::memory_order_seq_cst);
  r.head_waiters.fetch_add(1, std::memory_order_seq_cst);
  if (r.head.load(std::memory_order_seq_cst) == tail &&
      !AbortedNow()) {
    FutexWait(&r.head_seq, seq, WaitSliceMs());
  }
  r.head_waiters.fetch_sub(1, std::memory_order_seq_cst);
}

int ShmTransport::Send(const void* buf, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  size_t done = 0;
  double last_progress = MonoSeconds();
  while (done < len) {
    if (AbortedNow()) return -1;
    size_t n = TrySend(p + done, len - done);
    if (n == 0) {
      if (DeadlineExpired(last_progress)) return -1;
      WaitOutboundSpace();
    } else {
      done += n;
      last_progress = MonoSeconds();
    }
  }
  return 0;
}

int ShmTransport::Recv(void* buf, size_t len) {
  return RecvSegmented(buf, len, 0, nullptr);
}

int ShmTransport::RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                                const SegmentFn& on_segment) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  if (segment_bytes == 0 || segment_bytes > len) segment_bytes = len;
  size_t done = 0, cb_done = 0;
  double last_progress = MonoSeconds();
  while (done < len) {
    if (AbortedNow()) return -1;
    size_t n = TryRecv(p + done, len - done);
    if (n == 0) {
      if (DeadlineExpired(last_progress)) return -1;
      WaitInboundData();
      continue;
    }
    done += n;
    last_progress = MonoSeconds();
    // Fire full segments as they complete; the producer keeps filling the
    // ring while the callback (reduction) runs — the overlap is inherent.
    while (on_segment && done - cb_done >= segment_bytes && cb_done < len) {
      size_t seg_len = std::min(segment_bytes, len - cb_done);
      on_segment(cb_done, seg_len);
      cb_done += seg_len;
    }
  }
  if (on_segment && cb_done < len) on_segment(cb_done, len - cb_done);
  return 0;
}

int ShmTransport::SendRecv(const void* send_buf, size_t send_bytes,
                           void* recv_buf, size_t recv_bytes,
                           size_t segment_bytes, const SegmentFn& on_segment) {
  const uint8_t* sp = static_cast<const uint8_t*>(send_buf);
  uint8_t* rp = static_cast<uint8_t*>(recv_buf);
  if (segment_bytes == 0 || segment_bytes > recv_bytes) {
    segment_bytes = recv_bytes;
  }
  size_t sent = 0, rcvd = 0, cb_done = 0;
  double last_progress = MonoSeconds();
  while (sent < send_bytes || rcvd < recv_bytes) {
    if (AbortedNow()) return -1;
    bool progress = false;
    if (sent < send_bytes) {
      size_t n = TrySend(sp + sent, send_bytes - sent);
      sent += n;
      progress |= n != 0;
    }
    if (rcvd < recv_bytes) {
      size_t n = TryRecv(rp + rcvd, recv_bytes - rcvd);
      rcvd += n;
      progress |= n != 0;
    }
    while (on_segment && rcvd - cb_done >= segment_bytes &&
           cb_done < recv_bytes) {
      size_t seg_len = std::min(segment_bytes, recv_bytes - cb_done);
      on_segment(cb_done, seg_len);
      cb_done += seg_len;
      progress = true;
    }
    if (!progress) {
      if (DeadlineExpired(last_progress)) return -1;
      // Both directions stuck: park on whichever cursor unblocks us
      // (inbound data if we still expect bytes, else outbound space). The
      // peer's pump advances the other direction independently.
      if (rcvd < recv_bytes) {
        WaitInboundData();
      } else {
        WaitOutboundSpace();
      }
    } else {
      last_progress = MonoSeconds();
    }
  }
  if (on_segment && cb_done < recv_bytes) {
    on_segment(cb_done, recv_bytes - cb_done);
  }
  return 0;
}

}  // namespace hvdtpu
