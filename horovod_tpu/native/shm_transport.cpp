#include "shm_transport.h"

#include <ctype.h>
#include <dirent.h>
#include <fcntl.h>
#include <linux/futex.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <climits>
#include <cstring>
#include <new>
#include <thread>

#include "profiler.h"

namespace hvdtpu {

namespace {

// Shared (cross-process) futex wait/wake. The protocol never RELIES on wake
// delivery — every wait carries a timeout and re-checks the ring cursors —
// so futex here is purely a power/latency optimization over spinning. The
// batched doorbells (NotifyHeadAdvance/NotifyTailAdvance) lean the same way:
// a coalesced-away wake is repaired by the next batch boundary, the op-end
// flush, or at worst one wait-slice timeout.
int FutexWait(std::atomic<uint32_t>* addr, uint32_t expected, int timeout_ms) {
  timespec ts{timeout_ms / 1000, (timeout_ms % 1000) * 1000000L};
  return static_cast<int>(
      syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
              expected, &ts, nullptr, 0));
}

void FutexWake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, INT_MAX,
          nullptr, nullptr, 0);
}

constexpr uint32_t kMagic = 0x48565453u;  // "HVTS"
constexpr int kSpinIters = 4096;
constexpr int kWaitSliceMs = 100;

// Pre-futex spin budget. Spinning bets that the peer is running RIGHT NOW
// on another core; on a single-CPU host that bet is always lost — the peer
// cannot advance a cursor while we burn its timeslice — so the budget
// drops to a token few iterations and blocked waits go straight to the
// futex (which yields the core to the peer).
int SpinIters() {
  static const int iters =
      std::thread::hardware_concurrency() > 1 ? kSpinIters : 16;
  return iters;
}

// mbind(2) plumbing without <numaif.h> (absent on this image; the syscall
// is probed at runtime and any failure degrades to "no placement").
constexpr int kMpolPreferred = 1;     // MPOL_PREFERRED
constexpr unsigned kMpolMfMove = 2;   // MPOL_MF_MOVE

}  // namespace

int NumaNodeCount(const std::string& sysfs_dir) {
  DIR* d = opendir(sysfs_dir.c_str());
  if (d == nullptr) return 1;
  int nodes = 0;
  while (dirent* e = readdir(d)) {
    const char* n = e->d_name;
    if (strncmp(n, "node", 4) != 0 || n[4] == '\0') continue;
    bool digits = true;
    for (const char* p = n + 4; *p != '\0'; ++p) {
      if (!isdigit(static_cast<unsigned char>(*p))) {
        digits = false;
        break;
      }
    }
    if (digits) ++nodes;
  }
  closedir(d);
  return nodes > 0 ? nodes : 1;
}

// Single-producer/single-consumer byte ring. head/tail are free-running
// byte cursors (never wrapped); the data offset is cursor % ring_bytes.
// The producer's release-store of head (and the consumer's acquire-load)
// carries the happens-before for the bytes it covers; symmetrically tail
// hands regions back to the producer for reuse.
struct alignas(64) ShmRing {
  std::atomic<uint64_t> head;       // producer cursor  // atomic: release-publish
  char pad0[64 - sizeof(std::atomic<uint64_t>)];
  std::atomic<uint64_t> tail;       // consumer cursor  // atomic: release-publish
  char pad1[64 - sizeof(std::atomic<uint64_t>)];
  std::atomic<uint32_t> head_seq;   // futex word: bumped on head advance  // atomic: seqcst(futex doorbell protocol, see fences below)
  std::atomic<uint32_t> head_waiters;  // atomic: seqcst(futex doorbell protocol)
  char pad2[64 - 2 * sizeof(std::atomic<uint32_t>)];
  std::atomic<uint32_t> tail_seq;   // futex word: bumped on tail advance  // atomic: seqcst(futex doorbell protocol, see fences below)
  std::atomic<uint32_t> tail_waiters;  // atomic: seqcst(futex doorbell protocol)
  char pad3[64 - 2 * sizeof(std::atomic<uint32_t>)];
};

struct ShmTransport::Segment {
  uint32_t magic;
  std::atomic<uint32_t> ready;    // creator sets once initialized  // atomic: release-publish
  std::atomic<uint32_t> aborted;  // either side sets on shutdown/error  // atomic: acquire-read
  uint32_t reserved;
  uint64_t ring_bytes;
  ShmRing rings[2];  // [0]: creator -> opener, [1]: opener -> creator
  // Data areas follow: ring 0 bytes, then ring 1 bytes.
  uint8_t* data(int ring) {
    return reinterpret_cast<uint8_t*>(this + 1) +
           static_cast<size_t>(ring) * ring_bytes;
  }
};

ShmTransport::ShmTransport(std::string name, Segment* seg, size_t map_bytes,
                           bool creator)
    : name_(std::move(name)),
      seg_(seg),
      map_bytes_(map_bytes),
      ring_bytes_(seg->ring_bytes),
      creator_(creator),
      out_ring_(creator ? 0 : 1) {
  out_data_ = seg_->data(out_ring_);
  in_data_ = seg_->data(1 - out_ring_);
}

std::unique_ptr<ShmTransport> ShmTransport::Create(const std::string& name,
                                                   size_t ring_bytes) {
  if (ring_bytes == 0) ring_bytes = kDefaultShmRingBytes;
  // 64-byte-multiple capacity keeps the wrap point element-aligned for
  // every wire dtype the in-place view consumer hands out.
  ring_bytes = (ring_bytes + 63) & ~static_cast<size_t>(63);
  int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0 && errno == EEXIST) {
    // Stale segment from a crashed prior job that happened to reuse our
    // ports: reclaim the name.
    shm_unlink(name.c_str());
    fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  }
  if (fd < 0) return nullptr;
  size_t map_bytes = sizeof(Segment) + 2 * ring_bytes;
  if (ftruncate(fd, static_cast<off_t>(map_bytes)) != 0) {
    close(fd);
    shm_unlink(name.c_str());
    return nullptr;
  }
  void* mem = mmap(nullptr, map_bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name.c_str());
    return nullptr;
  }
  auto* seg = new (mem) Segment();
  for (ShmRing& r : seg->rings) {
    r.head.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init; ready.store(release) below publishes
    r.tail.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init
    r.head_seq.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init
    r.head_waiters.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init
    r.tail_seq.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init
    r.tail_waiters.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init
  }
  seg->aborted.store(0, std::memory_order_relaxed);  // atomic-ok: pre-publication init
  seg->ring_bytes = ring_bytes;
  seg->magic = kMagic;
  seg->ready.store(1, std::memory_order_release);
  return std::unique_ptr<ShmTransport>(
      new ShmTransport(name, seg, map_bytes, /*creator=*/true));
}

std::unique_ptr<ShmTransport> ShmTransport::Open(const std::string& name,
                                                 int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    int fd = shm_open(name.c_str(), O_RDWR, 0600);
    if (fd >= 0) {
      struct stat st;
      if (fstat(fd, &st) == 0 &&
          st.st_size >= static_cast<off_t>(sizeof(Segment))) {
        size_t map_bytes = static_cast<size_t>(st.st_size);
        void* mem = mmap(nullptr, map_bytes, PROT_READ | PROT_WRITE,
                         MAP_SHARED, fd, 0);
        close(fd);
        if (mem == MAP_FAILED) return nullptr;
        auto* seg = static_cast<Segment*>(mem);
        while (!(seg->magic == kMagic &&
                 seg->ready.load(std::memory_order_acquire) == 1)) {
          if (std::chrono::steady_clock::now() >= deadline) {
            munmap(mem, map_bytes);
            return nullptr;
          }
          std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
        if (sizeof(Segment) + 2 * seg->ring_bytes > map_bytes) {
          munmap(mem, map_bytes);
          return nullptr;
        }
        return std::unique_ptr<ShmTransport>(
            new ShmTransport(name, seg, map_bytes, /*creator=*/false));
      }
      close(fd);
    }
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

ShmTransport::~ShmTransport() {
  if (seg_ != nullptr) {
    Abort();  // release any peer still blocked on our rings
    if (creator_) Unlink();
    munmap(seg_, map_bytes_);
    seg_ = nullptr;
  }
}

void ShmTransport::Abort() {
  if (seg_ == nullptr) return;
  seg_->aborted.store(1, std::memory_order_release);
  for (ShmRing& r : seg_->rings) {
    r.head_seq.fetch_add(1);  // seq_cst: futex doorbell, pairs with waiters' seq_cst re-check
    r.tail_seq.fetch_add(1);
    FutexWake(&r.head_seq);
    FutexWake(&r.tail_seq);
  }
}

void ShmTransport::Unlink() {
  if (!unlinked_) {
    unlinked_ = true;
    shm_unlink(name_.c_str());  // ENOENT is fine (already gone)
  }
}

bool ShmTransport::ApplyNumaPolicy(ShmNumaMode mode) {
  if (mode == ShmNumaMode::OFF || seg_ == nullptr) return false;
  if (mode == ShmNumaMode::AUTO && NumaNodeCount() <= 1) {
    return false;  // single-node host: placement is moot
  }
  unsigned cpu = 0, node = 0;
  if (syscall(SYS_getcpu, &cpu, &node, nullptr) != 0) return false;
  // Consumer-local placement: pin the INBOUND ring's data pages to the node
  // this side runs on. Our reads (the in-place view consumer and TryRecv)
  // go node-local; the peer's producer writes cross the interconnect once,
  // through the store buffer — the cheap direction.
  unsigned long mask[16];
  memset(mask, 0, sizeof(mask));
  const unsigned bits = 8 * sizeof(unsigned long);
  if (node >= 16 * bits) return false;
  mask[node / bits] = 1ul << (node % bits);
  const long page = sysconf(_SC_PAGESIZE);
  if (page <= 0) return false;
  uintptr_t start = reinterpret_cast<uintptr_t>(in_data_);
  uintptr_t end = start + ring_bytes_;
  uintptr_t a_start =
      (start + static_cast<uintptr_t>(page) - 1) &
      ~(static_cast<uintptr_t>(page) - 1);
  uintptr_t a_end = end & ~(static_cast<uintptr_t>(page) - 1);
  if (a_end <= a_start) return false;  // ring smaller than a page
  // MPOL_PREFERRED (never ENOMEMs under pressure, unlike a strict bind) +
  // MF_MOVE to migrate pages the creator's init already first-touched.
  long rc = syscall(SYS_mbind, a_start, a_end - a_start, kMpolPreferred,
                    mask, 16 * bits + 1, kMpolMfMove);
  return rc == 0;
}

int64_t ShmTransport::OccupancyBytes() const {
  if (seg_ == nullptr) return 0;
  int64_t total = 0;
  for (int i = 0; i < 2; ++i) {
    const ShmRing& r = seg_->rings[i];
    const uint64_t head = r.head.load(std::memory_order_relaxed);  // atomic-ok: monitoring snapshot, torn pair tolerated
    const uint64_t tail = r.tail.load(std::memory_order_relaxed);  // atomic-ok: monitoring snapshot, torn pair tolerated
    // Free-running cursors: head >= tail modulo concurrent advance; a
    // transiently inverted read (tail racing past a stale head) clamps to 0
    // rather than wrapping to a huge unsigned spread.
    if (head > tail) total += static_cast<int64_t>(head - tail);
  }
  return total;
}

void ShmTransport::BumpAndWake(std::atomic<uint32_t>* seq) {
  seq->fetch_add(1, std::memory_order_seq_cst);
  FutexWake(seq);
  ++futex_wakes_;
}

void ShmTransport::NotifyHeadAdvance(size_t bytes, bool was_edge) {
  ShmRing& r = seg_->rings[out_ring_];
  if (!coalesce_) {
    // Legacy per-advance doorbell (small ops, HVDTPU_DOORBELL_BATCH=1):
    // the one wake IS the latency path there.
    r.head_seq.fetch_add(1, std::memory_order_seq_cst);
    if (r.head_waiters.load(std::memory_order_seq_cst) != 0) {
      FutexWake(&r.head_seq);
      ++futex_wakes_;
    }
    return;
  }
  // Dekker with the waiter's registration: our head store is already
  // published (release); the fence orders it against the waiter-count load,
  // so either we observe the waiter here or its post-registration re-check
  // observes our head — both-miss is impossible.
  std::atomic_thread_fence(std::memory_order_seq_cst);
  pending_head_bytes_ += bytes;
  const bool waiter =
      r.head_waiters.load(std::memory_order_seq_cst) != 0;
  if (pending_head_bytes_ >= static_cast<size_t>(doorbell_batch_) ||
      (waiter && was_edge)) {
    pending_head_bytes_ = 0;
    if (waiter) BumpAndWake(&r.head_seq);
  }
}

void ShmTransport::NotifyTailAdvance(size_t bytes, bool was_edge) {
  ShmRing& r = seg_->rings[1 - out_ring_];
  if (!coalesce_) {
    r.tail_seq.fetch_add(1, std::memory_order_seq_cst);
    if (r.tail_waiters.load(std::memory_order_seq_cst) != 0) {
      FutexWake(&r.tail_seq);
      ++futex_wakes_;
    }
    return;
  }
  std::atomic_thread_fence(std::memory_order_seq_cst);
  pending_tail_bytes_ += bytes;
  const bool waiter =
      r.tail_waiters.load(std::memory_order_seq_cst) != 0;
  if (pending_tail_bytes_ >= static_cast<size_t>(doorbell_batch_) ||
      (waiter && was_edge)) {
    pending_tail_bytes_ = 0;
    if (waiter) BumpAndWake(&r.tail_seq);
  }
}

void ShmTransport::FlushDoorbells() {
  // Ring every deferred bell: called before this side blocks (only the
  // peer's progress can wake us, so it must not be left sleeping on our
  // debt) and at op boundaries (the last chunks of an op may be under the
  // batch threshold forever).
  if (pending_head_bytes_ > 0) {
    pending_head_bytes_ = 0;
    ShmRing& r = seg_->rings[out_ring_];
    std::atomic_thread_fence(std::memory_order_seq_cst);
    if (r.head_waiters.load(std::memory_order_seq_cst) != 0) {
      BumpAndWake(&r.head_seq);
    }
  }
  if (pending_tail_bytes_ > 0) {
    pending_tail_bytes_ = 0;
    ShmRing& r = seg_->rings[1 - out_ring_];
    std::atomic_thread_fence(std::memory_order_seq_cst);
    if (r.tail_waiters.load(std::memory_order_seq_cst) != 0) {
      BumpAndWake(&r.tail_seq);
    }
  }
}

size_t ShmTransport::TrySend(const uint8_t* buf, size_t len) {
  ShmRing& r = seg_->rings[out_ring_];
  uint64_t head = r.head.load(std::memory_order_relaxed);  // atomic-ok: sole producer reads its own cursor
  uint64_t tail = r.tail.load(std::memory_order_acquire);
  size_t free_space = ring_bytes_ - static_cast<size_t>(head - tail);
  if (free_space == 0) return 0;
  size_t off = static_cast<size_t>(head % ring_bytes_);
  size_t chunk = std::min({free_space, len, ring_bytes_ - off});
  memcpy(out_data_ + off, buf, chunk);
  r.head.store(head + chunk, std::memory_order_release);
  // Empty->data edge: a consumer can only be asleep if it drained the ring
  // dry, so the chunk that refills it must ring through immediately. The
  // freshest tail tells us whether that drain happened. (Only the
  // coalescing path consults it; the legacy path rings every advance.)
  const bool was_edge =
      coalesce_ && r.tail.load(std::memory_order_seq_cst) == head;  // atomic-ok: Dekker edge-check, pairs with waiter's seq_cst window
  NotifyHeadAdvance(chunk, was_edge);
  return chunk;
}

size_t ShmTransport::TryRecv(uint8_t* buf, size_t len) {
  ShmRing& r = seg_->rings[1 - out_ring_];
  uint64_t tail = r.tail.load(std::memory_order_relaxed);  // atomic-ok: sole consumer reads its own cursor
  uint64_t head = r.head.load(std::memory_order_acquire);
  size_t avail = static_cast<size_t>(head - tail);
  if (avail == 0) return 0;
  size_t off = static_cast<size_t>(tail % ring_bytes_);
  size_t chunk = std::min({avail, len, ring_bytes_ - off});
  memcpy(buf, in_data_ + off, chunk);
  r.tail.store(tail + chunk, std::memory_order_release);
  // Full->space edge: a producer sleeps only against a completely full
  // ring; the drain that opens space must wake it at once.
  const bool was_edge = avail == ring_bytes_;
  NotifyTailAdvance(chunk, was_edge);
  return chunk;
}

size_t ShmTransport::TryConsumeViews(size_t done, size_t len,
                                     size_t view_align,
                                     const SegmentFn& on_segment) {
  ShmRing& r = seg_->rings[1 - out_ring_];
  uint64_t tail = r.tail.load(std::memory_order_relaxed);  // atomic-ok: sole consumer reads its own cursor
  uint64_t head = r.head.load(std::memory_order_acquire);
  size_t avail = static_cast<size_t>(head - tail);
  if (avail == 0) return 0;
  const size_t remaining = len - done;
  size_t align = view_align == 0 ? 1 : view_align;
  if (align > remaining) align = remaining;  // ragged caller tail
  if (align > 16) align = 1;  // staging buffer bound; dtypes are <= 8 bytes
  size_t off = static_cast<size_t>(tail % ring_bytes_);
  size_t run = std::min({avail, remaining, ring_bytes_ - off});
  size_t aligned = run - run % align;
  const bool was_edge = avail == ring_bytes_;
  if (aligned == 0) {
    // The next element straddles the wrap point (run < align while the ring
    // holds >= align bytes) or hasn't fully arrived yet. Assemble exactly
    // one element through a staging buffer once its bytes are all in; the
    // view contract (elem-aligned lengths) holds either way.
    if (avail < align) return 0;  // element incomplete: wait for more bytes
    alignas(16) uint8_t stage[16];
    const size_t first = ring_bytes_ - off;  // bytes before the wrap point
    memcpy(stage, in_data_ + off, first);
    memcpy(stage + first, in_data_, align - first);
    on_segment(stage, done, align);
    r.tail.store(tail + align, std::memory_order_release);
    NotifyTailAdvance(align, was_edge);
    return align;
  }
  const uint8_t* src = in_data_ + off;
  if (align > 1 && reinterpret_cast<uintptr_t>(src) % align != 0) {
    // An earlier odd-sized op (bool/uint8 payload, compressed wire bytes)
    // left the ring cursor off the element grid, so EVERY in-place view of
    // this op would hand the typed reducer a misaligned element — UB the
    // UBSan gate rightly aborts on. Degrade to the pre-PR-9 behavior for
    // this op: bounce the run through a bounded aligned buffer (one
    // staging copy, exactly the old cost; the aligned common case keeps
    // the zero-copy path).
    constexpr size_t kBounceCap = 256 * 1024;
    if (bounce_.empty()) bounce_.resize(kBounceCap);
    size_t n = std::min(aligned, bounce_.size());
    memcpy(bounce_.data(), src, n);
    on_segment(bounce_.data(), done, n);
    r.tail.store(tail + n, std::memory_order_release);
    NotifyTailAdvance(n, was_edge);
    return n;
  }
  // Zero-copy consumption: the callback reads straight out of the mapped
  // ring; the tail advances only afterwards, so the producer cannot reuse
  // the region mid-view. This removes the staging memcpy entirely — the
  // reduction becomes the only read of the incoming bytes.
  on_segment(src, done, aligned);
  r.tail.store(tail + aligned, std::memory_order_release);
  NotifyTailAdvance(aligned, was_edge);
  return aligned;
}

bool ShmTransport::PeerDead() {
  if (liveness_fd_ < 0) return false;
  pollfd pfd{liveness_fd_, POLLIN, 0};
  if (poll(&pfd, 1, 0) <= 0) return false;
  if ((pfd.revents & (POLLERR | POLLHUP | POLLNVAL)) == 0) {
    // POLLIN on an idle pair socket: EOF or stray bytes — peek to decide.
    char b;
    ssize_t n = recv(liveness_fd_, &b, 1, MSG_PEEK | MSG_DONTWAIT);
    if (n > 0 || (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))) {
      return false;
    }
  }
  peer_died_ = true;
  if (ctl_ != nullptr) ctl_->MarkPeerFailed();  // break the WHOLE plane
  Abort();  // wake our own other-direction waiters too
  return true;
}

bool ShmTransport::AbortedNow() const {
  return seg_->aborted.load(std::memory_order_acquire) != 0 ||
         (ctl_ != nullptr && ctl_->is_aborted());
}

int ShmTransport::WaitSliceMs() const {
  if (ctl_ == nullptr) return kWaitSliceMs;
  int64_t s = ctl_->detect_slice_ms;
  return static_cast<int>(s < 1 ? 1 : (s > kWaitSliceMs ? kWaitSliceMs : s));
}

bool ShmTransport::DeadlineExpired(double last_progress) {
  if (ctl_ == nullptr || ctl_->read_deadline_secs <= 0) return false;
  if (MonoSeconds() - last_progress <= ctl_->read_deadline_secs) return false;
  // Peer alive (no EOF on the liveness socket) but the ring hasn't moved
  // past the deadline: a hung peer. Fail the plane instead of waiting out
  // the coordinator's (possibly never-running) stall inspector.
  peer_died_ = true;
  ctl_->MarkPeerFailed();
  Abort();
  return true;
}

void ShmTransport::WaitOutboundSpace() {
  // Sampling-profiler phase tag: spin or futex-park, this whole function is
  // blocked-on-peer time (the WAIT bucket the perf attribution measures).
  ProfPhaseScope prof_wait(PerfPhase::WAIT);
  ShmRing& r = seg_->rings[out_ring_];
  uint64_t head = r.head.load(std::memory_order_relaxed);  // atomic-ok: sole producer reads its own cursor
  for (int i = 0, spins = SpinIters(); i < spins; ++i) {
    if (r.tail.load(std::memory_order_acquire) + ring_bytes_ != head ||
        AbortedNow()) {
      return;
    }
  }
  if (PeerDead()) return;
  uint32_t seq = r.tail_seq.load(std::memory_order_seq_cst);
  r.tail_waiters.fetch_add(1, std::memory_order_seq_cst);
  if (r.tail.load(std::memory_order_seq_cst) + ring_bytes_ == head &&  // atomic-ok: Dekker re-check between waiter-count bump and futex park
      !AbortedNow()) {
    // Peer-wait accounting (tracing layer): time parked on the futex is
    // time the op stalled on the consumer, not ring bandwidth.
    const double wait_t0 = MonoSeconds();
    FutexWait(&r.tail_seq, seq, WaitSliceMs());
    if (ctl_ != nullptr) {
      ctl_->AddWaitUs(static_cast<int64_t>((MonoSeconds() - wait_t0) * 1e6));
    }
  }
  r.tail_waiters.fetch_sub(1, std::memory_order_seq_cst);
}

void ShmTransport::WaitInboundData() {
  ProfPhaseScope prof_wait(PerfPhase::WAIT);
  ShmRing& r = seg_->rings[1 - out_ring_];
  // Wait for the head to move past its CURRENT position (not merely past
  // the tail): the in-place view consumer can be blocked on the back half
  // of a wrap-straddled element while the ring is technically non-empty.
  uint64_t observed = r.head.load(std::memory_order_acquire);
  uint64_t tail = r.tail.load(std::memory_order_relaxed);  // atomic-ok: sole consumer reads its own cursor
  if (observed != tail) {
    // Bytes are available; only a partial element can be waiting. The
    // producer is mid-write — spin briefly, skip the futex (its next
    // store lands in a bounded number of its own steps).
    for (int i = 0, spins = SpinIters(); i < spins; ++i) {
      if (r.head.load(std::memory_order_acquire) != observed ||
          AbortedNow()) {
        return;
      }
    }
    if (PeerDead()) return;
    std::this_thread::yield();
    return;
  }
  for (int i = 0, spins = SpinIters(); i < spins; ++i) {
    if (r.head.load(std::memory_order_acquire) != observed ||
        AbortedNow()) {
      return;
    }
  }
  if (PeerDead()) return;
  uint32_t seq = r.head_seq.load(std::memory_order_seq_cst);
  r.head_waiters.fetch_add(1, std::memory_order_seq_cst);
  if (r.head.load(std::memory_order_seq_cst) == observed &&  // atomic-ok: Dekker re-check between waiter-count bump and futex park
      !AbortedNow()) {
    // Peer-wait accounting (tracing layer): parked waiting for the
    // producer to publish bytes — the shm analog of a blocked recv().
    const double wait_t0 = MonoSeconds();
    FutexWait(&r.head_seq, seq, WaitSliceMs());
    if (ctl_ != nullptr) {
      ctl_->AddWaitUs(static_cast<int64_t>((MonoSeconds() - wait_t0) * 1e6));
    }
  }
  r.head_waiters.fetch_sub(1, std::memory_order_seq_cst);
}

int ShmTransport::Send(const void* buf, size_t len) {
  BeginOp(len);
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  size_t done = 0;
  double last_progress = MonoSeconds();
  while (done < len) {
    if (AbortedNow()) {
      FlushDoorbells();
      return -1;
    }
    size_t n = TrySend(p + done, len - done);
    if (n == 0) {
      if (DeadlineExpired(last_progress)) {
        FlushDoorbells();
        return -1;
      }
      FlushDoorbells();  // our wake depends on the peer: pay the debt first
      WaitOutboundSpace();
    } else {
      done += n;
      last_progress = MonoSeconds();
    }
  }
  FlushDoorbells();
  return 0;
}

int ShmTransport::Recv(void* buf, size_t len) {
  return RecvSegmented(buf, len, 0, 1, nullptr);
}

int ShmTransport::RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                                size_t view_align,
                                const SegmentFn& on_segment) {
  (void)segment_bytes;  // views are ring-run-granular, not segment-sized
  BeginOp(len);
  uint8_t* p = static_cast<uint8_t*>(buf);
  size_t done = 0;
  double last_progress = MonoSeconds();
  while (done < len) {
    if (AbortedNow()) {
      FlushDoorbells();
      return -1;
    }
    size_t n = on_segment
                   ? TryConsumeViews(done, len, view_align, on_segment)
                   : TryRecv(p + done, len - done);
    if (n == 0) {
      if (DeadlineExpired(last_progress)) {
        FlushDoorbells();
        return -1;
      }
      FlushDoorbells();
      WaitInboundData();
      continue;
    }
    done += n;
    last_progress = MonoSeconds();
  }
  FlushDoorbells();
  return 0;
}

int ShmTransport::DuplexPump(ShmTransport* tx, const void* send_buf,
                             size_t send_bytes, ShmTransport* rx,
                             void* recv_buf, size_t recv_bytes,
                             size_t view_align, const SegmentFn& on_segment) {
  tx->BeginOp(send_bytes);
  rx->BeginOp(recv_bytes);
  const uint8_t* sp = static_cast<const uint8_t*>(send_buf);
  uint8_t* rp = static_cast<uint8_t*>(recv_buf);
  size_t sent = 0, rcvd = 0;
  double last_progress = MonoSeconds();
  while (sent < send_bytes || rcvd < recv_bytes) {
    if (tx->AbortedNow() || rx->AbortedNow()) {
      tx->FlushDoorbells();
      rx->FlushDoorbells();
      return -1;
    }
    bool progress = false;
    if (sent < send_bytes) {
      size_t n = tx->TrySend(sp + sent, send_bytes - sent);
      sent += n;
      progress |= n != 0;
    }
    if (rcvd < recv_bytes) {
      size_t n = on_segment ? rx->TryConsumeViews(rcvd, recv_bytes,
                                                  view_align, on_segment)
                            : rx->TryRecv(rp + rcvd, recv_bytes - rcvd);
      rcvd += n;
      progress |= n != 0;
    }
    if (!progress) {
      // The lane we are about to park on is the one whose peer owes us
      // progress — charge the no-progress deadline (and therefore the
      // failure attribution) to IT, not to its healthy sibling.
      ShmTransport* gate = rcvd < recv_bytes ? rx : tx;
      if (gate->DeadlineExpired(last_progress)) {
        tx->FlushDoorbells();
        rx->FlushDoorbells();
        return -1;
      }
      // Pay both lanes' doorbell debts (our wake depends on two different
      // peers now), then park on whichever cursor unblocks us. The ring
      // schedule is matched hop-by-hop, so inbound data and outbound
      // space open together; the futex timeout slice bounds any stagger.
      tx->FlushDoorbells();
      rx->FlushDoorbells();
      if (rcvd < recv_bytes) {
        rx->WaitInboundData();
      } else {
        tx->WaitOutboundSpace();
      }
    } else {
      last_progress = MonoSeconds();
    }
  }
  tx->FlushDoorbells();
  rx->FlushDoorbells();
  return 0;
}

int ShmTransport::SendRecv(const void* send_buf, size_t send_bytes,
                           void* recv_buf, size_t recv_bytes,
                           size_t segment_bytes, size_t view_align,
                           const SegmentFn& on_segment) {
  (void)segment_bytes;  // views are ring-run-granular, not segment-sized
  BeginOp(send_bytes > recv_bytes ? send_bytes : recv_bytes);
  const uint8_t* sp = static_cast<const uint8_t*>(send_buf);
  uint8_t* rp = static_cast<uint8_t*>(recv_buf);
  size_t sent = 0, rcvd = 0;
  double last_progress = MonoSeconds();
  while (sent < send_bytes || rcvd < recv_bytes) {
    if (AbortedNow()) {
      FlushDoorbells();
      return -1;
    }
    bool progress = false;
    if (sent < send_bytes) {
      size_t n = TrySend(sp + sent, send_bytes - sent);
      sent += n;
      progress |= n != 0;
    }
    if (rcvd < recv_bytes) {
      size_t n =
          on_segment
              ? TryConsumeViews(rcvd, recv_bytes, view_align, on_segment)
              : TryRecv(rp + rcvd, recv_bytes - rcvd);
      rcvd += n;
      progress |= n != 0;
    }
    if (!progress) {
      if (DeadlineExpired(last_progress)) {
        FlushDoorbells();
        return -1;
      }
      // Both directions stuck: pay any deferred doorbells (the peer's
      // progress is our only wake source), then park on whichever cursor
      // unblocks us (inbound data if we still expect bytes, else outbound
      // space). The peer's pump advances the other direction independently.
      FlushDoorbells();
      if (rcvd < recv_bytes) {
        WaitInboundData();
      } else {
        WaitOutboundSpace();
      }
    } else {
      last_progress = MonoSeconds();
    }
  }
  FlushDoorbells();
  return 0;
}

}  // namespace hvdtpu
