// POSIX shared-memory transport: per-pair shm_open+mmap ring buffers with
// futex doorbells, used automatically for ranks sharing a host.
//
// One segment per rank pair, created by the lower rank, holding two
// single-producer/single-consumer byte rings (lower->higher and
// higher->lower). Each ring is lock-free within the segment: the producer
// owns the head cursor, the consumer the tail, and cross-process wakeups
// ride shared (non-private) futex words with a timeout fallback so a lost
// wake can only cost milliseconds, never a hang. Abort (worker shutdown or
// world break) flips a shared flag and wakes both sides; every blocked ring
// op observes it and fails over instead of spinning on a dead peer.
//
// Zero-copy receive (PR 9): segmented receives with a callback consume the
// payload IN PLACE — the callback gets views straight into the mapped ring
// (elem-aligned, wrap handled), the staging memcpy out of the ring is gone,
// and the tail advances only after the view is consumed. Doorbells are
// batched: wakes coalesce while the peer is demonstrably running
// (HVDTPU_DOORBELL_BATCH), with an immediate wake on every empty->data /
// full->space transition so a sleeping peer never waits past one chunk.
// Rings can be NUMA-pinned (HVDTPU_SHM_NUMA): each side binds its INBOUND
// ring's pages to its own node — reads local, the peer's writes ride the
// store buffer — probed via /sys/devices/system/node, no-op single-node.
//
// Reference analog: the fork's CUDA-IPC shared-memory communicator
// (horovod/common/ops/compressed/ SHM path) — here host memory instead of
// device memory, POSIX shm instead of cudaIpc handles.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "transport.h"

#include "thread_roles.h"

namespace hvdtpu {

// Per-direction ring capacity. Must absorb one full in-flight collective
// chunk for the inline (no sender thread) fast path to engage; tunable via
// HVDTPU_SHM_RING_BYTES.
constexpr int64_t kDefaultShmRingBytes = 1 << 20;

// Default futex-doorbell coalescing window (bytes moved per wake while the
// peer has a waiter registered but data/space keeps flowing). 1 = ring the
// bell on every cursor advance (the pre-batching behavior).
constexpr int64_t kDefaultDoorbellBatchBytes = 256 * 1024;

// NUMA placement mode for the shm rings (HVDTPU_SHM_NUMA; mirrored by
// envvars.SHM_NUMA_MODES — scripts/check_invariants.py ENUM-MIRROR).
// AUTO pins when the host has >1 node, ON attempts the mbind regardless,
// OFF never touches placement.
enum class ShmNumaMode : int32_t {
  AUTO = 0,
  ON = 1,
  OFF = 2,
};

// Nodes under `sysfs_dir` (/sys/devices/system/node): the NUMA probe.
// Returns 1 when the directory is absent/unreadable (treat as single-node).
int NumaNodeCount(const std::string& sysfs_dir = "/sys/devices/system/node");

// Concurrency contract (see common.h's TSA layer; this type is mutex-free on
// purpose): each ring is strict SPSC across two PROCESSES — the producer
// side owns the head cursor, the consumer the tail, both published with
// acquire/release atomics in the mapped segment; futex words handle
// cross-process wakeups. Within a process, a ShmTransport is driven by the
// core's background loop only (the same single-driver rule as DataPlane),
// except Abort()/abort flag reads, which are async-signal-style atomics any
// thread may touch during shutdown.
class ShmTransport : public Transport {
 public:
  // Creator (lower rank) allocates and initializes the segment; the opener
  // maps it. `name` must match on both sides and be unique per pair per job
  // (DataPlane derives it from the pair's data-plane ports). Both return
  // null on failure — the caller falls back to TCP after the socket
  // handshake confirms the peer agrees.
  HVDTPU_CALLED_ON(background)
  static std::unique_ptr<ShmTransport> Create(const std::string& name,
                                              size_t ring_bytes);
  HVDTPU_CALLED_ON(background)
  static std::unique_ptr<ShmTransport> Open(const std::string& name,
                                            int timeout_ms);
  ~ShmTransport() override;

  HVDTPU_CALLED_ON(any)
  const char* kind() const override { return "shm"; }
  HVDTPU_CALLED_ON(background)
  int Send(const void* buf, size_t len) override;
  HVDTPU_CALLED_ON(background)
  int Recv(void* buf, size_t len) override;
  // Zero-copy when on_segment is set: the payload is consumed IN PLACE via
  // ring views (elem-aligned per view_align; buf is untouched scratch).
  // Without a callback, bytes land in buf as before.
  HVDTPU_CALLED_ON(background)
  int RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                    size_t view_align, const SegmentFn& on_segment) override;
  // Interleaved full-duplex pump on the calling thread: no extra thread —
  // writes whatever fits the outbound ring, drains the inbound ring, and
  // fires segment callbacks as contiguous (aligned) runs complete — in
  // place, like RecvSegmented. The peer's concurrent pump guarantees both
  // directions advance.
  HVDTPU_CALLED_ON(background)
  int SendRecv(const void* send_buf, size_t send_bytes, void* recv_buf,
               size_t recv_bytes, size_t segment_bytes, size_t view_align,
               const SegmentFn& on_segment) override;
  // The data-plane algorithms exchange matched messages (every byte sent in
  // a step is consumed in the same step), so the ring is drained at each
  // step boundary and a payload no larger than the ring can never block.
  HVDTPU_CALLED_ON(background)
  bool InlineSendSafe(size_t bytes) const override {
    return bytes <= ring_bytes_;
  }

  // Mark the segment aborted and wake both sides; any blocked ring op
  // (either process) returns -1. Called from DataPlane::Shutdown so a dying
  // rank releases its same-host peers.
  HVDTPU_CALLED_ON(any)
  void Abort() override;
  // Peer-liveness probe: a SIGKILLed peer can never flip the abort flag, so
  // the wait loops also watch this (otherwise idle) socket to the peer and
  // abort on EOF — checked every wait slice, so a killed peer wakes a
  // blocked waiter within one slice. Optional; without it a dead peer
  // blocks until the caller tears the plane down.
  HVDTPU_CALLED_ON(background)
  void set_liveness_fd(int fd) { liveness_fd_ = fd; }
  // Shared fault-detection block (socket_util.h IoControl): wait slices
  // shrink to its detect_slice_ms, a plane-wide abort breaks blocked ring
  // ops within one slice, peer death (liveness EOF) marks the whole plane
  // failed, and read_deadline_secs bounds a zero-progress op against a
  // hung-but-alive peer. Optional (standalone/unit-test use keeps the
  // segment-local abort flag only).
  HVDTPU_CALLED_ON(background)
  void set_control(IoControl* ctl) { ctl_ = ctl; }
  // Futex-doorbell coalescing window in bytes (HVDTPU_DOORBELL_BATCH):
  // 0 = kDefaultDoorbellBatchBytes, 1 = ring on every advance (legacy).
  // Coalescing is ADAPTIVE per op: it engages only when the op moves at
  // least one window's worth of bytes (sustained streaming, where wake
  // syscalls amortize away); smaller ops keep the legacy per-advance
  // protocol — their one wake IS the latency path, measured slower under
  // coalescing on a contended box. Set before traffic (Connect time);
  // each side tunes its own bells.
  HVDTPU_CALLED_ON(background)
  void set_doorbell_batch(int64_t bytes) {
    doorbell_batch_ = bytes <= 0 ? kDefaultDoorbellBatchBytes : bytes;
  }
  // Bind this side's inbound ring pages to the local NUMA node
  // (HVDTPU_SHM_NUMA; mbind(MPOL_PREFERRED, MF_MOVE), page-rounded).
  // Returns true when a binding was applied; false = probed no-op
  // (single-node host, mode OFF, or the syscall is unavailable).
  HVDTPU_CALLED_ON(background)
  bool ApplyNumaPolicy(ShmNumaMode mode);
  // Drop the name from the shm namespace (creator side, once the opener
  // confirmed attach over the socket handshake): an abnormal death after
  // this point leaks nothing. Idempotent.
  HVDTPU_CALLED_ON(background)
  void Unlink();

  HVDTPU_CALLED_ON(any)
  size_t ring_bytes() const { return ring_bytes_; }
  // Bytes sitting in the segment's two rings right now (producer head minus
  // consumer tail, both directions) — the per-lane occupancy gauge. Any
  // thread (the cursors are cross-process atomics already).
  HVDTPU_CALLED_ON(any)
  int64_t OccupancyBytes() const override;
  // Futex wake syscalls this side has issued (doorbell-batching tests).
  HVDTPU_CALLED_ON(any)
  int64_t futex_wakes() const { return futex_wakes_; }
  // True once THIS lane's liveness probe saw the peer die (EOF) or its
  // no-progress deadline expired — failure ATTRIBUTION for exchanges that
  // span two lanes (DataPlane::Exchange's DuplexPump path must not blame
  // the healthy neighbor; the plane-wide IoControl flags cannot say which
  // lane tripped first).
  HVDTPU_CALLED_ON(any)
  bool peer_died() const { return peer_died_; }

  // Single-threaded duplex pump across TWO shm lanes (ring-neighbor
  // exchanges: send to `tx`'s peer while receiving from `rx`'s peer) — the
  // two-peer analog of SendRecv's same-peer pump. Replaces the
  // sender-thread-per-hop pattern for all-shm ring steps: no thread
  // create/join, no cross-thread scheduling churn, and the receive side
  // consumes in place (view semantics, like RecvSegmented). Both lanes
  // must be driven by the calling thread (the usual single-driver rule).
  HVDTPU_CALLED_ON(background)
  static int DuplexPump(ShmTransport* tx, const void* send_buf,
                        size_t send_bytes, ShmTransport* rx, void* recv_buf,
                        size_t recv_bytes, size_t view_align,
                        const SegmentFn& on_segment);

 private:
  struct Segment;  // shared-memory layout (shm_transport.cpp)
  struct ShmRingRef;

  ShmTransport(std::string name, Segment* seg, size_t map_bytes,
               bool creator);

  // Arm/disarm doorbell coalescing for the op moving `op_bytes` (see
  // set_doorbell_batch); called at every public-op entry.
  void BeginOp(size_t op_bytes) {
    coalesce_ = doorbell_batch_ > 1 &&
                op_bytes >= static_cast<size_t>(doorbell_batch_);
  }
  // One bounded copy attempt (never blocks); returns bytes moved.
  size_t TrySend(const uint8_t* buf, size_t len);
  size_t TryRecv(uint8_t* buf, size_t len);
  // One bounded IN-PLACE consume attempt: fires on_segment with up to one
  // aligned contiguous ring view (staging only for a wrap-straddled
  // element), advances the tail past it. `done` is the op's running offset
  // (callback offset + alignment bookkeeping); returns bytes consumed.
  size_t TryConsumeViews(size_t done, size_t len, size_t view_align,
                         const SegmentFn& on_segment);
  // Doorbell plumbing: called after a cursor advance. `was_edge` = the ring
  // crossed an empty->data (head) or full->space (tail) transition, which
  // always rings immediately — a sleeping peer can only be waiting on an
  // edge. Otherwise wakes coalesce until doorbell_batch_ bytes accumulated.
  void NotifyHeadAdvance(size_t bytes, bool was_edge);
  void NotifyTailAdvance(size_t bytes, bool was_edge);
  // The bell itself: bump the futex word and wake (counted). The caller
  // owns the Dekker ordering (seq_cst fence/RMW before the waiter check).
  void BumpAndWake(std::atomic<uint32_t>* seq);
  // Ring any deferred doorbells NOW: before this side blocks (the peer must
  // make progress for us to ever wake) and at every op boundary.
  void FlushDoorbells();
  // Park until the peer moves the given cursor or the deadline/abort hits.
  void WaitOutboundSpace();
  void WaitInboundData();
  // True (and segment aborted) when the liveness socket reports EOF.
  bool PeerDead();
  // True when any abort source fired (segment flag or plane-wide control).
  bool AbortedNow() const;
  // Wait slice in ms (control's detect slice, else the built-in default).
  int WaitSliceMs() const;
  // No-progress deadline check for a blocked op; marks the peer failed and
  // aborts the segment when breached. `last_progress` is a monotonic-seconds
  // stamp of the op's latest byte movement.
  bool DeadlineExpired(double last_progress);

  std::string name_;
  Segment* seg_ = nullptr;
  size_t map_bytes_ = 0;
  size_t ring_bytes_ = 0;
  bool creator_ = false;
  bool unlinked_ = false;
  int liveness_fd_ = -1;
  bool peer_died_ = false;
  IoControl* ctl_ = nullptr;
  int out_ring_ = 0;  // rings[out_ring_] is my producer side
  uint8_t* out_data_ = nullptr;
  uint8_t* in_data_ = nullptr;
  int64_t doorbell_batch_ = kDefaultDoorbellBatchBytes;
  bool coalesce_ = false;  // current op streams enough to batch the bells
  // Wake debt owed to a registered peer waiter while coalescing (bytes
  // advanced since the last bell, per direction). Driver-thread-only.
  size_t pending_head_bytes_ = 0;
  size_t pending_tail_bytes_ = 0;
  int64_t futex_wakes_ = 0;
  // Aligned bounce for in-place views whose ring offset an earlier
  // odd-sized op knocked off the element grid (TryConsumeViews); lazily
  // allocated, bounded.
  std::vector<uint8_t> bounce_;
};

}  // namespace hvdtpu
