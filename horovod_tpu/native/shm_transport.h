// POSIX shared-memory transport: per-pair shm_open+mmap ring buffers with
// futex doorbells, used automatically for ranks sharing a host.
//
// One segment per rank pair, created by the lower rank, holding two
// single-producer/single-consumer byte rings (lower->higher and
// higher->lower). Each ring is lock-free within the segment: the producer
// owns the head cursor, the consumer the tail, and cross-process wakeups
// ride shared (non-private) futex words with a timeout fallback so a lost
// wake can only cost milliseconds, never a hang. Abort (worker shutdown or
// world break) flips a shared flag and wakes both sides; every blocked ring
// op observes it and fails over instead of spinning on a dead peer.
//
// Reference analog: the fork's CUDA-IPC shared-memory communicator
// (horovod/common/ops/compressed/ SHM path) — here host memory instead of
// device memory, POSIX shm instead of cudaIpc handles.
#pragma once

#include <cstdint>
#include <memory>
#include <string>

#include "transport.h"

namespace hvdtpu {

// Per-direction ring capacity. Must absorb one full in-flight collective
// chunk for the inline (no sender thread) fast path to engage; tunable via
// HVDTPU_SHM_RING_BYTES.
constexpr int64_t kDefaultShmRingBytes = 1 << 20;

// Concurrency contract (see common.h's TSA layer; this type is mutex-free on
// purpose): each ring is strict SPSC across two PROCESSES — the producer
// side owns the head cursor, the consumer the tail, both published with
// acquire/release atomics in the mapped segment; futex words handle
// cross-process wakeups. Within a process, a ShmTransport is driven by the
// core's background loop only (the same single-driver rule as DataPlane),
// except Abort()/abort flag reads, which are async-signal-style atomics any
// thread may touch during shutdown.
class ShmTransport : public Transport {
 public:
  // Creator (lower rank) allocates and initializes the segment; the opener
  // maps it. `name` must match on both sides and be unique per pair per job
  // (DataPlane derives it from the pair's data-plane ports). Both return
  // null on failure — the caller falls back to TCP after the socket
  // handshake confirms the peer agrees.
  static std::unique_ptr<ShmTransport> Create(const std::string& name,
                                              size_t ring_bytes);
  static std::unique_ptr<ShmTransport> Open(const std::string& name,
                                            int timeout_ms);
  ~ShmTransport() override;

  const char* kind() const override { return "shm"; }
  int Send(const void* buf, size_t len) override;
  int Recv(void* buf, size_t len) override;
  int RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                    const SegmentFn& on_segment) override;
  // Interleaved full-duplex pump on the calling thread: no extra thread —
  // writes whatever fits the outbound ring, drains the inbound ring, and
  // fires segment callbacks as contiguous prefixes complete. The peer's
  // concurrent pump guarantees both directions advance.
  int SendRecv(const void* send_buf, size_t send_bytes, void* recv_buf,
               size_t recv_bytes, size_t segment_bytes,
               const SegmentFn& on_segment) override;
  // The data-plane algorithms exchange matched messages (every byte sent in
  // a step is consumed in the same step), so the ring is drained at each
  // step boundary and a payload no larger than the ring can never block.
  bool InlineSendSafe(size_t bytes) const override {
    return bytes <= ring_bytes_;
  }

  // Mark the segment aborted and wake both sides; any blocked ring op
  // (either process) returns -1. Called from DataPlane::Shutdown so a dying
  // rank releases its same-host peers.
  void Abort() override;
  // Peer-liveness probe: a SIGKILLed peer can never flip the abort flag, so
  // the wait loops also watch this (otherwise idle) socket to the peer and
  // abort on EOF — checked every wait slice, so a killed peer wakes a
  // blocked waiter within one slice. Optional; without it a dead peer
  // blocks until the caller tears the plane down.
  void set_liveness_fd(int fd) { liveness_fd_ = fd; }
  // Shared fault-detection block (socket_util.h IoControl): wait slices
  // shrink to its detect_slice_ms, a plane-wide abort breaks blocked ring
  // ops within one slice, peer death (liveness EOF) marks the whole plane
  // failed, and read_deadline_secs bounds a zero-progress op against a
  // hung-but-alive peer. Optional (standalone/unit-test use keeps the
  // segment-local abort flag only).
  void set_control(IoControl* ctl) { ctl_ = ctl; }
  // Drop the name from the shm namespace (creator side, once the opener
  // confirmed attach over the socket handshake): an abnormal death after
  // this point leaks nothing. Idempotent.
  void Unlink();

  size_t ring_bytes() const { return ring_bytes_; }

 private:
  struct Segment;  // shared-memory layout (shm_transport.cpp)

  ShmTransport(std::string name, Segment* seg, size_t map_bytes,
               bool creator);

  // One bounded copy attempt (never blocks); returns bytes moved.
  size_t TrySend(const uint8_t* buf, size_t len);
  size_t TryRecv(uint8_t* buf, size_t len);
  // Park until the peer moves the given cursor or the deadline/abort hits.
  void WaitOutboundSpace();
  void WaitInboundData();
  // True (and segment aborted) when the liveness socket reports EOF.
  bool PeerDead();
  // True when any abort source fired (segment flag or plane-wide control).
  bool AbortedNow() const;
  // Wait slice in ms (control's detect slice, else the built-in default).
  int WaitSliceMs() const;
  // No-progress deadline check for a blocked op; marks the peer failed and
  // aborts the segment when breached. `last_progress` is a monotonic-seconds
  // stamp of the op's latest byte movement.
  bool DeadlineExpired(double last_progress);

  std::string name_;
  Segment* seg_ = nullptr;
  size_t map_bytes_ = 0;
  size_t ring_bytes_ = 0;
  bool creator_ = false;
  bool unlinked_ = false;
  int liveness_fd_ = -1;
  IoControl* ctl_ = nullptr;
  int out_ring_ = 0;  // rings[out_ring_] is my producer side
  uint8_t* out_data_ = nullptr;
  uint8_t* in_data_ = nullptr;
};

}  // namespace hvdtpu
