// Always-on in-memory flight recorder (docs/fault-tolerance.md
// "Post-mortem debugging").
//
// A fixed-size per-rank ring of compact binary phase records — op begin/end,
// per-hop send/recv with peer+bytes+lane, reduce, quantize, fusion-wait,
// failure-detect and stall events — written unconditionally on the
// collective path (unlike the sampled JSON tracing layer, docs/tracing.md:
// a record is five relaxed atomic stores, no strings, no allocation, so the
// steady-state cost stays inside the <2% observability budget at
// every-op granularity). The ring is dumped to `flightrec.<rank>.bin`:
//
//   * on the abort cascade (Core::FailAllOutstanding),
//   * on stall escalation (Core::CheckStalls shutdown),
//   * on a fatal signal (SIGSEGV/SIGBUS/SIGABRT/SIGTERM handlers installed
//     by the core; the dump path uses only async-signal-safe syscalls),
//   * on demand (hvdtpu_flightrec_dump C API / the /debugz endpoint's
//     hvdtpu_flightrec_snapshot).
//
// The dump header carries the PR-8 clock offset ± error vs rank 0 plus a
// steady/wall anchor pair, so scripts/postmortem.py can merge surviving
// ranks' rings onto one global time axis with the same alignment machinery
// the distributed tracer uses. horovod_tpu/flightrec.py is the decoder;
// the FlightEvent / DumpReason values below are mirrored there and held in
// sync by scripts/check_invariants.py (ENUM-MIRROR).
//
// No reference analog: the reference's only post-hoc artifact is the
// optional timeline, which is off by default and gone with the process.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>

#include "thread_roles.h"

namespace hvdtpu {

// Record type tags. Mirrored in horovod_tpu/flightrec.py FLIGHT_EVENTS
// (scripts/check_invariants.py ENUM-MIRROR).
enum class FlightEvent : int32_t {
  NONE = 0,
  OP_BEGIN = 1,      // collective dispatched (name, op code in arg, bytes)
  OP_END = 2,        // collective finished (arg: 0 ok / 1 error)
  SEND = 3,          // one-directional hop (send_peer, bytes, wait split)
  RECV = 4,
  SENDRECV = 5,      // paired exchange (both peers, combined bytes)
  REDUCE = 6,        // reduction phase (busy time in dur)
  QUANTIZE = 7,      // wire-compression encode
  DEQUANTIZE = 8,    // wire-compression decode
  FUSION_WAIT = 9,   // tensor's enqueue -> batch-execution wait
  FAIL_DETECT = 10,  // lane failure pinned on a peer (send_peer = suspect)
  STALL = 11,        // coordinator stall warning / escalation
  ABORT = 12,        // data plane aborted (cascade reached this rank)
  MARK = 13,         // user marker (reserved for the Python API)
  ANOMALY = 14,      // perf sentry: op past its baseline (arg = PerfPhase
                     // code, send_peer = slow hop peer for wire-slow)
  NONFINITE = 15,    // NaN/Inf gradient at fusion copy-in (name = tensor,
                     // bytes = non-finite element count, arg = NanPolicy)
  DIVERGENCE = 16,   // cross-rank fingerprint mismatch (send_peer = the
                     // minority rank, arg = its crc32c, bytes = payload)
};

// Why a dump was written. Mirrored in horovod_tpu/flightrec.py DUMP_REASONS.
enum class DumpReason : int32_t {
  ON_DEMAND = 0,  // C API / /debugz snapshot
  ABORT = 1,      // abort cascade (detail = suspected failed peer, -1 none)
  STALL = 2,      // stall-shutdown escalation
  SIGNAL = 3,     // fatal signal (detail = signo)
  NONFINITE = 4,  // HVDTPU_NANCHECK=abort fail-fast (detail = this rank)
};

// One decoded record (the ring stores these packed into kRecordWords
// relaxed-atomic u64 words; see Pack/Unpack in flightrec.cpp).
struct FlightRecord {
  int64_t t_end_us = 0;  // Timeline::SteadyAbsUs at the event's end
  uint32_t dur_us = 0;   // event duration (clamped to u32: ~71 min)
  FlightEvent type = FlightEvent::NONE;
  uint16_t lane = 0;     // 0 none/local, 1 tcp, 2 shm, 3 tcp-zc
  int64_t bytes = 0;     // payload bytes (hops/ops) or aux quantity
  int32_t name_id = -1;  // interned name (-1 none, 0 the overflow slot)
  int32_t arg = 0;       // wait_us (hops) / status (OP_END) / op code / signo
  int32_t send_peer = -1;
  int32_t recv_peer = -1;
};

constexpr int kFlightRecordWords = 5;   // 40 bytes per record
constexpr int kFlightNameBytes = 48;    // interned-name slot size (w/ NUL)
constexpr int kFlightMaxNames = 512;    // names beyond this share kOverflow
constexpr uint32_t kFlightHeaderBytes = 128;
constexpr char kFlightMagic[8] = {'H', 'V', 'D', 'F', 'R', 'E', 'C', '1'};

inline uint16_t FlightLaneCode(const char* kind) {
  if (kind == nullptr) return 0;
  if (kind[0] == 't') return kind[3] == '-' ? 3 : 1;  // "tcp-zc" vs "tcp"
  if (kind[0] == 's') return 2;                       // "shm"
  return 0;                                           // "local" / unknown
}

// Concurrency contract: Record() may run from any thread (the collective-
// driving background thread in practice, plus the transient sender threads
// inside SendRecvSegmented) — the ring is a fetch_add slot claim plus
// relaxed word stores, so concurrent writers never block and never tear a
// word. InternName() is background-thread-only (it owns the lookup map);
// the name TABLE itself is published with release stores so any reader —
// including a signal handler — sees complete entries. Snapshot()/
// DumpToFile() run from any thread; SignalDump() is async-signal-safe
// (syscalls + atomic loads only, path precomposed at Configure time).
class FlightRecorder {
 public:
  FlightRecorder();
  ~FlightRecorder();

  // capacity <= 0 disables (every Record() is one branch). dump_dir may be
  // empty: recording and Snapshot() still work, automatic file dumps are
  // skipped. Call before the background loop starts.
  HVDTPU_CALLED_ON(background)
  void Configure(int64_t capacity, const std::string& dump_dir, int rank,
                 int world_size);
  HVDTPU_CALLED_ON(any)
  bool enabled() const { return cap_ > 0; }
  HVDTPU_CALLED_ON(any)
  int rank() const { return rank_; }
  // "<dump_dir>/flightrec.<rank>.bin" ("" when no dir configured).
  HVDTPU_CALLED_ON(any)
  const std::string& dump_path() const { return dump_path_; }

  // Intern `name` -> id (>= 1; 0 = the shared overflow slot once the table
  // fills; pass -1 to Record for nameless events). Background thread only.
  HVDTPU_CALLED_ON(background)
  int InternName(const std::string& name);

  // One ring write: five relaxed atomic word stores after a fetch_add slot
  // claim. name_id -1 = nameless; arg carries the event-specific scalar
  // (hop wait_us, OP_END status, signal number, ...).
  HVDTPU_CALLED_ON(any)
  void Record(FlightEvent type, int name_id, int64_t bytes, int send_peer,
              int recv_peer, int64_t t0_us, int64_t t1_us, int64_t arg,
              uint16_t lane);

  // Clock offset vs rank 0 (PR-8 sync), recorded into every dump header.
  HVDTPU_CALLED_ON(any)
  void SetClock(int64_t offset_us, int64_t err_us) {
    clock_offset_us_.store(offset_us, std::memory_order_relaxed);
    clock_err_us_.store(err_us, std::memory_order_relaxed);
  }

  HVDTPU_CALLED_ON(any)
  int64_t record_count() const {
    return next_.load(std::memory_order_relaxed);
  }

  // Serialized dump image: header + name table + records oldest-first.
  // Callable from any thread (concurrent writers may overwrite the oldest
  // slots mid-copy; forensics tolerates a torn tail, never a torn word).
  HVDTPU_CALLED_ON(any)
  std::string Snapshot(DumpReason reason, int32_t detail) const;

  // Write Snapshot() to `path` (empty = the configured dump_path). Returns
  // true on success. `fatal_once` dumps are latched: only the FIRST fatal
  // trigger (abort/stall/signal) writes, so a cascade of failures cannot
  // overwrite the record of the original one; on-demand dumps always write.
  HVDTPU_CALLED_ON(any)
  bool DumpToFile(DumpReason reason, int32_t detail,
                  const std::string& path = "", bool fatal_once = false);

  // Async-signal-safe dump to the precomposed path (open/write/close +
  // atomic loads only). No-op without a configured dump dir.
  HVDTPU_CALLED_ON(signal)
  void SignalDump(int signo);

 private:
  void SerializeHeader(char* out, DumpReason reason, int32_t detail,
                       int64_t write_count, uint32_t name_count) const;

  int64_t cap_ = 0;  // records in the ring (0 = disabled)
  int rank_ = 0;
  int world_size_ = 1;
  std::string dump_path_;
  std::unique_ptr<std::atomic<uint64_t>[]> words_;  // cap_ * kRecordWords  // atomic: relaxed-counter
  std::atomic<int64_t> next_{0};  // total records ever written  // atomic: relaxed-counter
  // Interned names: entries [0, name_count_) are immutable once published
  // (fill slot, then release-store the count). Slot 0 is reserved for
  // "<names-overflowed>" so ids stay >= 1 for real names.
  std::unique_ptr<char[]> names_;  // kFlightMaxNames * kFlightNameBytes
  std::atomic<uint32_t> name_count_{0};  // atomic: release-publish
  std::unordered_map<std::string, int> name_ids_;  // background thread only
  std::atomic<int64_t> clock_offset_us_{0};  // atomic: relaxed-counter
  std::atomic<int64_t> clock_err_us_{-1};  // atomic: relaxed-counter
  std::atomic<bool> fatal_dumped_{false};  // atomic: seqcst(one-shot fatal-dump latch)
};

// Process-wide recorder the fatal-signal handlers dump (the most recently
// configured enabled recorder wins; cleared when its core is destroyed).
// Handlers are installed once per process by InstallFlightSignalHandlers.
void SetSignalFlightRecorder(FlightRecorder* rec);
void ClearSignalFlightRecorder(FlightRecorder* rec);
void InstallFlightSignalHandlers();

}  // namespace hvdtpu
