// SHA-256 (FIPS 180-4) + HMAC-SHA256 (RFC 2104) — used to authenticate the
// control plane with a launcher-injected shared secret.
//
// Reference role: horovod/runner/common/util/secret.py generates the job
// secret and common/service/*_service.py HMAC every driver/task message; the
// native controller here verifies an HMAC proof in the HELLO frame so an
// unauthenticated connection cannot join (or poison) the job.
#pragma once

#include <cstdint>
#include <string>

namespace hvdtpu {

// 32-byte binary digest of msg.
void Sha256(const uint8_t* msg, size_t len, uint8_t out[32]);

// Lowercase hex HMAC-SHA256(key, msg).
std::string HmacSha256Hex(const std::string& key, const std::string& msg);

// Constant-time string equality (length leak is fine; contents are not).
bool ConstTimeEquals(const std::string& a, const std::string& b);

}  // namespace hvdtpu
