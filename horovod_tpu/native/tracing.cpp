#include "tracing.h"

namespace hvdtpu {

ClockEstimate EstimateClockOffset(const std::vector<ClockSample>& samples) {
  ClockEstimate best;
  int64_t best_rtt = 0;
  for (const ClockSample& s : samples) {
    const int64_t rtt = s.t3 - s.t1;
    if (rtt < 0) continue;  // clock went backwards / bogus sample
    if (!best.valid || rtt < best_rtt) {
      best_rtt = rtt;
      // The reply timestamp t2 was taken somewhere inside [t1, t3]; assuming
      // the midpoint symmetrizes the two legs, and the residual error is
      // bounded by half the round trip (+1 us granularity floor).
      best.offset_us = s.t2 - (s.t1 + s.t3) / 2;
      best.err_us = rtt / 2 + 1;
      best.valid = true;
    }
  }
  return best;
}

}  // namespace hvdtpu
