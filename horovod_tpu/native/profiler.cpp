#include "profiler.h"

#include <cxxabi.h>
#include <dlfcn.h>
#include <pthread.h>
#include <sys/syscall.h>
#include <ucontext.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>

// glibc spells the SIGEV_THREAD_ID target field through this macro; musl
// and older glibc headers omit it.
#ifndef sigev_notify_thread_id
#define sigev_notify_thread_id _sigev_un._tid
#endif

namespace hvdtpu {

namespace {

// Record header word layout: [0:8) frame count, [8:16) phase code + 1
// (0 = "no phase" so -1 survives the round trip), [16:32) op id,
// [32:64) reserved.
inline uint64_t PackHeader(int nframes, int32_t phase, int32_t op_id) {
  return static_cast<uint64_t>(nframes & 0xff) |
         (static_cast<uint64_t>((phase + 1) & 0xff) << 8) |
         (static_cast<uint64_t>(static_cast<uint16_t>(op_id)) << 16);
}

inline void UnpackHeader(uint64_t h, int* nframes, int32_t* phase,
                         int32_t* op_id) {
  *nframes = static_cast<int>(h & 0xff);
  *phase = static_cast<int32_t>((h >> 8) & 0xff) - 1;
  *op_id = static_cast<int32_t>((h >> 16) & 0xffff);
}

// Handler-drain handshake (same protocol as the flight recorder's): a
// handler increments BEFORE loading its thread's profiler pointer; a
// destructor on another thread drains the count before freeing the ring.
std::atomic<int> g_prof_handler_active{0};  // atomic: seqcst(handler-drain handshake)
std::atomic<bool> g_prof_handler_installed{false};  // atomic: seqcst(install-once exchange)

HVDTPU_ROLE(signal)
void ProfSignalHandler(int /*signo*/, siginfo_t* /*info*/, void* uc) {
  const int saved_errno = errno;
  g_prof_handler_active.fetch_add(1);
  // Per-thread routing: the timer that fired targeted THIS thread, and
  // only its own registration says which profiler owns it (in-process
  // multi-core test worlds run several).
  SamplingProfiler* p = ProfThread()->profiler;
  if (p != nullptr) p->Sample(uc);
  g_prof_handler_active.fetch_sub(1);
  errno = saved_errno;
}

// Demangled (when possible) symbol for `pc`, with module fallback. NOT
// async-signal-safe — fold-time only.
std::string Symbolize(uintptr_t pc) {
  Dl_info info;
  if (dladdr(reinterpret_cast<void*>(pc), &info) != 0 &&
      info.dli_sname != nullptr) {
    int status = 0;
    char* dem = abi::__cxa_demangle(info.dli_sname, nullptr, nullptr,
                                    &status);
    std::string out = status == 0 && dem != nullptr ? dem : info.dli_sname;
    std::free(dem);
    // Strip template/argument noise for fold keys: everything after the
    // first '(' (flamegraph frames read better as bare qualified names).
    const size_t paren = out.find('(');
    if (paren != std::string::npos) out.resize(paren);
    return out;
  }
  if (dladdr(reinterpret_cast<void*>(pc), &info) != 0 &&
      info.dli_fname != nullptr) {
    const char* base = std::strrchr(info.dli_fname, '/');
    return std::string("[") + (base != nullptr ? base + 1 : info.dli_fname) +
           "]";
  }
  char buf[32];
  std::snprintf(buf, sizeof(buf), "0x%zx", static_cast<size_t>(pc));
  return buf;
}

}  // namespace

ProfThreadState* ProfThread() {
  static thread_local ProfThreadState state;
  return &state;
}

// Fold-time aggregation: {phase, op, frames} -> count. std::map keeps the
// output deterministic for the tests.
struct SamplingProfiler::Agg {
  struct Key {
    int32_t phase;
    int32_t op_id;
    std::vector<uintptr_t> frames;  // leaf first
    bool operator<(const Key& o) const {
      if (phase != o.phase) return phase < o.phase;
      if (op_id != o.op_id) return op_id < o.op_id;
      return frames < o.frames;
    }
  };
  std::map<Key, int64_t> counts;
  int64_t total = 0;
  int64_t kept = 0;
};

SamplingProfiler::SamplingProfiler() = default;

SamplingProfiler::~SamplingProfiler() {
  Stop();
  // Every well-paired thread has unregistered by now (the core joins its
  // background loop first); drain any handler still inside Sample() before
  // the ring is freed — Sample() is bounded, so this terminates.
  while (g_prof_handler_active.load() > 0) {
    struct timespec ts = {0, 1000000};  // 1 ms
    nanosleep(&ts, nullptr);
  }
}

void SamplingProfiler::Configure(bool enabled, int hz, int64_t capacity,
                                 ProfClock clock, int rank) {
  enabled_ = enabled;
  rank_ = rank;
  clock_ = clock;
  if (hz > 0) hz_ = hz > 1000 ? 1000 : hz;
  if (!enabled_) {
    cap_ = 0;
    return;
  }
  int64_t cap = capacity > 0 ? capacity : kProfDefaultCapacity;
  if (cap < 64) cap = 64;
  if (cap > kProfMaxCapacity) cap = kProfMaxCapacity;
  cap_ = cap;
  words_ = std::make_unique<std::atomic<uint64_t>[]>(
      static_cast<size_t>(cap_) * kProfRecordWords);
  for (int64_t i = 0; i < cap_ * kProfRecordWords; ++i) {
    words_[i].store(0, std::memory_order_relaxed);
  }
  ops_ = std::make_unique<char[]>(
      static_cast<size_t>(kProfMaxOps) * kProfOpNameBytes);
  std::memset(ops_.get(), 0,
              static_cast<size_t>(kProfMaxOps) * kProfOpNameBytes);
  std::snprintf(ops_.get(), kProfOpNameBytes, "<ops-overflowed>");
  op_count_.store(1, std::memory_order_release);
}

int SamplingProfiler::InternOp(const std::string& name) {
  if (!enabled_) return 0;
  auto it = op_ids_.find(name);
  if (it != op_ids_.end()) return it->second;
  uint32_t n = op_count_.load(std::memory_order_relaxed);  // atomic-ok: single-writer reads its own count
  if (n >= kProfMaxOps) {
    op_ids_.emplace(name, 0);
    return 0;
  }
  char* slot = ops_.get() + static_cast<size_t>(n) * kProfOpNameBytes;
  std::snprintf(slot, kProfOpNameBytes, "%s", name.c_str());
  op_count_.store(n + 1, std::memory_order_release);
  op_ids_.emplace(name, static_cast<int>(n));
  return static_cast<int>(n);
}

void SamplingProfiler::ArmTimer(ProfThreadState* t, bool arm) {
  if (!t->registered) return;
  struct itimerspec its;
  std::memset(&its, 0, sizeof(its));
  if (arm) {
    const long ns = 1000000000L / hz_;
    its.it_interval.tv_sec = 0;
    its.it_interval.tv_nsec = ns;
    its.it_value = its.it_interval;
  }  // all-zero disarms
  timer_settime(t->timer, 0, &its, nullptr);
  t->timer_armed = arm;
}

void SamplingProfiler::RegisterThread() {
  if (!enabled_) return;
  ProfThreadState* t = ProfThread();
  if (t->registered) return;
  // Stack bounds for the unwinder's range checks: every frame-pointer
  // dereference must land inside this thread's own mapped stack, so a
  // broken chain (frame-pointer-less libc frames, leaf tails) terminates
  // the walk instead of faulting inside a signal handler.
  pthread_attr_t attr;
  if (pthread_getattr_np(pthread_self(), &attr) == 0) {
    void* lo = nullptr;
    size_t sz = 0;
    if (pthread_attr_getstack(&attr, &lo, &sz) == 0 && lo != nullptr) {
      t->stack_lo = reinterpret_cast<uintptr_t>(lo);
      t->stack_hi = t->stack_lo + sz;
    }
    pthread_attr_destroy(&attr);
  }
  if (t->stack_hi == 0) return;  // no bounds -> never unwind this thread
  struct sigevent sev;
  std::memset(&sev, 0, sizeof(sev));
  sev.sigev_notify = SIGEV_THREAD_ID;
  sev.sigev_signo = SIGPROF;
  sev.sigev_notify_thread_id =
      static_cast<pid_t>(syscall(SYS_gettid));
  const clockid_t clk = clock_ == ProfClock::WALL ? CLOCK_MONOTONIC
                                                  : CLOCK_THREAD_CPUTIME_ID;
  if (timer_create(clk, &sev, &t->timer) != 0) return;
  t->registered = true;
  t->profiler = this;
  InstallProfSignalHandler();
  MutexLock lk(mu_);
  threads_.push_back(t);
  if (running_.load(std::memory_order_acquire)) ArmTimer(t, true);
}

void SamplingProfiler::UnregisterThread() {
  ProfThreadState* t = ProfThread();
  if (!t->registered || t->profiler != this) return;
  // Null the routing pointer FIRST: a SIGPROF already queued for this
  // thread may still be delivered after timer_delete, and the handler must
  // observe the teardown (same-thread program order guarantees it does).
  t->profiler = nullptr;
  // The rest under the registry mutex: Start/Stop walk threads_ and touch
  // timer_armed/registered from other threads under the same lock.
  MutexLock lk(mu_);
  ArmTimer(t, false);
  timer_delete(t->timer);
  t->registered = false;
  for (size_t i = 0; i < threads_.size(); ++i) {
    if (threads_[i] == t) {
      threads_.erase(threads_.begin() + static_cast<ptrdiff_t>(i));
      break;
    }
  }
}

int SamplingProfiler::registered_threads() const {
  MutexLock lk(mu_);
  return static_cast<int>(threads_.size());
}

void SamplingProfiler::Start() {
  if (!enabled_) return;
  MutexLock lk(mu_);
  if (running_.load(std::memory_order_acquire)) return;
  // Fresh window: drop the previous ring contents so folded output never
  // mixes two windows.
  for (int64_t i = 0; i < cap_ * kProfRecordWords; ++i) {
    words_[i].store(0, std::memory_order_relaxed);
  }
  next_.store(0, std::memory_order_relaxed);
  running_.store(true, std::memory_order_release);
  for (ProfThreadState* t : threads_) ArmTimer(t, true);
}

void SamplingProfiler::Stop() {
  if (!enabled_) return;
  MutexLock lk(mu_);
  if (!running_.load(std::memory_order_acquire)) return;
  running_.store(false, std::memory_order_release);
  for (ProfThreadState* t : threads_) ArmTimer(t, false);
}

void SamplingProfiler::Sample(void* ucontext) {
  if (!running_.load(std::memory_order_relaxed) || cap_ <= 0) return;  // atomic-ok: async-signal gate; stale read only costs one sample
  ProfThreadState* t = ProfThread();
  uintptr_t pcs[kProfMaxFrames];
  int n = 0;
  uintptr_t pc = 0;
  uintptr_t fp = 0;
  ucontext_t* uc = static_cast<ucontext_t*>(ucontext);
#if defined(__x86_64__)
  if (uc != nullptr) {
    pc = static_cast<uintptr_t>(uc->uc_mcontext.gregs[REG_RIP]);
    fp = static_cast<uintptr_t>(uc->uc_mcontext.gregs[REG_RBP]);
  }
#elif defined(__aarch64__)
  if (uc != nullptr) {
    pc = static_cast<uintptr_t>(uc->uc_mcontext.pc);
    fp = static_cast<uintptr_t>(uc->uc_mcontext.regs[29]);
  }
#else
  (void)uc;
#endif
  if (pc != 0) pcs[n++] = pc;
  // Frame-pointer walk: [fp] = caller's fp, [fp + 8] = return address.
  // Every dereference is bounds-checked against the thread's own stack and
  // the chain must strictly grow toward the stack base, so a missing or
  // corrupt frame pointer ends the walk — it can never fault or loop.
  uintptr_t lo = t->stack_lo;
  const uintptr_t hi = t->stack_hi;
  while (n < kProfMaxFrames && fp >= lo && fp + 2 * sizeof(uintptr_t) <= hi &&
         (fp & (sizeof(uintptr_t) - 1)) == 0) {
    const uintptr_t* frame = reinterpret_cast<const uintptr_t*>(fp);
    const uintptr_t ret = frame[1];
    if (ret < 4096) break;
    pcs[n++] = ret;
    const uintptr_t next_fp = frame[0];
    if (next_fp <= fp) break;  // must move toward the stack base
    lo = fp + 1;
    fp = next_fp;
  }
  if (n == 0) return;
  const int64_t idx = next_.fetch_add(1, std::memory_order_relaxed);
  std::atomic<uint64_t>* w =
      words_.get() + (idx % cap_) * kProfRecordWords;
  w[0].store(PackHeader(n, t->phase.load(std::memory_order_relaxed),
                        t->op_id.load(std::memory_order_relaxed)),
             std::memory_order_relaxed);
  for (int i = 0; i < n; ++i) {
    w[1 + i].store(static_cast<uint64_t>(pcs[i]), std::memory_order_relaxed);
  }
  for (int i = n; i < kProfMaxFrames; ++i) {
    w[1 + i].store(0, std::memory_order_relaxed);
  }
}

void SamplingProfiler::FoldInto(Agg* agg) const {
  const int64_t wc = next_.load(std::memory_order_relaxed);
  const int64_t kept = wc < cap_ ? wc : cap_;
  const int64_t start = wc < cap_ ? 0 : wc % cap_;
  agg->total = wc;
  agg->kept = kept;
  const uint32_t nops = op_count_.load(std::memory_order_acquire);
  for (int64_t i = 0; i < kept; ++i) {
    const std::atomic<uint64_t>* w =
        words_.get() + ((start + i) % cap_) * kProfRecordWords;
    int nframes = 0;
    int32_t phase = -1, op_id = 0;
    UnpackHeader(w[0].load(std::memory_order_relaxed), &nframes, &phase,
                 &op_id);
    if (nframes <= 0 || nframes > kProfMaxFrames) continue;  // torn/empty
    Agg::Key key;
    key.phase = phase;
    key.op_id = op_id < static_cast<int32_t>(nops) ? op_id : 0;
    key.frames.reserve(static_cast<size_t>(nframes));
    for (int f = 0; f < nframes; ++f) {
      key.frames.push_back(static_cast<uintptr_t>(
          w[1 + f].load(std::memory_order_relaxed)));
    }
    ++agg->counts[key];
  }
}

std::string SamplingProfiler::FoldedJson() const {
  if (!enabled_ || cap_ <= 0) {
    return "{\"version\": 1, \"enabled\": false, \"stacks\": []}";
  }
  Agg agg;
  FoldInto(&agg);
  // Symbolize each unique pc once (dladdr is microseconds; stacks repeat).
  std::map<uintptr_t, std::string> syms;
  int64_t phase_counts[kPerfPhases + 1] = {0};  // [kPerfPhases] = untagged
  for (const auto& kv : agg.counts) {
    const int32_t p = kv.first.phase;
    phase_counts[p >= 0 && p < kPerfPhases ? p : kPerfPhases] += kv.second;
    for (uintptr_t pc : kv.first.frames) {
      if (syms.find(pc) == syms.end()) syms[pc] = Symbolize(pc);
    }
  }
  std::string out = "{\"version\": 1, \"enabled\": true, \"rank\": " +
                    std::to_string(rank_) + ", \"hz\": " +
                    std::to_string(hz_) + ", \"clock\": \"" +
                    (clock_ == ProfClock::WALL ? "wall" : "cpu") +
                    "\", \"running\": " + (running() ? "true" : "false") +
                    ", \"samples\": " + std::to_string(agg.total) +
                    ", \"kept\": " + std::to_string(agg.kept) +
                    ", \"phases\": {";
  bool first = true;
  for (int p = 0; p <= kPerfPhases; ++p) {
    if (phase_counts[p] == 0) continue;
    if (!first) out += ", ";
    first = false;
    out += '"';
    out += p < kPerfPhases ? PerfPhaseName(static_cast<PerfPhase>(p))
                           : "idle";
    out += "\": " + std::to_string(phase_counts[p]);
  }
  out += "}, \"stacks\": [";
  first = true;
  const uint32_t nops = op_count_.load(std::memory_order_acquire);
  for (const auto& kv : agg.counts) {
    if (!first) out += ", ";
    first = false;
    const int32_t p = kv.first.phase;
    const char* op =
        kv.first.op_id > 0 && kv.first.op_id < static_cast<int32_t>(nops)
            ? ops_.get() +
                  static_cast<size_t>(kv.first.op_id) * kProfOpNameBytes
            : "";
    out += "{\"phase\": \"";
    out += p >= 0 && p < kPerfPhases
               ? PerfPhaseName(static_cast<PerfPhase>(p))
               : "idle";
    out += "\", \"op\": " + JsonEscapeString(op) +
           ", \"count\": " + std::to_string(kv.second) + ", \"frames\": [";
    for (size_t f = 0; f < kv.first.frames.size(); ++f) {
      if (f > 0) out += ", ";
      out += JsonEscapeString(syms[kv.first.frames[f]]);
    }
    out += "]}";
  }
  out += "]}";
  return out;
}

std::string SamplingProfiler::FoldedText() const {
  if (!enabled_ || cap_ <= 0) return std::string();
  Agg agg;
  FoldInto(&agg);
  std::map<uintptr_t, std::string> syms;
  for (const auto& kv : agg.counts) {
    for (uintptr_t pc : kv.first.frames) {
      if (syms.find(pc) == syms.end()) syms[pc] = Symbolize(pc);
    }
  }
  const uint32_t nops = op_count_.load(std::memory_order_acquire);
  std::string out;
  for (const auto& kv : agg.counts) {
    const int32_t p = kv.first.phase;
    const char* op =
        kv.first.op_id > 0 && kv.first.op_id < static_cast<int32_t>(nops)
            ? ops_.get() +
                  static_cast<size_t>(kv.first.op_id) * kProfOpNameBytes
            : "-";
    // flamegraph.pl folds on ';'-joined root-first frames; the phase and op
    // lead the stack so one flamegraph splits by {op, phase} at its base.
    // Frame names are sanitized (';' and whitespace) to keep the grammar.
    out += p >= 0 && p < kPerfPhases
               ? PerfPhaseName(static_cast<PerfPhase>(p))
               : "idle";
    out += ';';
    for (const char* c = op[0] != '\0' ? op : "-"; *c != '\0'; ++c) {
      out += *c == ';' || *c == ' ' || *c == '\n' ? '_' : *c;
    }
    for (size_t f = kv.first.frames.size(); f-- > 0;) {
      out += ';';
      for (char c : syms[kv.first.frames[f]]) {
        out += c == ';' || c == ' ' || c == '\n' ? '_' : c;
      }
    }
    out += ' ';
    out += std::to_string(kv.second);
    out += '\n';
  }
  return out;
}

bool SamplingProfiler::WriteFolded(const std::string& path) const {
  if (!enabled_ || path.empty()) return false;
  const std::string body = FoldedText();
  FILE* f = std::fopen(path.c_str(), "w");
  if (f == nullptr) return false;
  const bool ok = std::fwrite(body.data(), 1, body.size(), f) == body.size();
  std::fclose(f);
  return ok;
}

void InstallProfSignalHandler() {
  if (g_prof_handler_installed.exchange(true)) return;
  struct sigaction sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = ProfSignalHandler;
  sa.sa_flags = SA_SIGINFO | SA_RESTART;
  sigemptyset(&sa.sa_mask);
  sigaction(SIGPROF, &sa, nullptr);
}

}  // namespace hvdtpu
