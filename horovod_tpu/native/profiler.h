// Always-available in-process sampling profiler (docs/profiling.md).
//
// The observability stack explains WHAT happened (/metrics), WHY an op was
// slow at span level (tracing.h), what a DEAD job was doing (flightrec.h)
// and WHETHER an op beats its own baseline (perfstats.h) — this subsystem
// answers the remaining question: WHICH CODE is burning the cycles when the
// sentry names a phase. Per-thread sampling via POSIX timers delivering
// SIGPROF (timer_create + SIGEV_THREAD_ID at HVDTPU_PROF_HZ, on the
// thread's CPU clock or the monotonic wall clock), an async-signal-safe
// frame-pointer unwinder writing fixed-size records into a lock-free ring
// (same discipline as the flight recorder: no locks, no allocation, no
// syscalls in the handler), and dladdr symbolization deferred entirely to
// dump time. Every sample is tagged with the sampled thread's CURRENT
// PerfPhase and op from a thread-local the data plane publishes at the
// PR-10 phase-accumulator points — so folded output splits into flamegraphs
// by {op, phase}: "where does REDUCE actually spend its cycles on the
// wire-slow rank?".
//
// Surfaces: the secret-gated /profz endpoint (start/stop window +
// folded-stacks JSON) beside /metrics, hvd.profile() in Python,
// `hvdrun --profile DIR` collecting prof.<rank>.folded per rank
// (scripts/prof_report.py merges them), and the C API
// (hvdtpu_set_profiler / hvdtpu_profiler_{start,stop,snapshot}).
//
// Reference analog: upstream Horovod's timeline+profiling workflow (arxiv
// 1802.05799) and the phase-attributed MPI characterization of arxiv
// 1810.11112 — there offline and by hand; here live, per-phase, and
// machine-mergeable.
#pragma once

#include <signal.h>
#include <time.h>

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "perfstats.h"

namespace hvdtpu {

// Frames kept per sample (leaf first). 24 return addresses cover every
// data-plane call chain with room to spare; deeper stacks truncate at the
// root end.
constexpr int kProfMaxFrames = 24;
// Ring record: one header word (frame count, phase, op id) + the pc words.
constexpr int kProfRecordWords = kProfMaxFrames + 1;
// Interned op-name slots (flight-recorder discipline: slot 0 is the shared
// overflow entry so InternOp never fails).
constexpr int kProfMaxOps = 256;
constexpr int kProfOpNameBytes = 48;
// Default sampling rate. Prime, so the sampler cannot phase-lock with
// millisecond-periodic loops (the classic 100 Hz vs 1 kHz aliasing trap).
constexpr int kProfDefaultHz = 97;
// Default ring capacity in samples (~3.2 MB): at 97 Hz that holds a
// ~169 s window per sampled thread before the ring wraps (newest kept).
constexpr int64_t kProfDefaultCapacity = 16384;
constexpr int64_t kProfMaxCapacity = 4 * 1024 * 1024;

// Sampling clock (HVDTPU_PROF_CLOCK). CPU: the thread's CPU-time clock —
// samples land only while the thread burns cycles, the classic flamegraph
// contract. WALL: the monotonic clock — blocked time (peer waits, chaos
// delays) is sampled too, so the per-phase split matches the perf-
// attribution wall buckets. Mirrored by envvars.PROF_CLOCK_MODES
// (scripts/check_invariants.py ENUM-MIRROR).
enum class ProfClock : int32_t {
  CPU = 0,
  WALL = 1,
};

class SamplingProfiler;

// Per-thread sampling state. The SIGPROF handler runs ON the thread whose
// timer fired and reads only this thread's slot, so phase/op publication is
// same-thread: relaxed atomics are plenty (they exist to pin the ordering
// against the compiler, not other CPUs). stack_lo/hi bound the frame-
// pointer walk — every dereference is range-checked against the thread's
// own mapped stack, so a broken chain terminates instead of faulting.
struct ProfThreadState {
  std::atomic<int32_t> phase{-1};  // PerfPhase code; -1 = outside any op  // atomic: relaxed-counter
  std::atomic<int32_t> op_id{0};   // interned op slot (0 = none)  // atomic: relaxed-counter
  uintptr_t stack_lo = 0;
  uintptr_t stack_hi = 0;
  timer_t timer{};
  bool timer_armed = false;
  bool registered = false;
  // The profiler this thread is registered with — the SIGPROF handler
  // samples into it. Same-thread only: set by RegisterThread, cleared by
  // UnregisterThread BEFORE the timer is deleted, so even a signal queued
  // across the teardown observes null (signal handlers see their own
  // thread's prior stores in program order).
  SamplingProfiler* profiler = nullptr;
};

// This thread's slot (TLS; never null).
ProfThreadState* ProfThread();

// Scoped phase publication — the data plane brackets its wire/reduce/codec/
// wait regions with these at the same points the PR-10 accumulators ride.
// Nesting saves and restores (a WAIT slice inside a WIRE hop publishes WAIT
// for its duration, then WIRE again). Cost when idle: two relaxed TLS
// stores per scope — nanoseconds against microsecond-scale regions.
class ProfPhaseScope {
 public:
  explicit ProfPhaseScope(PerfPhase phase) {
    ProfThreadState* t = ProfThread();
    prev_ = t->phase.load(std::memory_order_relaxed);
    t->phase.store(static_cast<int32_t>(phase), std::memory_order_relaxed);
  }
  ~ProfPhaseScope() {
    ProfThread()->phase.store(prev_, std::memory_order_relaxed);
  }
  ProfPhaseScope(const ProfPhaseScope&) = delete;
  ProfPhaseScope& operator=(const ProfPhaseScope&) = delete;

 private:
  int32_t prev_;
};

// Scoped op publication (op id + WALL base phase for the op's duration);
// the core wraps each collective execution in one.
class ProfOpScope {
 public:
  explicit ProfOpScope(int op_id) {
    ProfThreadState* t = ProfThread();
    prev_op_ = t->op_id.load(std::memory_order_relaxed);
    prev_phase_ = t->phase.load(std::memory_order_relaxed);
    t->op_id.store(op_id, std::memory_order_relaxed);
    t->phase.store(static_cast<int32_t>(PerfPhase::WALL),
                   std::memory_order_relaxed);
  }
  ~ProfOpScope() {
    ProfThreadState* t = ProfThread();
    t->op_id.store(prev_op_, std::memory_order_relaxed);
    t->phase.store(prev_phase_, std::memory_order_relaxed);
  }
  ProfOpScope(const ProfOpScope&) = delete;
  ProfOpScope& operator=(const ProfOpScope&) = delete;

 private:
  int32_t prev_op_;
  int32_t prev_phase_;
};

// Concurrency contract: RegisterThread/UnregisterThread run on the thread
// being sampled (they own its TLS slot and POSIX timer; the registry vector
// is mutex-guarded, cold path). Start/Stop/FoldedJson run from any thread
// (the /profz HTTP handler in practice) — the ring is fetch_add slot claims
// plus relaxed word stores, so concurrent samplers never block and a
// concurrent fold sees torn TAILS (the oldest records mid-overwrite), never
// torn words. Sample() is async-signal-safe: atomic loads/stores and
// range-checked stack reads only. InternOp is background-thread-only, like
// FlightRecorder::InternName.
class SamplingProfiler {
 public:
  SamplingProfiler();
  ~SamplingProfiler();

  // enabled=false turns every other entry point into one branch. hz <= 0
  // keeps the default; capacity <= 0 keeps the default ring size. Call
  // before threads register (the core does this pre-Start).
  HVDTPU_CALLED_ON(background)
  void Configure(bool enabled, int hz, int64_t capacity, ProfClock clock,
                 int rank);
  HVDTPU_CALLED_ON(any)
  bool enabled() const { return enabled_; }
  HVDTPU_CALLED_ON(any)
  int hz() const { return hz_; }
  HVDTPU_CALLED_ON(any)
  ProfClock clock() const { return clock_; }
  HVDTPU_CALLED_ON(any)
  int rank() const { return rank_; }

  // Create (disarmed) this thread's sampling timer and record its stack
  // bounds; arms immediately when a window is running. No-op when disabled
  // or already registered. UnregisterThread must run on the same thread
  // before it exits (the background loop pairs them RAII-style).
  HVDTPU_CALLED_ON(any)
  void RegisterThread();
  HVDTPU_CALLED_ON(any)
  void UnregisterThread();
  HVDTPU_CALLED_ON(any)
  int registered_threads() const EXCLUDES(mu_);

  // Sampling window control. Start clears the ring and arms every
  // registered thread's timer; Stop disarms them. Both idempotent, any
  // thread (/profz, hvd.profile(), the runner's whole-job window).
  HVDTPU_CALLED_ON(background)
  void Start() EXCLUDES(mu_);
  HVDTPU_CALLED_ON(background)
  void Stop() EXCLUDES(mu_);
  HVDTPU_CALLED_ON(any)
  bool running() const {
    return running_.load(std::memory_order_acquire);
  }

  // Total samples ever written this window (ring keeps the newest
  // min(count, capacity)).
  HVDTPU_CALLED_ON(any)
  int64_t sample_count() const {
    return next_.load(std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(any)
  int64_t capacity() const { return cap_; }

  // Intern `name` -> op slot (>= 1; 0 = shared overflow). Background
  // (collective-driving) thread only.
  HVDTPU_CALLED_ON(background)
  int InternOp(const std::string& name);

  // One sample: unwind the interrupted thread's frame-pointer chain and
  // write a record. Called from the SIGPROF handler with the handler's
  // ucontext (leaf pc + frame pointer); async-signal-safe.
  HVDTPU_CALLED_ON(signal)
  void Sample(void* ucontext);

  // Folded-stacks JSON (the /profz payload and hvd.profile()'s return):
  // aggregated {phase, op, frames} -> count, symbolized via dladdr at this
  // point only. Any thread, live (tolerates concurrent samplers).
  HVDTPU_CALLED_ON(any)
  std::string FoldedJson() const;
  // flamegraph.pl-compatible folded lines: "PHASE;op;root;...;leaf N".
  HVDTPU_CALLED_ON(any)
  std::string FoldedText() const;
  // Write FoldedText to `path` (prof.<rank>.folded). False on I/O failure
  // or when disabled.
  HVDTPU_CALLED_ON(any)
  bool WriteFolded(const std::string& path) const;

 private:
  struct Agg;  // fold-time aggregation (profiler.cpp)
  void ArmTimer(ProfThreadState* t, bool arm);
  void FoldInto(Agg* agg) const;

  bool enabled_ = false;
  int hz_ = kProfDefaultHz;
  ProfClock clock_ = ProfClock::CPU;
  int rank_ = 0;
  int64_t cap_ = 0;  // samples in the ring (0 until configured)
  std::unique_ptr<std::atomic<uint64_t>[]> words_;  // cap_ * kProfRecordWords  // atomic: relaxed-counter
  std::atomic<int64_t> next_{0};  // atomic: relaxed-counter
  std::atomic<bool> running_{false};  // atomic: release-publish
  // Interned op names (flight-recorder style publication: fill slot, then
  // release-store the count; readers acquire the count).
  std::unique_ptr<char[]> ops_;  // kProfMaxOps * kProfOpNameBytes
  std::atomic<uint32_t> op_count_{0};  // atomic: release-publish
  std::unordered_map<std::string, int> op_ids_;  // background thread only
  mutable Mutex mu_;
  std::vector<ProfThreadState*> threads_ GUARDED_BY(mu_);
};

// Install the SIGPROF handler once per process (SA_RESTART + SA_SIGINFO).
// The handler samples into the CALLING THREAD's registered profiler
// (ProfThreadState::profiler) — multiple cores in one process (in-process
// test worlds) each sample their own threads.
void InstallProfSignalHandler();

}  // namespace hvdtpu
