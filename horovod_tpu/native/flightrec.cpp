#include "flightrec.h"

#include <fcntl.h>
#include <signal.h>
#include <time.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <memory>

namespace hvdtpu {

namespace {

inline int64_t SteadyNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline int64_t WallNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

inline uint64_t PackU32Pair(uint32_t lo, uint32_t hi) {
  return static_cast<uint64_t>(lo) |
         (static_cast<uint64_t>(hi) << 32);
}

// Little-endian scalar writes into the header buffer. The ring words are
// stored host-endian and dumped verbatim; every supported target is
// little-endian (x86-64 / aarch64), and the decoder asserts the magic.
template <typename T>
inline void Put(char* buf, size_t off, T v) {
  std::memcpy(buf + off, &v, sizeof(T));
}

}  // namespace

FlightRecorder::FlightRecorder() = default;

FlightRecorder::~FlightRecorder() { ClearSignalFlightRecorder(this); }

void FlightRecorder::Configure(int64_t capacity, const std::string& dump_dir,
                               int rank, int world_size) {
  rank_ = rank;
  world_size_ = world_size;
  if (capacity <= 0) {
    cap_ = 0;
    return;
  }
  // Floor keeps the ring useful (a handful of events IS the last op) and
  // the dump header's oldest-first reorder trivial.
  cap_ = capacity < 64 ? 64 : capacity;
  words_ = std::make_unique<std::atomic<uint64_t>[]>(
      static_cast<size_t>(cap_) * kFlightRecordWords);
  for (int64_t i = 0; i < cap_ * kFlightRecordWords; ++i) {
    words_[i].store(0, std::memory_order_relaxed);
  }
  names_ = std::make_unique<char[]>(
      static_cast<size_t>(kFlightMaxNames) * kFlightNameBytes);
  std::memset(names_.get(), 0,
              static_cast<size_t>(kFlightMaxNames) * kFlightNameBytes);
  // Slot 0: the shared overflow name, so InternName never fails.
  std::snprintf(names_.get(), kFlightNameBytes, "<names-overflowed>");
  name_count_.store(1, std::memory_order_release);
  if (!dump_dir.empty()) {
    dump_path_ = dump_dir + "/flightrec." + std::to_string(rank) + ".bin";
  }
}

int FlightRecorder::InternName(const std::string& name) {
  if (!enabled()) return 0;
  auto it = name_ids_.find(name);
  if (it != name_ids_.end()) return it->second;
  uint32_t n = name_count_.load(std::memory_order_relaxed);  // atomic-ok: single-writer reads its own count
  if (n >= kFlightMaxNames) {
    name_ids_.emplace(name, 0);  // memoize the overflow verdict too
    return 0;
  }
  char* slot = names_.get() + static_cast<size_t>(n) * kFlightNameBytes;
  std::snprintf(slot, kFlightNameBytes, "%s", name.c_str());
  // Publish AFTER the slot is complete: readers (incl. signal handlers)
  // acquire the count and only read entries below it.
  name_count_.store(n + 1, std::memory_order_release);
  name_ids_.emplace(name, static_cast<int>(n));
  return static_cast<int>(n);
}

void FlightRecorder::Record(FlightEvent type, int name_id, int64_t bytes,
                            int send_peer, int recv_peer, int64_t t0_us,
                            int64_t t1_us, int64_t arg, uint16_t lane) {
  if (!enabled()) return;
  const int64_t idx = next_.fetch_add(1, std::memory_order_relaxed);
  std::atomic<uint64_t>* w =
      words_.get() + (idx % cap_) * kFlightRecordWords;
  int64_t dur = t1_us - t0_us;
  if (dur < 0) dur = 0;
  const uint32_t dur32 =
      dur > INT32_MAX ? static_cast<uint32_t>(INT32_MAX)
                      : static_cast<uint32_t>(dur);
  int64_t a = arg;
  if (a > INT32_MAX) a = INT32_MAX;
  if (a < INT32_MIN) a = INT32_MIN;
  w[0].store(static_cast<uint64_t>(t1_us), std::memory_order_relaxed);
  w[1].store(static_cast<uint64_t>(dur32) |
                 (static_cast<uint64_t>(static_cast<uint16_t>(
                      static_cast<int32_t>(type))) << 32) |
                 (static_cast<uint64_t>(lane) << 48),
             std::memory_order_relaxed);
  w[2].store(static_cast<uint64_t>(bytes), std::memory_order_relaxed);
  w[3].store(PackU32Pair(static_cast<uint32_t>(name_id),
                         static_cast<uint32_t>(static_cast<int32_t>(a))),
             std::memory_order_relaxed);
  w[4].store(PackU32Pair(static_cast<uint32_t>(send_peer),
                         static_cast<uint32_t>(recv_peer)),
             std::memory_order_relaxed);
}

void FlightRecorder::SerializeHeader(char* out, DumpReason reason,
                                     int32_t detail, int64_t write_count,
                                     uint32_t name_count) const {
  std::memset(out, 0, kFlightHeaderBytes);
  std::memcpy(out, kFlightMagic, sizeof(kFlightMagic));
  Put<uint32_t>(out, 8, 1);                   // version
  Put<uint32_t>(out, 12, kFlightHeaderBytes);
  Put<int32_t>(out, 16, rank_);
  Put<int32_t>(out, 20, world_size_);
  Put<int64_t>(out, 24, clock_offset_us_.load(std::memory_order_relaxed));
  Put<int64_t>(out, 32, clock_err_us_.load(std::memory_order_relaxed));
  Put<int64_t>(out, 40, SteadyNowUs());       // anchor pair at dump time
  Put<int64_t>(out, 48, WallNowUs());
  Put<int64_t>(out, 56, write_count);
  Put<uint32_t>(out, 64, static_cast<uint32_t>(cap_));
  Put<uint32_t>(out, 68, kFlightRecordWords * 8);
  Put<uint32_t>(out, 72, name_count);
  Put<uint32_t>(out, 76, kFlightNameBytes);
  Put<int32_t>(out, 80, static_cast<int32_t>(reason));
  Put<int32_t>(out, 84, detail);
}

std::string FlightRecorder::Snapshot(DumpReason reason,
                                     int32_t detail) const {
  if (!enabled()) return std::string();
  const int64_t wc = next_.load(std::memory_order_relaxed);
  const uint32_t names = name_count_.load(std::memory_order_acquire);
  const int64_t kept = wc < cap_ ? wc : cap_;
  std::string out;
  out.resize(kFlightHeaderBytes +
             static_cast<size_t>(names) * kFlightNameBytes +
             static_cast<size_t>(kept) * kFlightRecordWords * 8);
  char* p = &out[0];
  SerializeHeader(p, reason, detail, wc, names);
  p += kFlightHeaderBytes;
  std::memcpy(p, names_.get(), static_cast<size_t>(names) * kFlightNameBytes);
  p += static_cast<size_t>(names) * kFlightNameBytes;
  // Oldest-first: ring position of the oldest kept record is wc % cap_
  // once the ring has wrapped, 0 before.
  const int64_t start = wc < cap_ ? 0 : wc % cap_;
  uint64_t* dst = reinterpret_cast<uint64_t*>(p);
  for (int64_t i = 0; i < kept; ++i) {
    const std::atomic<uint64_t>* w =
        words_.get() + ((start + i) % cap_) * kFlightRecordWords;
    for (int j = 0; j < kFlightRecordWords; ++j) {
      dst[i * kFlightRecordWords + j] = w[j].load(std::memory_order_relaxed);
    }
  }
  return out;
}

bool FlightRecorder::DumpToFile(DumpReason reason, int32_t detail,
                                const std::string& path, bool fatal_once) {
  if (!enabled()) return false;
  const std::string& target = path.empty() ? dump_path_ : path;
  if (target.empty()) return false;
  if (fatal_once && fatal_dumped_.exchange(true)) return false;
  std::string img = Snapshot(reason, detail);
  FILE* f = std::fopen(target.c_str(), "wb");
  const bool ok =
      f != nullptr &&
      std::fwrite(img.data(), 1, img.size(), f) == img.size();
  if (f != nullptr) std::fclose(f);
  // A failed write must not burn the only dump opportunity: re-arm the
  // latch so a later trigger (stall after a full disk was cleared, the
  // fatal-signal handler) still gets its chance at a post-mortem.
  if (fatal_once && !ok) fatal_dumped_.store(false);
  return ok;
}

void FlightRecorder::SignalDump(int signo) {
  if (!enabled() || dump_path_.empty()) return;
  if (fatal_dumped_.exchange(true)) return;
  const int fd = ::open(dump_path_.c_str(),
                        O_CREAT | O_WRONLY | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) {
    fatal_dumped_.store(false);  // nothing written: leave the latch armed
    return;
  }
  const int64_t wc = next_.load(std::memory_order_relaxed);
  const uint32_t names = name_count_.load(std::memory_order_acquire);
  char header[kFlightHeaderBytes];
  SerializeHeader(header, DumpReason::SIGNAL, signo, wc, names);
  // Partial writes are not retried: a truncated tail still decodes up to
  // the last whole record, and anything fancier is risk inside a handler.
  ssize_t n = ::write(fd, header, sizeof(header));
  if (n == static_cast<ssize_t>(sizeof(header))) {
    n = ::write(fd, names_.get(),
                static_cast<size_t>(names) * kFlightNameBytes);
  }
  if (n >= 0) {
    const int64_t kept = wc < cap_ ? wc : cap_;
    const int64_t start = wc < cap_ ? 0 : wc % cap_;
    uint64_t chunk[64 * kFlightRecordWords];
    int64_t i = 0;
    while (i < kept) {
      int64_t m = 0;
      while (m < 64 && i + m < kept) {
        const std::atomic<uint64_t>* w =
            words_.get() + ((start + i + m) % cap_) * kFlightRecordWords;
        for (int j = 0; j < kFlightRecordWords; ++j) {
          chunk[m * kFlightRecordWords + j] =
              w[j].load(std::memory_order_relaxed);
        }
        ++m;
      }
      if (::write(fd, chunk,
                  static_cast<size_t>(m) * kFlightRecordWords * 8) < 0) {
        break;
      }
      i += m;
    }
  }
  ::close(fd);
  // SIGTERM is launcher/watchdog cleanup, not a cause (postmortem.py
  // classifies it exactly so) — and an application with its own SIGTERM
  // handler may survive it. Re-arm the latch so a LATER genuine fatal
  // (SIGSEGV, abort cascade) can overwrite this dump with the real story;
  // the reverse order stays protected (a prior fatal dump latches this
  // handler out above).
  if (signo == SIGTERM) fatal_dumped_.store(false);
}

// ---------------------------------------------------------------------------
// Fatal-signal plumbing
// ---------------------------------------------------------------------------

namespace {

std::atomic<FlightRecorder*> g_signal_recorder{nullptr};  // atomic: seqcst(publish/drain pairs with g_handler_active)
// Handshake with ClearSignalFlightRecorder: a handler enters (increments)
// BEFORE loading the recorder pointer, so the clearing thread can null the
// pointer and then drain the count, guaranteeing no handler still holds a
// recorder whose buffers its destructor is about to free. Both sides use
// seq_cst: a handler that observed a non-null pointer ordered its increment
// before the clearer's null store, so the drain loop must see it.
std::atomic<int> g_handler_active{0};  // atomic: seqcst(handler-drain handshake, see comment above)
constexpr int kFlightSignals[] = {SIGSEGV, SIGBUS, SIGABRT, SIGTERM};
struct sigaction g_prev_actions[sizeof(kFlightSignals) /
                                sizeof(kFlightSignals[0])];
std::atomic<bool> g_handlers_installed{false};  // atomic: seqcst(install-once exchange)

HVDTPU_ROLE(signal)
void FlightSignalHandler(int signo) {
  g_handler_active.fetch_add(1);
  FlightRecorder* rec = g_signal_recorder.load();
  if (rec != nullptr) rec->SignalDump(signo);
  g_handler_active.fetch_sub(1);
  // Restore the pre-install disposition and re-raise so the process still
  // dies (or runs the application's own handler) exactly as before.
  for (size_t i = 0;
       i < sizeof(kFlightSignals) / sizeof(kFlightSignals[0]); ++i) {
    if (kFlightSignals[i] == signo) {
      sigaction(signo, &g_prev_actions[i], nullptr);
      raise(signo);
      return;
    }
  }
}

}  // namespace

void SetSignalFlightRecorder(FlightRecorder* rec) {
  g_signal_recorder.store(rec);  // seq_cst: pairs with the handler's seq_cst load
}

void ClearSignalFlightRecorder(FlightRecorder* rec) {
  FlightRecorder* expected = rec;
  g_signal_recorder.compare_exchange_strong(expected, nullptr);
  // A handler on another thread may have loaded `rec` (or a predecessor)
  // just before the clear — e.g. the launcher's SIGTERM landing exactly
  // while the user thread tears the Core down. Wait it out before the
  // caller (~FlightRecorder) frees the ring; SignalDump is bounded file
  // I/O, so this terminates.
  while (g_handler_active.load() > 0) {
    struct timespec ts = {0, 1000000};  // 1 ms
    nanosleep(&ts, nullptr);
  }
}

void InstallFlightSignalHandlers() {
  if (g_handlers_installed.exchange(true)) return;
  struct sigaction sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sa_handler = FlightSignalHandler;
  sigemptyset(&sa.sa_mask);
  // SIGPROF is masked for the dump's duration: the sampling profiler
  // (profiler.h) may be firing at HVDTPU_PROF_HZ on this very thread, and
  // a sampler interrupting the fatal dump's write loop would stretch the
  // one chance at a post-mortem (pinned by the unit-test re-entrancy
  // storm; docs/profiling.md "Signal coexistence").
  sigaddset(&sa.sa_mask, SIGPROF);
  // No SA_RESETHAND: the handler restores the saved disposition itself so
  // it can chain an application handler instead of always going to default.
  for (size_t i = 0;
       i < sizeof(kFlightSignals) / sizeof(kFlightSignals[0]); ++i) {
    sigaction(kFlightSignals[i], &sa, &g_prev_actions[i]);
  }
}

}  // namespace hvdtpu
