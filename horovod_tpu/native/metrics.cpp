// Metrics registry implementation: see metrics.h for the concurrency model.

#include "metrics.h"

#include <cassert>
#include <cinttypes>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace hvdtpu {

namespace {

// Render a double the way Prometheus clients do: integers without a decimal
// point, everything else with enough digits to round-trip, +Inf spelled out.
std::string RenderValue(double v) {
  if (std::isinf(v)) return v > 0 ? "+Inf" : "-Inf";
  if (std::isnan(v)) return "NaN";
  if (v == static_cast<int64_t>(v) && std::fabs(v) < 1e15) {
    char buf[32];
    snprintf(buf, sizeof(buf), "%" PRId64, static_cast<int64_t>(v));
    return buf;
  }
  // Shortest representation that round-trips (so bucket bounds render as
  // "0.0004", not "0.00040000000000000002").
  char buf[64];
  for (int prec = 1; prec <= 17; ++prec) {
    snprintf(buf, sizeof(buf), "%.*g", prec, v);
    if (strtod(buf, nullptr) == v) break;
  }
  return buf;
}

std::string EscapeLabelValue(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

}  // namespace

std::string RenderLabels(const MetricLabels& labels) {
  if (labels.empty()) return "";
  std::string out = "{";
  bool first = true;
  for (const auto& kv : labels) {
    if (!first) out += ",";
    first = false;
    out += kv.first + "=\"" + EscapeLabelValue(kv.second) + "\"";
  }
  out += "}";
  return out;
}

std::vector<double> LatencyBuckets() {
  // 100 us .. 102 s in x4 steps: wide enough to span a 4 KB shm hop and a
  // stalled multi-GB ring without exceeding 11 buckets per series.
  return {1e-4, 4e-4, 1.6e-3, 6.4e-3, 2.56e-2, 1.024e-1, 4.096e-1, 1.6384,
          6.5536, 26.2144, 104.8576};
}

std::vector<double> BytesBuckets() {
  // 256 B .. 1 GB in x4 steps.
  return {256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
          67108864, 268435456, 1073741824};
}

Metrics::Family* Metrics::Resolve(const std::string& name,
                                  const std::string& help, Kind kind) {
  auto it = families_.find(name);
  if (it == families_.end()) {
    Family& f = families_[name];
    f.kind = kind;
    f.help = help;
    return &f;
  }
  assert(it->second.kind == kind && "metric re-registered with another type");
  if (it->second.kind != kind) return nullptr;
  return &it->second;
}

Counter* Metrics::GetCounter(const std::string& name, const std::string& help,
                             const MetricLabels& labels) {
  MutexLock lk(mu_);
  Family* f = Resolve(name, help, Kind::COUNTER);
  if (f == nullptr) { static Counter orphan; return &orphan; }
  Series& s = f->series[RenderLabels(labels)];
  if (!s.counter) s.counter.reset(new Counter());
  return s.counter.get();
}

Gauge* Metrics::GetGauge(const std::string& name, const std::string& help,
                         const MetricLabels& labels) {
  MutexLock lk(mu_);
  Family* f = Resolve(name, help, Kind::GAUGE);
  if (f == nullptr) { static Gauge orphan; return &orphan; }
  Series& s = f->series[RenderLabels(labels)];
  if (!s.gauge) s.gauge.reset(new Gauge());
  return s.gauge.get();
}

Histogram* Metrics::GetHistogram(const std::string& name,
                                 const std::string& help,
                                 const std::vector<double>& bounds,
                                 const MetricLabels& labels) {
  MutexLock lk(mu_);
  Family* f = Resolve(name, help, Kind::HISTOGRAM);
  if (f == nullptr) { static Histogram orphan({1.0}); return &orphan; }
  Series& s = f->series[RenderLabels(labels)];
  if (!s.histogram) s.histogram.reset(new Histogram(bounds));
  return s.histogram.get();
}

size_t Metrics::SeriesCount() const {
  MutexLock lk(mu_);
  size_t n = 0;
  for (const auto& kv : families_) n += kv.second.series.size();
  return n;
}

std::string Metrics::Dump() const {
  MutexLock lk(mu_);
  std::string out;
  out.reserve(4096);
  for (const auto& fam : families_) {
    const std::string& name = fam.first;
    const Family& f = fam.second;
    const char* type = f.kind == Kind::COUNTER ? "counter"
                       : f.kind == Kind::GAUGE ? "gauge"
                                               : "histogram";
    out += "# HELP " + name + " " + f.help + "\n";
    out += "# TYPE " + name + " " + std::string(type) + "\n";
    for (const auto& ser : f.series) {
      const std::string& lbl = ser.first;
      const Series& s = ser.second;
      if (f.kind == Kind::COUNTER) {
        out += name + lbl + " " +
               RenderValue(static_cast<double>(s.counter->Get())) + "\n";
      } else if (f.kind == Kind::GAUGE) {
        out += name + lbl + " " + RenderValue(s.gauge->Get()) + "\n";
      } else {
        const Histogram& h = *s.histogram;
        // _bucket series: cumulative counts, le label appended to (inside)
        // the existing label set.
        int64_t cum = 0;
        auto bucket_line = [&](const std::string& le, int64_t count) {
          std::string l = lbl.empty()
                              ? "{le=\"" + le + "\"}"
                              : lbl.substr(0, lbl.size() - 1) + ",le=\"" +
                                    le + "\"}";
          out += name + "_bucket" + l + " " +
                 RenderValue(static_cast<double>(count)) + "\n";
        };
        for (size_t i = 0; i < h.bounds().size(); ++i) {
          cum += h.BucketCount(i);
          bucket_line(RenderValue(h.bounds()[i]), cum);
        }
        cum += h.BucketCount(h.bounds().size());
        bucket_line("+Inf", cum);
        out += name + "_sum" + lbl + " " + RenderValue(h.Sum()) + "\n";
        out += name + "_count" + lbl + " " +
               RenderValue(static_cast<double>(cum)) + "\n";
      }
    }
  }
  return out;
}

}  // namespace hvdtpu
