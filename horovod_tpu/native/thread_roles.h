// Thread-role annotations (docs/static-analysis.md "Thread roles").
//
// The TSA layer (common.h) covers every MUTEX; the lock-free subsystems
// (flight recorder, perf/grad slots, shm SPSC rings, the profiler sample
// ring) rely on single-driver contracts instead. These macros turn those
// comments into machine-checked metadata, enforced by
// scripts/check_threadroles.py:
//
//   HVDTPU_CALLED_ON(role)  — this function may only be called by threads
//                             running as `role`. Roles: background (the
//                             core's collective-driving loop, including the
//                             Python host thread strictly before the loop
//                             starts), user (Python caller threads), signal
//                             (async-signal handlers), any (thread-safe).
//   HVDTPU_ROLE(role)       — this function IS a role's entry point (thread
//                             loop or signal handler): its body executes as
//                             `role`, seeding the checker's call-graph walk.
//
// The checker rejects calls from role A into functions pinned to role B,
// requires every public method of the lock-free subsystem headers to declare
// a role, and forbids anything reachable from a `signal` root from touching
// malloc/locks/stdio (the flight recorder's fatal-handler contract). Under
// clang both expand to annotate attributes so `-ast-dump=json` carries them;
// under gcc they compile to nothing.
//
// Kept in its own header (not common.h) so the dependency-light headers —
// transport.h, shm_transport.h, flightrec.h, perfstats.h, gradstats.h,
// tracing.h — can annotate without pulling in common.h's <thread>/<mutex>
// transitive weight.
#pragma once

#if defined(__clang__)
#define HVDTPU_CALLED_ON(role) \
  __attribute__((annotate("hvdtpu_called_on:" #role)))
#define HVDTPU_ROLE(role) __attribute__((annotate("hvdtpu_role:" #role)))
#else
#define HVDTPU_CALLED_ON(role)  // no-op under gcc; checked by lint
#define HVDTPU_ROLE(role)       // no-op under gcc; checked by lint
#endif
