// Core types for the native runtime.
//
// TPU-native rebuild of the reference's horovod/common/common.h (Status,
// DataType enum, TensorTableEntry) — redesigned around a TCP control/data plane
// instead of MPI/NCCL. No external dependencies beyond POSIX + libstdc++.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------------------
// Clang Thread Safety Analysis annotations (docs/static-analysis.md).
//
// Compiled with `clang++ -Wthread-safety -Werror=thread-safety` (the
// `make analyze` target) these attributes turn the locking discipline into a
// compile-time contract: every GUARDED_BY field must be touched under its
// mutex, every REQUIRES function must be called with it held. Under gcc (the
// default build) they expand to nothing. Pattern follows the canonical
// mutex.h from the Clang TSA documentation / Abseil.
// ---------------------------------------------------------------------------
#if defined(__clang__)
#define HVDTPU_TSA(x) __attribute__((x))
#else
#define HVDTPU_TSA(x)  // no-op under gcc
#endif

#define CAPABILITY(x) HVDTPU_TSA(capability(x))
#define SCOPED_CAPABILITY HVDTPU_TSA(scoped_lockable)
#define GUARDED_BY(x) HVDTPU_TSA(guarded_by(x))
#define PT_GUARDED_BY(x) HVDTPU_TSA(pt_guarded_by(x))
#define ACQUIRE(...) HVDTPU_TSA(acquire_capability(__VA_ARGS__))
#define RELEASE(...) HVDTPU_TSA(release_capability(__VA_ARGS__))
#define TRY_ACQUIRE(...) HVDTPU_TSA(try_acquire_capability(__VA_ARGS__))
#define REQUIRES(...) HVDTPU_TSA(requires_capability(__VA_ARGS__))
#define EXCLUDES(...) HVDTPU_TSA(locks_excluded(__VA_ARGS__))
#define ACQUIRED_BEFORE(...) HVDTPU_TSA(acquired_before(__VA_ARGS__))
#define ACQUIRED_AFTER(...) HVDTPU_TSA(acquired_after(__VA_ARGS__))
#define RETURN_CAPABILITY(x) HVDTPU_TSA(lock_returned(x))
#define NO_THREAD_SAFETY_ANALYSIS HVDTPU_TSA(no_thread_safety_analysis)

// Thread-role annotations (HVDTPU_CALLED_ON / HVDTPU_ROLE): the lock-free
// complement to the TSA layer above, in their own dependency-light header.
#include "thread_roles.h"

namespace hvdtpu {

// std::mutex carries no capability attribute under libstdc++, so the analysis
// cannot see through it; this annotated wrapper is what every lock in the
// native core uses. Same storage, same cost — the attributes are metadata.
class CAPABILITY("mutex") Mutex {
 public:
  void Lock() ACQUIRE() { mu_.lock(); }
  void Unlock() RELEASE() { mu_.unlock(); }
  // For CondVar: the analysis models waits as "lock stays held", which is
  // the contract the surrounding code relies on anyway.
  std::mutex& native_handle() { return mu_; }

 private:
  std::mutex mu_;
};

// RAII lock (std::lock_guard/std::unique_lock replacement). Supports the
// unlock-work-relock pattern (Timeline::WriterLoop): the analysis tracks the
// Unlock()/Lock() pair on the scoped object.
class SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) ACQUIRE(mu) : lk_(mu.native_handle()) {}
  ~MutexLock() RELEASE() {}
  void Unlock() RELEASE() { lk_.unlock(); }
  void Lock() ACQUIRE() { lk_.lock(); }
  std::unique_lock<std::mutex>& native_handle() { return lk_; }

 private:
  std::unique_lock<std::mutex> lk_;
};

// Condition variable over an annotated Mutex. Predicates are spelled as
// explicit while-loops at the call sites (not wait(lk, pred) lambdas): the
// analysis cannot see that a lambda body runs with the lock held, a loop in
// the REQUIRES-checked scope it can.
class CondVar {
 public:
  void Wait(MutexLock& lk) { cv_.wait(lk.native_handle()); }
  // Timed wait (spurious wakeups allowed, like Wait): for consumers that
  // drain on a period instead of being notified per item — the timeline
  // writer batches its queue this way so emitters never pay a wakeup.
  // wait_until on system_clock (not wait_for): libstdc++'s steady-clock
  // wait_for lowers to pthread_cond_clockwait, which this toolchain's TSan
  // does not intercept — it then loses the unlock inside the wait and
  // reports phantom double-locks/races. pthread_cond_timedwait (the
  // system_clock path) is intercepted; a realtime jump at worst stretches
  // one backstop period.
  void WaitForMs(MutexLock& lk, int ms) {
    cv_.wait_until(lk.native_handle(),
                   std::chrono::system_clock::now() +
                       std::chrono::milliseconds(ms));
  }
  void NotifyOne() { cv_.notify_one(); }
  void NotifyAll() { cv_.notify_all(); }

 private:
  std::condition_variable cv_;
};

// Mirrors the reference DataType enum (horovod/common/message.h:28-39).
enum class DataType : int32_t {
  UINT8 = 0,
  INT8 = 1,
  INT32 = 4,
  INT64 = 5,
  FLOAT16 = 6,
  FLOAT32 = 7,
  FLOAT64 = 8,
  BOOL = 9,
  BFLOAT16 = 10,
};

inline size_t DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::UINT8:
    case DataType::INT8:
    case DataType::BOOL:
      return 1;
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return 2;
    case DataType::INT32:
    case DataType::FLOAT32:
      return 4;
    case DataType::INT64:
    case DataType::FLOAT64:
      return 8;
  }
  return 0;
}

inline const char* DataTypeName(DataType dt) {
  switch (dt) {
    case DataType::UINT8: return "uint8";
    case DataType::INT8: return "int8";
    case DataType::INT32: return "int32";
    case DataType::INT64: return "int64";
    case DataType::FLOAT16: return "float16";
    case DataType::FLOAT32: return "float32";
    case DataType::FLOAT64: return "float64";
    case DataType::BOOL: return "bool";
    case DataType::BFLOAT16: return "bfloat16";
  }
  return "unknown";
}

// Collective op kinds (reference RequestType, message.h:50-52).
enum class OpType : int32_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  ALLTOALL = 3,
  REDUCESCATTER = 4,
  JOIN = 5,
};

// Reduction ops (matches horovod_tpu.ops.collectives.ReduceOp).
enum class ReduceOp : int32_t {
  AVERAGE = 0,
  SUM = 1,
  ADASUM = 2,
  MIN = 3,
  MAX = 4,
  PRODUCT = 5,
};

enum class StatusCode : int32_t {
  OK = 0,
  IN_PROGRESS = 1,
  INVALID_ARGUMENT = 2,
  ABORTED = 3,
  DUPLICATE_NAME = 4,
};

struct Status {
  StatusCode code = StatusCode::OK;
  std::string reason;
  static Status OK() { return Status{}; }
  static Status Error(StatusCode c, std::string r) { return Status{c, std::move(r)}; }
  bool ok() const { return code == StatusCode::OK; }
};

// Allocator whose construct() default-initializes (a no-op for trivial
// types) instead of value-initializing: resize() on a ByteBuf is "malloc
// only", no zero-fill pass. Working buffers about to be fully overwritten
// — the unfused allreduce output, the fusion buffer — must not pay a
// 16-64 MB memset per op; note that bulk copies into a ByteBuf should go
// through memcpy (or a fused kernel like CopyMomentsF32), not range
// insert: libstdc++ only lowers uninitialized range copies to the
// (non-temporal, large-copy-optimized) memmove for std::allocator.
template <typename T>
struct DefaultInitAllocator : std::allocator<T> {
  template <typename U>
  struct rebind {
    using other = DefaultInitAllocator<U>;
  };
  template <typename U>
  void construct(U* p) {
    ::new (static_cast<void*>(p)) U;
  }
  template <typename U, typename... Args>
  void construct(U* p, Args&&... args) {
    ::new (static_cast<void*>(p)) U(std::forward<Args>(args)...);
  }
};

// Collective working/output buffer (TensorEntry::output and the data
// plane's gather outputs): byte vector with uninitialized growth.
using ByteBuf = std::vector<uint8_t, DefaultInitAllocator<uint8_t>>;

// A pending collective on this rank (reference: TensorTableEntry, common.h:183).
struct TensorEntry {
  std::string name;
  OpType op_type = OpType::ALLREDUCE;
  ReduceOp reduce_op = ReduceOp::SUM;
  DataType dtype = DataType::FLOAT32;
  std::vector<int64_t> shape;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t root_rank = 0;            // broadcast
  std::vector<int32_t> splits;      // alltoall (may be empty = even)
  const void* input = nullptr;      // caller-owned until completion
  // Output buffer: owned by the core, copied out by the caller after wait.
  // ByteBuf (uninitialized growth): every fill path overwrites the full
  // range it sizes, so the old value-init zero pass was pure waste.
  ByteBuf output;
  int32_t handle = -1;
  // Absolute steady-clock us at Enqueue (Timeline::SteadyAbsUs): the start
  // of the tensor's FUSION-WAIT trace span — how long it sat queued/fusing
  // before its batch executed (docs/tracing.md). 0 on zombie stand-ins.
  int64_t enqueued_at_us = 0;

  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  int64_t byte_size() const {
    return num_elements() * static_cast<int64_t>(DataTypeSize(dtype));
  }
};

inline int64_t NumElements(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (auto d : shape) n *= d;
  return n;
}

inline std::string ShapeStr(const std::vector<int64_t>& shape) {
  std::string s = "[";
  for (size_t i = 0; i < shape.size(); ++i) {
    if (i) s += ", ";
    s += std::to_string(shape[i]);
  }
  return s + "]";
}

}  // namespace hvdtpu
