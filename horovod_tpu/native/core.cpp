// Native core runtime: tensor queue, background cycle loop, rank-0
// negotiation controller, fusion, and the ctypes-visible C API.
//
// TPU-native rebuild of the reference's L1/L2 layers:
//  - background loop + C API: horovod/common/operations.cc
//    (BackgroundThreadLoop :374, RunLoopOnce :591, C API :705-913,
//     EnqueueTensor* :917-1144)
//  - controller negotiation: horovod/common/controller.cc
//    (ComputeResponseList :63, ConstructResponse :380, FuseResponses :686,
//     IncrementTensorCount :838)
//  - tensor queue + duplicate detection: horovod/common/tensor_queue.{h,cc}
//  - stall inspector: horovod/common/stall_inspector.{h,cc}
//
// Differences by design: the control plane is plain TCP to rank 0 (no MPI/Gloo),
// the data plane is the TCP mesh in data_plane.cpp (no NCCL — on TPU the hot
// path is XLA/ICI; this core serves the eager, Horovod-parity process mode),
// and wire structs are the hand-rolled encoding in message.cpp (no flatbuffers).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <list>
#include <cmath>
#include <condition_variable>
#include <cstdarg>
#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <regex>
#include <set>
#include <thread>
#include <unordered_map>
#include <unordered_set>

#include "autotune.h"
#include "sha256.h"
#include "common.h"
#include "compressed.h"
#include "data_plane.h"
#include "gradstats.h"
#include "message.h"
#include "metrics.h"
#include "perfstats.h"
#include "profiler.h"
#include "socket_util.h"
#include "timeline.h"
#include "tracing.h"

#include <execinfo.h>
#include <poll.h>
#include <sys/resource.h>
#include <unistd.h>
#include <fcntl.h>

namespace hvdtpu {

namespace {

// Diagnostic terminate handler: print a native backtrace before aborting so
// an uncaught C++ exception in a background thread is debuggable in CI logs.
// Installed lazily from hvdtpu_create (not a static initializer — merely
// loading the library must not hijack the host process's handler) and chains
// to whatever handler was installed before.
std::terminate_handler g_prev_terminate = nullptr;

void TerminateWithBacktrace() {
  void* frames[64];
  int n = backtrace(frames, 64);
  fprintf(stderr, "[hvdtpu] fatal: uncaught exception; backtrace:\n");
  backtrace_symbols_fd(frames, n, 2);
  if (g_prev_terminate != nullptr && g_prev_terminate != TerminateWithBacktrace) {
    g_prev_terminate();
  }
  abort();
}

void InstallTerminateHandlerOnce() {
  static std::once_flag once;
  std::call_once(once, [] {
    g_prev_terminate = std::set_terminate(TerminateWithBacktrace);
  });
}

enum class CtrlMsg : int32_t {
  HELLO = 1,
  PEERS = 2,
  READY = 3,      // full requests + cache-hit names
  RESPONSES = 4,
  JOIN = 5,
  NEED_FULL = 6,  // coordinator -> worker: cache miss, resend full requests
  PARAMS = 7,     // coordinator -> worker: autotuned cycle time / fusion
  CLOCK = 8,      // clock-sync ping-pong: worker {t1} <-> coord {t1, t2}
  GRADCHECK = 9,  // worker -> coordinator: post-allreduce output
                  // fingerprint {seq, crc32c, tensor} for the cross-rank
                  // divergence probe (docs/numerics.md)
};

void LogWarn(int rank, const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "[hvdtpu %d] WARNING: ", rank);
  vfprintf(stderr, fmt, ap);
  fputc('\n', stderr);
  va_end(ap);
}

double NowSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void LogBadFrame(int rank, const char* where,
                 const std::vector<uint8_t>& frame) {
  char hex[3 * 64 + 1] = {0};
  size_t n = frame.size() < 64 ? frame.size() : 64;
  for (size_t i = 0; i < n; ++i) {
    snprintf(hex + 3 * i, 4, "%02x ", frame[i]);
  }
  fprintf(stderr,
          "[hvdtpu %d] ERROR: corrupt control frame in %s (len=%zu): %s\n",
          rank, where, frame.size(), hex);
}

// Compare everything that must match for a cached announcement to be valid
// (reference: ResponseCache keys on name + params, response_cache.cc).
bool SameRequest(const Request& a, const Request& b) {
  return a.op_type == b.op_type && a.reduce_op == b.reduce_op &&
         a.dtype == b.dtype && a.shape == b.shape &&
         a.prescale == b.prescale && a.postscale == b.postscale &&
         a.root_rank == b.root_rank && a.splits == b.splits;
}

// Defined below Core (same anonymous namespace); ExecuteResponse needs it
// for reduce-scatter prescale/postscale before the definition appears.
void ScaleBuffer(void* data, int64_t count, DataType dtype, double factor);

}  // namespace

// LRU response cache (reference: horovod/common/response_cache.{h,cc}).
// The reference synchronizes bit-indexed cache entries with two bitvector
// allreduces per cycle; with a TCP star control plane the race-free analog is
// name-keyed: workers announce just the tensor NAME when their request is
// byte-identical to the last one, the coordinator re-materializes the full
// request from its own cache, and a miss (eviction divergence) is repaired by
// a NEED_FULL round trip instead of a protocol error.
class RequestCache {
 public:
  void SetCapacity(int64_t cap) { capacity_ = cap; }
  // Autotune's categorical switch (reference: CategoricalParameter cache
  // on/off, parameter_manager.h:225). capacity==0 (user opt-out) always
  // wins. Toggling only gates the WIRE fast path (enabled()); both sides
  // keep tracking() entries while disabled — otherwise a request that
  // changed during a disabled window would leave a stale-but-valid entry
  // behind, and a later bare-name hit on it is NOT repaired by NEED_FULL
  // (that round trip only covers absent entries).
  void SetEnabled(bool on) { on_ = on; }
  bool enabled() const { return on_ && capacity_ > 0; }
  bool tracking() const { return capacity_ > 0; }

  // Worker side: true if `q` matches the cached entry for its name (-> the
  // bare name suffices on the wire). Updates/inserts the entry otherwise.
  bool CheckAndPut(const Request& q) {
    auto it = map_.find(q.name);
    if (it != map_.end()) {
      Touch(it);
      if (SameRequest(it->second.req, q)) return true;
      it->second.req = q;
      return false;
    }
    Insert(q.name).req = q;
    return false;
  }

  // Coordinator side: remember rank r's full request for this name.
  void PutRank(const Request& q) {
    auto it = map_.find(q.name);
    Entry& e = it != map_.end() ? (Touch(it), it->second) : Insert(q.name);
    if (static_cast<size_t>(q.rank) >= e.by_rank.size()) {
      e.by_rank.resize(q.rank + 1);
      e.valid.resize(q.rank + 1, false);
    }
    e.by_rank[q.rank] = q;
    e.valid[q.rank] = true;
  }

  // Coordinator side: recover rank r's request from a bare-name hit.
  bool GetRank(const std::string& name, int rank, Request* out) {
    auto it = map_.find(name);
    if (it == map_.end()) return false;
    Touch(it);
    Entry& e = it->second;
    if (static_cast<size_t>(rank) >= e.valid.size() || !e.valid[rank]) {
      return false;
    }
    *out = e.by_rank[rank];
    return true;
  }

  void Erase(const std::string& name) {
    auto it = map_.find(name);
    if (it == map_.end()) return;
    lru_.erase(it->second.pos);
    map_.erase(it);
  }

 private:
  struct Entry {
    Request req;                    // worker side: my last-sent request
    std::vector<Request> by_rank;   // coordinator side
    std::vector<bool> valid;
    std::list<std::string>::iterator pos;
  };
  using Map = std::unordered_map<std::string, Entry>;

  void Touch(Map::iterator it) {
    lru_.erase(it->second.pos);
    lru_.push_front(it->first);
    it->second.pos = lru_.begin();
  }
  Entry& Insert(const std::string& name) {
    while (static_cast<int64_t>(map_.size()) >= capacity_ && !lru_.empty()) {
      map_.erase(lru_.back());
      lru_.pop_back();
    }
    lru_.push_front(name);
    Entry& e = map_[name];
    e.pos = lru_.begin();
    return e;
  }

  int64_t capacity_ = 1024;  // reference default: HOROVOD_CACHE_CAPACITY
  bool on_ = true;
  Map map_;
  std::list<std::string> lru_;
};

// Batched control-plane sender (HVDTPU_CTRL_BATCH): per-tensor READY /
// RESPONSES / NEED_FULL plus the CLOCK and GRADCHECK piggyback frames queued
// during one background cycle coalesce into ONE vectored SendAllVec per peer
// at flush — one syscall per peer per cycle instead of one per message,
// which is where w16+ coordination cost actually lives. The wire stream is
// byte-identical to a SendFrame sequence (each frame keeps its own u64
// length prefix), so the receive side is untouched. Owned by the background
// thread, like the fds it writes.
class CtrlOutbox {
 public:
  void set_enabled(bool on) { enabled_ = on; }
  bool enabled() const { return enabled_; }
  void set_counters(Counter* frames, Counter* batches) {
    frames_total_ = frames;
    batches_total_ = batches;
  }

  // Queue one frame for fd. Disabled -> immediate SendFrame (same return);
  // enabled -> queued, returns 0, send failures surface at Flush.
  int Queue(int fd, std::vector<uint8_t> payload) {
    if (frames_total_ != nullptr) frames_total_->Inc();
    if (!enabled_) {
      if (batches_total_ != nullptr) batches_total_->Inc();
      return SendFrame(fd, payload);
    }
    queues_[fd].push_back(std::move(payload));
    return 0;
  }

  bool pending() const { return !queues_.empty(); }

  // Ship everything queued, one vectored send per fd (chunked well under
  // POSIX's IOV_MAX floor of 1024). Returns 0 when every peer's send
  // succeeded, else -1 with *bad_fd naming the first failure; the remaining
  // peers still flush — one dead worker must not strand a broadcast.
  int Flush(int* bad_fd) {
    int rc = 0;
    for (auto& kv : queues_) {
      const int fd = kv.first;
      auto& frames = kv.second;
      // Length prefixes must outlive the iovecs that point at them.
      std::vector<uint64_t> lens(frames.size());
      for (size_t i = 0; i < frames.size(); ++i) lens[i] = frames[i].size();
      size_t done = 0;
      bool fd_ok = true;
      while (fd_ok && done < frames.size()) {
        const size_t n = std::min<size_t>(frames.size() - done, 500);
        std::vector<iovec> iov;
        iov.reserve(2 * n);
        for (size_t i = done; i < done + n; ++i) {
          iov.push_back({&lens[i], sizeof(uint64_t)});
          if (!frames[i].empty()) {
            iov.push_back({frames[i].data(), frames[i].size()});
          }
        }
        if (batches_total_ != nullptr) batches_total_->Inc();
        if (SendAllVec(fd, iov.data(), static_cast<int>(iov.size())) != 0) {
          if (rc == 0 && bad_fd != nullptr) *bad_fd = fd;
          rc = -1;
          fd_ok = false;
        }
        done += n;
      }
    }
    queues_.clear();
    return rc;
  }

  // Drop whatever is queued for a disconnecting peer.
  void Forget(int fd) { queues_.erase(fd); }

 private:
  bool enabled_ = true;
  std::map<int, std::vector<std::vector<uint8_t>>> queues_;
  Counter* frames_total_ = nullptr;
  Counter* batches_total_ = nullptr;
};

struct CoreConfig {
  int rank = 0;
  int size = 1;
  int local_rank = 0;
  int local_size = 1;
  int cross_rank = 0;
  int cross_size = 1;
  std::string coord_host = "127.0.0.1";
  int coord_port = 0;
  std::string my_host = "127.0.0.1";
  double cycle_time_ms = 1.0;
  int64_t fusion_threshold = 64 * 1024 * 1024;  // reference default, 64 MB
  std::string timeline_path;
  bool timeline_mark_cycles = false;
  // Distributed tracing (docs/tracing.md): every Nth collective op gets
  // per-hop child spans on the timeline (0 = spans off; the op-level
  // phases always ride a running timeline). Clock sync against rank 0 is
  // refreshed through the control plane on this period while tracing.
  int64_t trace_sample = 0;
  double clock_sync_interval_secs = 30.0;
  // Always-on flight recorder (flightrec.h; docs/fault-tolerance.md
  // "Post-mortem debugging"): ring capacity in records (0 disables —
  // HVDTPU_FLIGHTREC=0) and the dump directory for the automatic
  // abort/stall/fatal-signal dumps (empty = in-memory only; Snapshot and
  // /debugz still work).
  int64_t flightrec_events = 4096;
  std::string flightrec_dir;
  // Always-on perf attribution (perfstats.h; docs/observability.md). The
  // streaming baselines are on by default (HVDTPU_PERFSTATS=0 disables);
  // the slowdown sentry fires past slowdown_pct once a key has
  // min_samples. perf_profile_path: where Shutdown persists this rank's
  // per-key baselines + anomaly log for the cross-run regression sentry
  // (HVDTPU_PERF_PROFILE_DIR -> perf_profile.<rank>.json; empty = skip).
  bool perfstats = true;
  double perf_slowdown_pct = 50.0;
  int64_t perf_min_samples = 20;
  std::string perf_profile_path;
  // Always-available sampling profiler (profiler.h; docs/profiling.md).
  // Enabled by default: the subsystem costs nothing until a window runs
  // (HVDTPU_PROF=0 compiles it down to one branch per entry point).
  // prof_hz/prof_capacity <= 0 keep the defaults; prof_clock: 0 cpu,
  // 1 wall. prof_path: where Shutdown writes prof.<rank>.folded
  // (HVDTPU_PROF_DIR -> hvdrun --profile; empty = skip); a non-empty path
  // also starts the window at Start — the whole-job profile the runner
  // collects.
  bool prof = true;
  int prof_hz = 0;
  int64_t prof_capacity = 0;
  int32_t prof_clock = 0;
  std::string prof_path;
  double stall_warn_secs = 60.0;  // reference HOROVOD_STALL_CHECK_TIME
  // Shared job secret (reference: runner/common/util/secret.py). When set,
  // every HELLO must carry an HMAC proof; unauthenticated connections are
  // rejected. Empty = auth disabled (un-launched / single-host debugging).
  std::string secret;
  // Reference HOROVOD_STALL_SHUTDOWN_TIME: after this long stalled, break
  // the world instead of hanging forever. The reference defaults this to 0
  // (disabled), which left the escalation dead code in practice; here the
  // default is AUTO (< 0): 10x the warning threshold, so a wedged world
  // always breaks eventually. 0 still disables explicitly.
  double stall_shutdown_secs = -1.0;
  // Failure detection (docs/fault-tolerance.md): HVDTPU_FAILURE_DETECT_MS
  // bounds how long a peer death can go unnoticed on a blocked transport
  // op, HVDTPU_FORMUP_TIMEOUT_SECONDS bounds rendezvous/mesh form-up.
  int64_t failure_detect_ms = 500;
  double formup_timeout_secs = 60.0;
  // Transport-level no-progress deadline (HVDTPU_READ_DEADLINE_SECONDS):
  // a lane that is open but moves ZERO bytes for this long mid-collective
  // is declared dead — the only way to catch a hung-but-alive peer or a
  // silent partition (no EOF ever arrives). 0 disables. Progress resets
  // the clock, so long transfers on slow links are safe.
  double read_deadline_secs = 10.0;
  // Armed fault injection (HVDTPU_CHAOS -> hvdtpu_set_chaos), NONE normally.
  ChaosSpec chaos;
  int64_t cache_capacity = 1024;  // reference HOROVOD_CACHE_CAPACITY
  // Autotune (reference HOROVOD_AUTOTUNE_* knobs, operations.cc:474-532).
  bool autotune = false;
  std::string autotune_log;
  int autotune_warmup_samples = 3;
  int autotune_cycles_per_sample = 50;
  int autotune_max_samples = 30;
  double autotune_gp_noise = 0.2;
  // Allreduce algorithm selection (HVDTPU_ALLREDUCE_ALGO; data_plane.h
  // AllreduceAlgo). Crossover/segment <= 0 keep the data-plane defaults.
  int32_t allreduce_algo = 0;  // AUTO
  int64_t allreduce_crossover = 0;
  int64_t allreduce_segment = 0;
  // Scale-out knobs. allreduce_sa_group (HVDTPU_ALLREDUCE_SA_GROUP): the
  // group-size floor at which AUTO's big-message dispatch prefers
  // scatter-allgather over the ring; < 0 keeps the data-plane default,
  // 0 removes scatter-allgather from the AUTO menu entirely. ctrl_batch
  // (HVDTPU_CTRL_BATCH): nonzero coalesces each background cycle's
  // control-plane frames into one vectored send per peer.
  int64_t allreduce_sa_group = -1;
  int32_t ctrl_batch = 1;
  // Broadcast flat/tree crossover (HVDTPU_BCAST_FLAT_MAX; data_plane.h):
  // payloads at or below this many bytes take the flat root-fanout, larger
  // ones the binomial tree. < 0 keeps the data-plane default, 0 forces the
  // tree for every size.
  int64_t bcast_flat_max = -1;
  // Transport subsystem (HVDTPU_SHM / HVDTPU_SHM_RING_BYTES /
  // HVDTPU_ALLREDUCE_HIER; data_plane.h). shm defaults on — same-host pairs
  // negotiate shared-memory lanes at Connect and fall back to TCP when
  // either side fails setup. hier: 0 off, 1 on, 2 auto (autotuner-owned).
  int32_t shm_enabled = 1;
  int64_t shm_ring_bytes = 0;
  int32_t allreduce_hier = 2;
  // Zero-copy transport lane (HVDTPU_TCP_ZEROCOPY / HVDTPU_SHM_NUMA /
  // HVDTPU_DOORBELL_BATCH; transport.h ZeroCopyMode, shm_transport.h
  // ShmNumaMode). tcp_zerocopy: 0 auto, 1 on, 2 off, 3 uring; shm_numa:
  // 0 auto, 1 on, 2 off; doorbell_batch: futex-wake coalescing window in
  // bytes (0 = lane default, 1 = wake per cursor advance).
  int32_t tcp_zerocopy = 0;
  int32_t shm_numa = 0;
  int64_t doorbell_batch = 0;
  // Wire compression (HVDTPU_COMPRESSION; compressed.h WireCompression:
  // 0 none, 1 fp16, 2 int8, 3 int4, 4 auto/autotuned). Applies to fp32
  // SUM/AVERAGE allreduces at or above min_bytes whose tensor names all
  // miss the skip regex (biases/norms stay dense, reference: the fork's
  // per-layer ignore rules).
  int32_t wire_compression = 0;
  int64_t compression_min_bytes = 1024;
  std::string compression_skip_regex;
  // Numerical-health observability (gradstats.h; docs/numerics.md). On by
  // default: the moments fold into passes the core already pays for.
  // nancheck: 0 off, 1 warn (default), 2 abort — what the first NaN/Inf
  // gradient does. gradcheck_sample: fingerprint every Nth allreduce's
  // post-reduce output and compare across ranks through the control plane
  // (0 disables the divergence probe; must be uniform across ranks, which
  // the launcher's env broadcast guarantees). grad_profile_path: where
  // Shutdown persists grad_profile.<rank>.json (HVDTPU_GRAD_PROFILE_DIR;
  // empty = skip) for scripts/grad_diff.py.
  bool gradstats = true;
  int32_t nancheck = 1;
  int64_t gradcheck_sample = 64;
  std::string grad_profile_path;
};

class Core {
 public:
  explicit Core(const CoreConfig& cfg)
      : cfg_(cfg),
        data_plane_(cfg.rank, cfg.size),
        cycle_time_ms_(cfg.cycle_time_ms),
        fusion_threshold_(cfg.fusion_threshold) {}

  ~Core() {
    Shutdown();
    CloseFd(wake_pipe_[0]);
    CloseFd(wake_pipe_[1]);
  }

  Status Start() EXCLUDES(mu_);
  void Shutdown() EXCLUDES(mu_);

  // Returns handle >= 0, or Status error via *status.
  int64_t Enqueue(TensorEntry entry, Status* status) EXCLUDES(mu_);
  // Grouped-collective enqueue window (hvd.grouped_* batched negotiation):
  // between GroupBegin and GroupEnd, enqueued entries are withheld from the
  // control-plane announcement drain, so the whole group lands in ONE
  // background cycle once released — one READY frame up, one RESPONSES
  // frame down for N tensors (the READY/RESPONSES frames already
  // name-coalesce per cycle), and the coordinator's fusion lookahead sees
  // every member at once. The caller MUST pair Begin with End (the Python
  // context manager guarantees it); waiting on a held handle would hang.
  void GroupBegin() EXCLUDES(mu_);
  void GroupEnd() EXCLUDES(mu_);
  Status WaitHandle(int64_t handle) EXCLUDES(mu_);
  int PollHandle(int64_t handle) EXCLUDES(mu_);
  int64_t ResultBytes(int64_t handle) EXCLUDES(mu_);
  // Copies result and releases the handle.
  Status CopyResult(int64_t handle, void* dst, int64_t capacity)
      EXCLUDES(mu_);
  // Blocks until all ranks joined; returns the last joined rank.
  int64_t Join() EXCLUDES(mu_);

  // Runtime timeline control (reference: horovod_start_timeline /
  // horovod_stop_timeline, operations.cc:735-790). Thread-safe: the request
  // is applied by the background thread at the top of its next cycle so the
  // Timeline object stays single-owner. trace_sample: -1 = keep the
  // configured span-sampling rate, otherwise the new every-Nth-op rate
  // (hvd.start_trace).
  void RequestTimeline(bool start, const std::string& path, bool mark_cycles,
                       int64_t trace_sample = -1) EXCLUDES(timeline_req_mu_);
  // Clock offset vs rank 0 (offset ± error, microseconds; err < 0 = never
  // synced). Lock-free, callable from any thread (C API introspection).
  void ClockOffset(int64_t* offset_us, int64_t* err_us) const {
    if (offset_us != nullptr) {
      *offset_us = clock_offset_us_.load(std::memory_order_relaxed);
    }
    if (err_us != nullptr) {
      *err_us = clock_err_us_.load(std::memory_order_relaxed);
    }
  }
  // Current (possibly autotuned) loop parameters, for tests/introspection.
  double CurrentCycleTimeMs() EXCLUDES(mu_);
  int64_t CurrentFusionThreshold() EXCLUDES(mu_);
  // Cumulative data-plane payload accounting. Thin shim over the metrics
  // registry (hvdtpu_allreduce_{raw,wire}_bytes_total) — the registry is
  // the single source of truth; this keeps the pre-metrics C/Python API
  // stable. Lock-free counters, safe from user threads while ops run.
  void WireStats(int64_t* raw_bytes, int64_t* wire_bytes) {
    *raw_bytes = data_plane_.total_raw_bytes();
    *wire_bytes = data_plane_.total_wire_bytes();
  }
  // ZeRO-1 memory proof (docs/optimizer.md): the Python sharded optimizer
  // reports its resident optimizer-state footprint here so the PR-11 memory
  // gauges can attest the 1/world claim next to hvdtpu_rss_bytes. Lazy
  // GetGauge (registry lock) + atomic Gauge::Set — safe from user threads
  // at any point in the core lifecycle, no member caching needed.
  void SetOptimizerStateBytes(int64_t bytes) {
    metrics_
        .GetGauge("hvdtpu_optimizer_state_bytes",
                  "Resident optimizer-state bytes on this rank (ZeRO-1 "
                  "shards report ~1/world of the replicated footprint)")
        ->Set(static_cast<double>(bytes));
  }
  // Prometheus text exposition of every registered series (C API:
  // hvdtpu_metrics_dump; served over HTTP by horovod_tpu/observability.py).
  // Callable from any thread at any point in the core lifecycle.
  std::string MetricsDump() { return metrics_.Dump(); }
  // Flight-recorder surface (C API hvdtpu_flightrec_*; /debugz). Callable
  // from any thread at any point in the core lifecycle — a disabled or
  // unstarted recorder snapshots to "".
  std::string FlightSnapshot() {
    return flightrec_.Snapshot(DumpReason::ON_DEMAND, -1);
  }
  bool FlightDumpToFile(const char* path) {
    const bool ok = flightrec_.DumpToFile(DumpReason::ON_DEMAND, -1,
                                          path != nullptr ? path : "");
    if (ok && m_flightrec_dumps_ != nullptr) m_flightrec_dumps_->Inc();
    return ok;
  }
  // Perf-attribution surface (C API hvdtpu_perfstats_snapshot; /perfz).
  // Keyed-baseline snapshot as JSON — lock-free reads, callable from any
  // thread at any point in the core lifecycle.
  std::string PerfSnapshot() { return perfstats_.SnapshotJson(); }
  // Numerical-health surface (C API hvdtpu_gradstats_snapshot; /gradz /
  // hvd.grad_report()). Same lock-free-read contract as PerfSnapshot.
  std::string GradSnapshot() { return gradstats_.SnapshotJson(); }
  // Sampling-profiler surface (C API hvdtpu_profiler_*; /profz /
  // hvd.profile()). All callable from any thread at any point in the core
  // lifecycle — a disabled profiler starts/stops as no-ops and snapshots
  // the "enabled: false" stub.
  void ProfilerStart() { profiler_.Start(); }
  void ProfilerStop() { profiler_.Stop(); }
  bool ProfilerRunning() const { return profiler_.running(); }
  std::string ProfilerSnapshot() const { return profiler_.FoldedJson(); }
  CoreConfig* mutable_config() { return &cfg_; }  // pre-Start() only

 private:
  void BackgroundLoop() EXCLUDES(mu_);
  void WaitForWork() EXCLUDES(mu_);  // poll control fds + wake pipe
  void Wake();                       // nudge the background loop
  void PumpControlPlane() EXCLUDES(mu_);  // role-dependent per-cycle work
  void FlushCtrlOutbox();                  // ship queued control frames
  void CoordinatorIngest() EXCLUDES(mu_);  // rank 0: read worker frames
  // rank 0: match + fuse + broadcast
  void CoordinatorEmitResponses() EXCLUDES(mu_);
  void WorkerSendReady(std::vector<Request> reqs,
                       std::vector<std::string> cached);
  void HandleReadyRequests(std::vector<Request> reqs);  // coordinator table
  Response BuildResponse(const std::string& name);
  void ExecuteResponseList(const std::vector<Response>& list) EXCLUDES(mu_);
  void ExecuteResponse(const Response& resp) EXCLUDES(mu_);
  void ExecuteFusedAllreduce(const Response& resp,
                             std::vector<TensorEntry*>& entries,
                             WireCompression comp) EXCLUDES(mu_);
  void CompleteEntry(TensorEntry* e, const Status& st) EXCLUDES(mu_);
  void CheckStalls();
  // Effective stall-shutdown window: AUTO (< 0) resolves to 10x the warning
  // threshold so the escalation is never silently dead; 0 disables.
  double EffectiveStallShutdownSecs() const {
    return cfg_.stall_shutdown_secs < 0 ? 10.0 * cfg_.stall_warn_secs
                                        : cfg_.stall_shutdown_secs;
  }
  // A data-plane op failed with the plane aborted: a peer died (or tripped
  // its liveness deadline) mid-collective. Count it, make sure every lane
  // is broken (the cascade that unblocks the rest of the world), and fail
  // over so elastic mode can catch HvdTpuInternalError and re-rendezvous.
  void HandleDataPlaneFailure(const Status& st) EXCLUDES(mu_);

 public:
  // Elastic recovery accounting (C API hvdtpu_observe_recovery): the Python
  // runtime measures detection -> successful re-initialization and records
  // it against the NEW core's registry, so hvd.metrics() after a recovery
  // shows both the failure count and the recovery latency.
  void ObserveRecovery(double secs) {
    if (m_recovery_seconds_ != nullptr) m_recovery_seconds_->Observe(secs);
    if (m_failures_detected_ != nullptr) m_failures_detected_->Inc();
  }

 private:
  // Effective wire compression for one negotiated allreduce: the configured
  // (or autotuned) mode, gated on dtype fp32, op SUM/AVERAGE, total payload
  // >= compression_min_bytes, and no tensor name matching the skip regex.
  // Every input is identical on every rank (the mode arrives in config or a
  // PARAMS frame, the rest comes from the broadcast Response), so all ranks
  // resolve the same answer — a split would desynchronize the wire format.
  WireCompression EffectiveCompression(const Response& resp,
                                       int64_t total_bytes);

  CoreConfig cfg_;
  DataPlane data_plane_;
  Timeline timeline_;
  // Always-on flight recorder: the data plane records hops into it, this
  // class records op begin/end + fusion waits + stalls, and the fatal
  // paths dump it (FailAllOutstanding, CheckStalls escalation, the signal
  // handlers flightrec.cpp installs).
  FlightRecorder flightrec_;
  // Always-on perf attribution: streaming per-key baselines fed after
  // every completed op; the slowdown sentry rides ObserveOp
  // (docs/observability.md "Live perf attribution").
  PerfStats perfstats_;
  // Anomaly log for perf_profile.<rank>.json (background thread only,
  // bounded; written out by Shutdown after the loop is joined).
  std::vector<std::string> perf_anomaly_log_;
  bool perf_profile_written_ = false;
  void WritePerfProfile();
  // Numerical-health telemetry (gradstats.h; docs/numerics.md): per-tensor
  // gradient moments + per-key quantization quality + the cross-rank
  // divergence probe. grad_quality_ is the per-op accumulator the data
  // plane's WireCompress calls fill (background thread only).
  GradStats gradstats_;
  GradQuality grad_quality_;
  bool grad_profile_written_ = false;
  void WriteGradProfile();
  // Divergence probe state: every executed (non-Adasum) allreduce bumps
  // gradcheck_seq_ identically on every rank; sampled ops fingerprint
  // their post-reduce output and the coordinator majority-votes per seq.
  // Both are background-thread-owned, like message_table_.
  int64_t gradcheck_seq_ = 0;
  struct GradcheckSlot {
    std::string name;
    int64_t bytes = 0;
    std::vector<uint32_t> crcs;
    std::vector<uint8_t> have;
    int count = 0;
  };
  std::map<int64_t, GradcheckSlot> gradcheck_pending_;  // coordinator only
  // Fingerprint this op's post-allreduce output when the sampler says so
  // (background thread; called before postscale, so every rank hashes
  // bitwise-identical bytes). adasum ops are skipped — their pairwise
  // adaptive combine is outside the PR-3 bitwise-identity contract.
  void MaybeGradcheck(const std::string& name, const void* data,
                      int64_t bytes);
  // Coordinator side: record one rank's fingerprint for seq; when every
  // rank reported, majority-vote and convict the minority (DIVERGENCE
  // flight event + hvdtpu_divergence_total{suspect=...}).
  void RecordFingerprint(int64_t seq, int rank, uint32_t crc,
                         const std::string& name, int64_t bytes);
  // Non-finite sentinel (HVDTPU_NANCHECK): count + flight event + WARN for
  // a tensor whose copy-in moments saw NaN/Inf; returns true when the
  // policy is ABORT and the op must fail-fast before any data moves.
  // `slot` keys the per-tensor 1/s WARN/flight throttle — a NaN-flooded
  // model must not evict the flight ring's op/hop forensics (counters
  // stay exact regardless).
  bool NoteNonfinite(const std::string& tensor, const GradMoments& m,
                     int slot);
  // Always-available sampling profiler (profiler.h; docs/profiling.md):
  // the background loop registers itself for SIGPROF sampling, the data
  // plane publishes the phase thread-local the samples are tagged with,
  // and /profz / hvd.profile() / hvdrun --profile drive the window.
  SamplingProfiler profiler_;
  bool prof_written_ = false;
  // Memory-occupancy telemetry (docs/profiling.md "Memory telemetry"):
  // refreshed by the background loop at most once per second. Fusion
  // high-water is tracked here (the per-batch gauge is set at execution).
  double last_mem_update_at_ = 0;
  int64_t fusion_highwater_bytes_ = 0;
  Gauge* m_fusion_buffer_gauge_ = nullptr;
  Gauge* m_fusion_highwater_gauge_ = nullptr;
  Gauge* m_residual_bytes_gauge_ = nullptr;
  Gauge* m_rss_gauge_ = nullptr;
  Gauge* m_rss_peak_gauge_ = nullptr;
  std::vector<std::pair<int, int64_t>> shm_occupancy_scratch_;
  void UpdateMemoryGauges(bool force = false);

  // One histogram-pair + counter observation per completed data-plane op,
  // plus the perf-attribution sentry: `perf_sig` is the tensor-set
  // signature keying the streaming baselines (empty skips perf — JOIN,
  // failed lookups).
  void ObserveOp(const char* op, double secs, int64_t bytes,
                 const char* algo, const std::string& transport, bool hier,
                 const char* compression, DataType dtype, bool ok,
                 const std::string& perf_sig = std::string());
  // Refresh the autotune-owned parameter gauges (Start + every adoption).
  void UpdateParamGauges(double cycle_ms, int64_t fusion, bool cache_on,
                         int64_t crossover);

  // Wire-compression state: error-feedback residuals per (fused) tensor,
  // the compiled skip regex (with a per-name verdict memo — regex_search
  // is microseconds per call and the same tensor names recur every cycle
  // on the serialized collective thread), and the autotuner's current
  // choice under HVDTPU_COMPRESSION=auto (background thread only — both
  // the worker PARAMS handler and the coordinator adoption run there).
  ResidualStore residual_store_;
  std::regex comp_skip_re_;
  bool comp_skip_set_ = false;
  std::unordered_map<std::string, bool> comp_skip_memo_;
  int32_t comp_auto_ = 0;

  // Control plane.
  int coord_listen_fd_ = -1;           // rank 0
  std::vector<int> worker_fds_;        // rank 0: fd per rank (self = -1)
  int control_fd_ = -1;                // workers: connection to rank 0

  // Self-pipe waking the background loop's poll() the instant work arrives
  // (local enqueue/join/shutdown). Control-plane frames wake it by their fd
  // becoming readable, so small collectives are event-driven end to end
  // instead of paying up to one cycle_time_ms sleep per hop; the cycle time
  // degrades to the idle-poll timeout.
  int wake_pipe_[2] = {-1, -1};

  // Tensor queue + outstanding table (reference: tensor_queue.{h,cc}).
  // mu_ is the only lock shared between user threads (Enqueue/Wait/Poll/
  // CopyResult/Join) and the background thread; everything it guards is
  // annotated below and checked by `make analyze`.
  Mutex mu_;
  CondVar cv_;                                 // completion + enqueue signal
  // enqueued, not yet announced
  std::deque<TensorEntry*> pending_ GUARDED_BY(mu_);
  // Grouped-enqueue hold (GroupBegin/GroupEnd): while true, pending_ stays
  // queued so the whole group announces in one cycle.
  bool group_hold_ GUARDED_BY(mu_) = false;
  // by name
  std::unordered_map<std::string, TensorEntry*> outstanding_ GUARDED_BY(mu_);
  std::unordered_map<int64_t, TensorEntry*> handles_ GUARDED_BY(mu_);
  // completed handle -> status
  std::unordered_map<int64_t, Status> done_ GUARDED_BY(mu_);
  int64_t next_handle_ GUARDED_BY(mu_) = 0;
  // Runtime-mutable loop parameters (autotune adoption / PARAMS frames write
  // them, user threads read them via CurrentCycleTimeMs/CurrentFusion
  // Threshold). Split out of cfg_ so they can carry GUARDED_BY — the rest of
  // cfg_ is immutable once Start() spawns the background thread.
  double cycle_time_ms_ GUARDED_BY(mu_) = 1.0;
  int64_t fusion_threshold_ GUARDED_BY(mu_) = 64 * 1024 * 1024;

  // Coordinator negotiation state (reference: controller message_table_).
  // Background-thread-owned, like cache_, param_manager_, residual_store_,
  // comp_*_ and worker_fds_ (after Start): only BackgroundLoop's call tree
  // touches them, so they need no lock and carry no annotation — thread
  // ownership is a contract the analysis cannot express.
  struct PendingName {
    std::vector<Request> requests;
    double first_seen = 0;
    bool stall_warned = false;
  };
  std::map<std::string, PendingName> message_table_;  // ordered for determinism
  std::deque<std::string> ready_names_;               // count reached
  std::set<int32_t> joined_ranks_;
  std::set<int32_t> dead_ranks_;  // disconnected workers (never come back)
  bool join_pending_local_ GUARDED_BY(mu_) = false;
  std::atomic<int32_t> last_joined_rank_{-1};  // atomic: seqcst(join handshake with mutex-guarded state)
  std::atomic<bool> join_done_{false};  // atomic: seqcst(join handshake with mutex-guarded state)

  std::thread background_;
  std::atomic<bool> shutdown_{false};  // atomic: seqcst(shutdown latch, read via implicit loads)
  std::atomic<bool> world_broken_{false};  // atomic: seqcst(failure latch)
  // Worker-side failover latch (set by HandleDataPlaneFailure, consumed at
  // the top of the next background cycle — see the deferral note there).
  std::atomic<bool> worker_failover_pending_{false};  // atomic: seqcst(failover doorbell)
  bool started_ = false;

  // Response cache (see RequestCache above). Worker role uses req/enabled;
  // coordinator role uses the per-rank table.
  RequestCache cache_;

  // Batched control-plane sender (see CtrlOutbox above). Background-thread
  // owned like the fds it writes; flushed before any collective runs and at
  // the end of every pump.
  CtrlOutbox outbox_;

  // Autotune: coordinator-only decisions, broadcast via CtrlMsg::PARAMS.
  ParameterManager param_manager_;

  // Pending timeline start/stop, applied by the background thread.
  Mutex timeline_req_mu_;
  bool timeline_req_pending_ GUARDED_BY(timeline_req_mu_) = false;
  bool timeline_req_start_ GUARDED_BY(timeline_req_mu_) = false;
  std::string timeline_req_path_ GUARDED_BY(timeline_req_mu_);
  bool timeline_req_mark_ GUARDED_BY(timeline_req_mu_) = false;
  int64_t timeline_req_sample_ GUARDED_BY(timeline_req_mu_) = -1;

  void ApplyTimelineRequest() EXCLUDES(timeline_req_mu_);

  // Cross-rank clock alignment (docs/tracing.md): offset ± error of this
  // rank's steady clock vs rank 0's, estimated from CLOCK ping-pongs at
  // form-up and refreshed through the control plane while tracing. The
  // atomics are readable from any thread (hvdtpu_clock_offset); everything
  // else is background-thread-owned (Start writes before the spawn).
  std::atomic<int64_t> clock_offset_us_{0};  // atomic: relaxed-counter
  std::atomic<int64_t> clock_err_us_{-1};  // atomic: relaxed-counter
  double clock_synced_at_ = 0;
  double clock_adopted_at_ = 0;
  double clock_ping_sent_at_ = 0;
  bool clock_ping_inflight_ = false;
  // Emit (or refresh) this rank's trace-metadata event: clock offset ±
  // error, steady/wall anchors, sampling rate. No-op while no timeline
  // runs. Background thread (or Start, before the spawn) only.
  void EmitTraceMeta();
  void FailAllOutstanding(const std::string& reason) EXCLUDES(mu_);

  // Live-metrics registry (metrics.h) + handles pre-resolved in Start() so
  // the background loop's per-cycle updates are pure lock-free atomic ops.
  // Per-op histogram handles are label-dependent and resolved per op (a
  // mutex-guarded map lookup — microseconds against millisecond-scale
  // collectives, background thread only). Declared LAST so the registry's
  // mutex/map do not displace the hot negotiation state above across
  // cache lines.
  Metrics metrics_;
  Counter* m_cycles_ = nullptr;
  Histogram* m_cycle_hist_ = nullptr;
  Gauge* m_queue_depth_ = nullptr;
  Gauge* m_outstanding_ = nullptr;
  Gauge* m_stalled_ = nullptr;
  Counter* m_stall_warnings_ = nullptr;
  Gauge* m_dead_ranks_ = nullptr;
  Gauge* m_cycle_time_gauge_ = nullptr;
  Gauge* m_fusion_threshold_gauge_ = nullptr;
  Gauge* m_cache_enabled_gauge_ = nullptr;
  Gauge* m_crossover_gauge_ = nullptr;
  Gauge* m_hier_gauge_ = nullptr;
  Gauge* m_comp_mode_gauge_ = nullptr;
  Histogram* m_fusion_batch_bytes_ = nullptr;
  Histogram* m_fusion_utilization_ = nullptr;
  Counter* m_fused_tensors_ = nullptr;
  Counter* m_op_errors_ = nullptr;
  Counter* m_failures_detected_ = nullptr;
  Histogram* m_recovery_seconds_ = nullptr;
  Counter* m_flightrec_dumps_ = nullptr;
  // Numerical-health counters (docs/numerics.md): non-finite gradient
  // elements seen, divergence probes run, and error-feedback residual
  // resets (hvdtpu_divergence_total is label-resolved per suspect rank at
  // emission).
  Counter* m_nonfinite_grads_ = nullptr;
  Counter* m_gradcheck_probes_ = nullptr;
  Counter* m_residual_resets_ = nullptr;
  // Clock-sync quality vs rank 0 (PR-8 alignment), refreshed at every
  // adoption so the aggregator/console can flag degraded ranks.
  Gauge* m_clock_offset_gauge_ = nullptr;
  Gauge* m_clock_err_gauge_ = nullptr;
  // Negotiation-cache effectiveness: requests that rode the bare-name fast
  // path vs full announcements while the cache was tracking (workers count
  // their send decision, rank 0 counts bare-name rematerializations and
  // NEED_FULL repairs).
  Counter* m_cache_hits_ = nullptr;
  Counter* m_cache_misses_ = nullptr;
  // One failure-cascade count per core incarnation: after the plane aborts,
  // every queued op fails with the same coherent status — only the first
  // detection is a new failure (background thread only).
  bool failure_counted_ = false;
};

void Core::RequestTimeline(bool start, const std::string& path,
                           bool mark_cycles, int64_t trace_sample) {
  MutexLock lk(timeline_req_mu_);
  timeline_req_pending_ = true;
  timeline_req_start_ = start;
  timeline_req_path_ = path;
  timeline_req_mark_ = mark_cycles;
  timeline_req_sample_ = trace_sample;
}

void Core::ApplyTimelineRequest() {
  bool pending, start, mark;
  std::string path;
  int64_t sample;
  {
    MutexLock lk(timeline_req_mu_);
    pending = timeline_req_pending_;
    timeline_req_pending_ = false;
    start = timeline_req_start_;
    path = timeline_req_path_;
    mark = timeline_req_mark_;
    sample = timeline_req_sample_;
  }
  if (!pending) return;
  if (start) {
    timeline_.Shutdown();
    timeline_.Initialize(path, cfg_.rank);
    cfg_.timeline_mark_cycles = mark;
    if (sample >= 0) cfg_.trace_sample = sample;
    // This (background) thread is the data plane's single driver, so the
    // sampler can be retargeted here.
    data_plane_.set_trace_sample(cfg_.trace_sample);
    // A runtime-started trace on a worker that skipped the form-up sync
    // (un-traced launch) needs an offset NOW, not one refresh interval
    // from now: age out the sync state so the next pump cycle pings.
    if (cfg_.rank != 0 &&
        clock_err_us_.load(std::memory_order_relaxed) < 0) {
      clock_synced_at_ = 0;
    }
    EmitTraceMeta();
  } else {
    timeline_.Shutdown();
    cfg_.timeline_mark_cycles = false;
  }
}

void Core::EmitTraceMeta() {
  if (!timeline_.Initialized()) return;
  const int64_t unix_us =
      std::chrono::duration_cast<std::chrono::microseconds>(
          std::chrono::system_clock::now().time_since_epoch())
          .count();
  // Hostname rides into JSON: strip the two characters that could corrupt
  // it (quotes/backslashes have no business in a hostname anyway).
  std::string host = cfg_.my_host;
  for (char& c : host) {
    if (c == '"' || c == '\\') c = '_';
  }
  std::string args =
      "{\"rank\": " + std::to_string(cfg_.rank) +
      ", \"size\": " + std::to_string(cfg_.size) +
      ", \"host\": \"" + host + "\"" +
      ", \"clock_offset_us\": " +
      std::to_string(clock_offset_us_.load(std::memory_order_relaxed)) +
      ", \"clock_err_us\": " +
      std::to_string(clock_err_us_.load(std::memory_order_relaxed)) +
      ", \"steady_init_us\": " + std::to_string(timeline_.init_steady_us()) +
      ", \"steady_now_us\": " + std::to_string(Timeline::SteadyAbsUs()) +
      ", \"unix_now_us\": " + std::to_string(unix_us) +
      ", \"trace_sample\": " + std::to_string(cfg_.trace_sample) + "}";
  timeline_.Metadata(args);
}

void Core::ObserveOp(const char* op, double secs, int64_t bytes,
                     const char* algo, const std::string& transport,
                     bool hier, const char* compression, DataType dtype,
                     bool ok, const std::string& perf_sig) {
  MetricLabels labels{{"op", op},
                      {"algo", algo},
                      {"transport", transport},
                      {"hier", hier ? "1" : "0"},
                      {"compression", compression},
                      {"dtype", DataTypeName(dtype)}};
  metrics_
      .GetHistogram("hvdtpu_op_seconds",
                    "Data-plane wall time per collective op", LatencyBuckets(),
                    labels)
      ->Observe(secs);
  metrics_
      .GetHistogram("hvdtpu_op_bytes",
                    "Payload bytes per collective op (raw, pre-compression)",
                    BytesBuckets(), labels)
      ->Observe(static_cast<double>(bytes));
  metrics_
      .GetCounter("hvdtpu_ops_total", "Completed collective ops",
                  MetricLabels{{"op", op}})
      ->Inc();
  if (!ok) m_op_errors_->Inc();

  // Perf attribution (docs/observability.md): feed the streaming baselines
  // and run the slowdown sentry. Failed ops are excluded — their wall time
  // measures abort latency, not performance — and only real tensor-set
  // signatures key a baseline.
  if (!ok || !perfstats_.enabled() || perf_sig.empty()) return;
  // The op type is part of the key: a BROADCAST and an ALLREDUCE of the
  // same tensor name have unrelated cost profiles — sharing a baseline
  // would let the cheaper op drag it down and fire phantom anomalies on
  // the costlier one.
  std::string key;
  key.reserve(perf_sig.size() + transport.size() + 36);
  key += perf_sig;
  key += '|';
  key += algo;
  key += '|';
  key += transport;
  key += hier ? "|1|" : "|0|";
  key += compression;
  key += '|';
  key += op;
  PerfStats::OpSample sample;
  sample.wall_us = static_cast<int64_t>(secs * 1e6);
  sample.wait_us = data_plane_.op_wait_us();
  sample.wire_us = data_plane_.op_wire_us();
  sample.reduce_us = data_plane_.op_reduce_us();
  sample.codec_us = data_plane_.op_codec_us();
  sample.slow_peer = data_plane_.op_slow_peer();
  const int perf_slot = perfstats_.KeySlot(key);
  const PerfStats::Anomaly an = perfstats_.RecordOp(perf_slot, sample);
  if (!an.fired) return;
  metrics_
      .GetCounter(
          "hvdtpu_perf_anomalies_total",
          "Completed ops the slowdown sentry flagged against their rolling "
          "baseline (HVDTPU_PERF_SLOWDOWN_PCT), by dominant phase",
          MetricLabels{{"phase", PerfPhaseName(an.phase)}})
      ->Inc();
  {
    // Flight ring: the anomaly spans the op it flags; arg carries the
    // dominant-phase code, send_peer the wire-slow suspect (-1 otherwise).
    const int64_t now = Timeline::SteadyAbsUs();
    flightrec_.Record(FlightEvent::ANOMALY, flightrec_.InternName(perf_sig),
                      bytes, an.slow_peer, -1, now - sample.wall_us, now,
                      static_cast<int64_t>(an.phase), 0);
  }
  // Per-KEY log throttle (PerfStats::ShouldWarn): each slow key warns at
  // most once per second, but a chatty key can no longer starve a second,
  // different key's first warning — that second key appearing IS the
  // signal ("now codec-bound too").
  if (perfstats_.ShouldWarn(perf_slot, Timeline::SteadyAbsUs())) {
    LogWarn(cfg_.rank,
            "perf sentry: op '%s' ran %.2fx its baseline (%.2f ms vs "
            "%.2f ms), dominant phase %s%s",
            perf_sig.c_str(), an.ratio, sample.wall_us / 1e3,
            an.baseline_us / 1e3, PerfPhaseName(an.phase),
            an.slow_peer >= 0
                ? (" (slow hop peer rank " + std::to_string(an.slow_peer) +
                   ")")
                      .c_str()
                : "");
  }
  if (perf_anomaly_log_.size() < 512) {
    // Tensor names are user-controlled and ride into JSON: escape them
    // properly (quotes/backslashes/control bytes) — a stripped-only key
    // with an embedded newline would corrupt perf_profile.<rank>.json and
    // silently drop this rank from the cross-run merge.
    perf_anomaly_log_.push_back(
        "{\"t_us\": " + std::to_string(Timeline::SteadyAbsUs()) +
        ", \"op\": \"" + op + "\", \"key\": " + JsonEscapeString(key) +
        ", \"wall_us\": " + std::to_string(sample.wall_us) +
        ", \"baseline_us\": " +
        std::to_string(static_cast<int64_t>(an.baseline_us)) +
        ", \"ratio\": " + std::to_string(an.ratio) + ", \"phase\": \"" +
        PerfPhaseName(an.phase) +
        "\", \"slow_peer\": " + std::to_string(an.slow_peer) + "}");
  }
}

void Core::WritePerfProfile() {
  if (cfg_.perf_profile_path.empty() || !perfstats_.enabled() ||
      perf_profile_written_) {
    return;
  }
  perf_profile_written_ = true;
  std::string body = "{\"version\": 1, \"rank\": " +
                     std::to_string(cfg_.rank) +
                     ", \"size\": " + std::to_string(cfg_.size) +
                     ", \"perfstats\": " + perfstats_.SnapshotJson() +
                     ", \"anomalies\": [";
  for (size_t i = 0; i < perf_anomaly_log_.size(); ++i) {
    if (i > 0) body += ", ";
    body += perf_anomaly_log_[i];
  }
  body += "]}\n";
  FILE* f = fopen(cfg_.perf_profile_path.c_str(), "w");
  if (f == nullptr) {
    LogWarn(cfg_.rank, "perf profile: cannot write %s",
            cfg_.perf_profile_path.c_str());
    return;
  }
  fwrite(body.data(), 1, body.size(), f);
  fclose(f);
}

void Core::WriteGradProfile() {
  if (cfg_.grad_profile_path.empty() || !gradstats_.enabled() ||
      grad_profile_written_) {
    return;
  }
  grad_profile_written_ = true;
  std::string body = "{\"version\": 1, \"rank\": " +
                     std::to_string(cfg_.rank) +
                     ", \"size\": " + std::to_string(cfg_.size) +
                     ", \"gradstats\": " + gradstats_.SnapshotJson() + "}\n";
  FILE* f = fopen(cfg_.grad_profile_path.c_str(), "w");
  if (f == nullptr) {
    LogWarn(cfg_.rank, "grad profile: cannot write %s",
            cfg_.grad_profile_path.c_str());
    return;
  }
  fwrite(body.data(), 1, body.size(), f);
  fclose(f);
}

bool Core::NoteNonfinite(const std::string& tensor, const GradMoments& m,
                         int slot) {
  if (m.nonfinite == 0) return false;
  const NanPolicy policy = gradstats_.nan_policy();
  if (policy == NanPolicy::OFF) return false;
  m_nonfinite_grads_->Add(m.nonfinite);
  gradstats_.NoteNonfinite(m.nonfinite);
  // A diverged model floods NaN tensors hundreds of ops per second:
  // throttle the LOG and the flight record per tensor (first event always
  // passes) so the ring keeps the op/hop records a post-mortem needs.
  // Aborts always surface — they are about to freeze the ring anyway.
  const int64_t now = Timeline::SteadyAbsUs();
  if (policy == NanPolicy::ABORT ||
      gradstats_.ShouldWarnNonfinite(slot, now)) {
    flightrec_.Record(FlightEvent::NONFINITE, flightrec_.InternName(tensor),
                      m.nonfinite, -1, -1, now, now,
                      static_cast<int64_t>(policy), 0);
    LogWarn(cfg_.rank,
            "non-finite gradient in tensor '%s': %lld of %lld elements are "
            "NaN/Inf (HVDTPU_NANCHECK=%s)",
            tensor.c_str(), static_cast<long long>(m.nonfinite),
            static_cast<long long>(m.count), NanPolicyName(policy));
  }
  if (policy != NanPolicy::ABORT) return false;
  // Fail-fast forensics BEFORE any lane breaks: this rank's own dump must
  // carry the NONFINITE record (and the NONFINITE reason code) so the
  // post-mortem verdict can name the tensor, not just the rank.
  if (flightrec_.DumpToFile(DumpReason::NONFINITE, cfg_.rank, "",
                            /*fatal_once=*/true) &&
      m_flightrec_dumps_ != nullptr) {
    m_flightrec_dumps_->Inc();
  }
  return true;
}

void Core::MaybeGradcheck(const std::string& name, const void* data,
                          int64_t bytes) {
  if (!gradstats_.enabled() || cfg_.size <= 1 || bytes <= 0) return;
  const int64_t every = gradstats_.gradcheck_sample();
  if (every <= 0) return;
  // The sequence counter advances on EVERY probed-eligible op so all ranks
  // roll the same sampling decision (the knob is env-broadcast uniform).
  const int64_t seq = ++gradcheck_seq_;
  if (seq % every != 0) return;
  const uint32_t crc = Crc32c(data, static_cast<size_t>(bytes));
  gradstats_.NoteProbe();
  m_gradcheck_probes_->Inc();
  if (cfg_.rank == 0) {
    RecordFingerprint(seq, 0, crc, name, bytes);
    return;
  }
  if (control_fd_ < 0) return;
  // Piggybacked control-plane frame: rides the already-open coordinator
  // connection, one small frame per sampled op (cost model in
  // docs/numerics.md) — batched with the cycle's other control traffic.
  Writer w;
  w.I32(static_cast<int32_t>(CtrlMsg::GRADCHECK));
  w.I64(seq);
  w.I64(static_cast<int64_t>(crc));
  w.Str(name);
  outbox_.Queue(control_fd_, w.Take());
}

void Core::RecordFingerprint(int64_t seq, int rank, uint32_t crc,
                             const std::string& name, int64_t bytes) {
  GradcheckSlot& slot = gradcheck_pending_[seq];
  if (slot.crcs.empty()) {
    slot.crcs.assign(cfg_.size, 0);
    slot.have.assign(cfg_.size, 0);
  }
  if (rank < 0 || rank >= cfg_.size || slot.have[rank] != 0) return;
  slot.crcs[rank] = crc;
  slot.have[rank] = 1;
  ++slot.count;
  if (!name.empty()) slot.name = name;
  if (bytes > 0) slot.bytes = bytes;
  if (slot.count < cfg_.size) {
    // Bound the pending table: a rank that shut down (or lost its frame)
    // must not pin entries forever — drop the oldest incomplete probes.
    while (gradcheck_pending_.size() > 256) {
      gradcheck_pending_.erase(gradcheck_pending_.begin());
    }
    return;
  }
  // Every rank reported: majority vote. The majority fingerprint is the
  // most frequent value (ties broken toward the lowest holding rank, so a
  // 1v1 world convicts rank 1, matching the verdict convention that rank 0
  // holds the reference copy of negotiated state).
  std::unordered_map<uint32_t, int> freq;
  for (int r = 0; r < cfg_.size; ++r) ++freq[slot.crcs[r]];
  uint32_t majority = slot.crcs[0];
  int best = 0;
  for (int r = 0; r < cfg_.size; ++r) {
    const int f = freq[slot.crcs[r]];
    if (f > best) {
      best = f;
      majority = slot.crcs[r];
    }
  }
  if (best < cfg_.size) {
    for (int r = 0; r < cfg_.size; ++r) {
      if (slot.crcs[r] == majority) continue;
      // Silent data corruption (or non-determinism): the invariant every
      // collective here guarantees — bitwise-identical outputs on every
      // rank (PR-3 made even the compressed paths honor it) — broke.
      gradstats_.NoteDivergence();
      metrics_
          .GetCounter(
              "hvdtpu_divergence_total",
              "Cross-rank divergence-probe mismatches: sampled "
              "post-allreduce outputs whose crc32c differed from the "
              "world's majority (silent data corruption or "
              "non-determinism), by minority rank",
              MetricLabels{{"suspect", std::to_string(r)}})
          ->Inc();
      const int64_t now = Timeline::SteadyAbsUs();
      flightrec_.Record(FlightEvent::DIVERGENCE,
                        flightrec_.InternName(slot.name), slot.bytes, r, -1,
                        now, now, static_cast<int64_t>(slot.crcs[r]), 0);
      LogWarn(0,
              "DIVERGENCE: tensor '%s' (probe #%lld) — rank %d's "
              "post-allreduce fingerprint %08x differs from the majority "
              "%08x; silent data corruption or non-determinism",
              slot.name.c_str(), static_cast<long long>(seq), r,
              slot.crcs[r], majority);
    }
  }
  gradcheck_pending_.erase(seq);
}

void Core::UpdateParamGauges(double cycle_ms, int64_t fusion, bool cache_on,
                             int64_t crossover) {
  m_cycle_time_gauge_->Set(cycle_ms);
  m_fusion_threshold_gauge_->Set(static_cast<double>(fusion));
  m_cache_enabled_gauge_->Set(cache_on ? 1 : 0);
  m_crossover_gauge_->Set(static_cast<double>(crossover));
  // hier/compression are read back from the just-applied state so the
  // gauges always show the EFFECTIVE values (forced or autotuned).
  m_hier_gauge_->Set(data_plane_.hier_active() ? 1 : 0);
  const int32_t comp =
      cfg_.wire_compression == static_cast<int32_t>(WireCompression::AUTO)
          ? comp_auto_
          : cfg_.wire_compression;
  m_comp_mode_gauge_->Set(static_cast<double>(comp));
}

double Core::CurrentCycleTimeMs() {
  MutexLock lk(mu_);
  return cycle_time_ms_;
}

int64_t Core::CurrentFusionThreshold() {
  MutexLock lk(mu_);
  return fusion_threshold_;
}

Status Core::Start() {
  if (started_) return Status::OK();
  if (!cfg_.timeline_path.empty()) {
    timeline_.Initialize(cfg_.timeline_path, cfg_.rank);
  }
  cache_.SetCapacity(cfg_.cache_capacity);

  // Metrics registry: route data-plane byte accounting into this core's
  // registry (single source of truth behind hvdtpu_wire_stats AND /metrics)
  // and pre-resolve every fixed-label handle the background loop touches.
  data_plane_.set_metrics(&metrics_);
  metrics_.GetGauge("hvdtpu_rank", "This worker's global rank")
      ->Set(cfg_.rank);
  metrics_.GetGauge("hvdtpu_world_size", "Number of ranks in the world")
      ->Set(cfg_.size);
  m_cycles_ = metrics_.GetCounter(
      "hvdtpu_cycles_total", "Background-loop coordination cycles run");
  m_cycle_hist_ = metrics_.GetHistogram(
      "hvdtpu_cycle_seconds",
      "Coordination tick latency: wall time of one background-loop cycle "
      "(control-plane pump + any collectives it executed)",
      LatencyBuckets());
  m_queue_depth_ = metrics_.GetGauge(
      "hvdtpu_negotiation_queue_depth",
      "Coordinator message_table_ size: tensors announced by some ranks "
      "and still waiting for the rest (always 0 on non-coordinators)");
  m_outstanding_ = metrics_.GetGauge(
      "hvdtpu_outstanding_ops",
      "Collectives enqueued on this rank and not yet completed");
  m_stalled_ = metrics_.GetGauge(
      "hvdtpu_stalled",
      "1 while the stall inspector sees at least one tensor past the "
      "warning threshold, else 0 (coordinator only)");
  m_stall_warnings_ = metrics_.GetCounter(
      "hvdtpu_stall_warnings_total", "Stall warnings emitted by rank 0");
  m_dead_ranks_ = metrics_.GetGauge(
      "hvdtpu_dead_ranks",
      "Workers that disconnected without joining (coordinator only)");
  m_cycle_time_gauge_ = metrics_.GetGauge(
      "hvdtpu_cycle_time_ms", "Current (possibly autotuned) cycle time");
  m_fusion_threshold_gauge_ = metrics_.GetGauge(
      "hvdtpu_fusion_threshold_bytes",
      "Current (possibly autotuned) tensor-fusion threshold");
  m_cache_enabled_gauge_ = metrics_.GetGauge(
      "hvdtpu_cache_enabled",
      "1 when the response-cache bare-name fast path is active");
  m_crossover_gauge_ = metrics_.GetGauge(
      "hvdtpu_algo_crossover_bytes",
      "Current (possibly autotuned) ring/latency-algorithm crossover");
  m_hier_gauge_ = metrics_.GetGauge(
      "hvdtpu_hier_enabled",
      "1 when the hierarchical two-level allreduce is on (forced or "
      "autotuned)");
  m_comp_mode_gauge_ = metrics_.GetGauge(
      "hvdtpu_compression_mode",
      "Effective wire-compression mode code (0 none, 1 fp16, 2 int8, "
      "3 int4; under auto, the autotuner's current choice)");
  m_fusion_batch_bytes_ = metrics_.GetHistogram(
      "hvdtpu_fusion_batch_bytes",
      "Total payload bytes per fused allreduce batch", BytesBuckets());
  m_fusion_utilization_ = metrics_.GetHistogram(
      "hvdtpu_fusion_utilization",
      "Fused batch bytes as a fraction of the fusion threshold",
      {0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0});
  m_fused_tensors_ = metrics_.GetCounter(
      "hvdtpu_fused_tensors_total",
      "Tensors that rode a multi-tensor fused allreduce batch");
  m_op_errors_ = metrics_.GetCounter(
      "hvdtpu_op_errors_total", "Collectives that completed with an error");
  m_failures_detected_ = metrics_.GetCounter(
      "hvdtpu_failures_detected_total",
      "Peer failures detected by this rank (data-plane lane death, "
      "liveness-deadline trips, worker disconnects with ops pending)");
  m_recovery_seconds_ = metrics_.GetHistogram(
      "hvdtpu_recovery_seconds",
      "Failure-detection to successful re-initialization latency, observed "
      "by the elastic runtime after each recovery", LatencyBuckets());
  m_flightrec_dumps_ = metrics_.GetCounter(
      "hvdtpu_flightrec_dumps_total",
      "Flight-recorder dump files written (abort cascade, stall "
      "escalation, or on demand; fatal-signal dumps happen after the "
      "registry is unreachable and are not counted)");
  // Clock-sync quality (docs/tracing.md): this rank's steady-clock offset
  // vs rank 0 and the estimator's error bound. err = -1 until the first
  // sync, so the aggregator/console can flag never-aligned ranks.
  m_clock_offset_gauge_ = metrics_.GetGauge(
      "hvdtpu_clock_offset_us",
      "Steady-clock offset vs rank 0 in microseconds (PR-8 NTP-style "
      "alignment; 0 on rank 0)");
  m_clock_err_gauge_ = metrics_.GetGauge(
      "hvdtpu_clock_err_us",
      "Error bound of the clock-offset estimate in microseconds "
      "(-1 = never synced)");
  m_clock_err_gauge_->Set(-1);

  // Failure detection + fault injection (docs/fault-tolerance.md): slices
  // bound abort-propagation latency on every lane, the read deadline
  // catches hung-but-alive peers, the form-up timeout bounds mesh
  // establishment, and any armed chaos spec rides into the data plane.
  data_plane_.set_failure_detect_ms(cfg_.failure_detect_ms);
  data_plane_.set_read_deadline_secs(cfg_.read_deadline_secs);
  data_plane_.set_formup_timeout_ms(
      static_cast<int64_t>(cfg_.formup_timeout_secs * 1000.0));
  data_plane_.set_chaos(cfg_.chaos);
  // Distributed tracing: the data plane emits per-hop child spans into this
  // core's timeline for every trace_sample-th op (docs/tracing.md).
  data_plane_.set_tracer(&timeline_);
  data_plane_.set_trace_sample(cfg_.trace_sample);
  // Always-on flight recorder: every hop/op/failure event lands in the
  // in-memory ring regardless of trace sampling; the fatal-signal handlers
  // dump the MOST RECENTLY started core's ring (one live core per worker
  // process in production — in-process test worlds get the last one).
  flightrec_.Configure(cfg_.flightrec_events, cfg_.flightrec_dir, cfg_.rank,
                       cfg_.size);
  data_plane_.set_flightrec(&flightrec_);
  if (flightrec_.enabled()) {
    InstallFlightSignalHandlers();
    SetSignalFlightRecorder(&flightrec_);
  }
  // Always-on perf attribution (docs/observability.md): streaming per-key
  // baselines + the slowdown sentry, fed from the same hop instrumentation
  // the flight recorder rides.
  perfstats_.Configure(cfg_.perfstats, cfg_.perf_slowdown_pct,
                       cfg_.perf_min_samples);
  data_plane_.set_perf_enabled(perfstats_.enabled());
  // Numerical-health telemetry (docs/numerics.md): gradient moments fold
  // into the fusion copy-in, quantization quality into the compressed
  // hops, and the divergence probe fingerprints every Nth op's output.
  gradstats_.Configure(cfg_.gradstats,
                       static_cast<NanPolicy>(cfg_.nancheck),
                       cfg_.gradcheck_sample);
  m_nonfinite_grads_ = metrics_.GetCounter(
      "hvdtpu_nonfinite_grads_total",
      "NaN/Inf gradient elements seen at fusion copy-in "
      "(HVDTPU_NANCHECK; docs/numerics.md)");
  m_gradcheck_probes_ = metrics_.GetCounter(
      "hvdtpu_gradcheck_probes_total",
      "Cross-rank divergence probes this rank ran: sampled post-allreduce "
      "outputs fingerprinted and reported to rank 0 "
      "(HVDTPU_GRADCHECK_SAMPLE)");
  m_residual_resets_ = metrics_.GetCounter(
      "hvdtpu_residual_resets_total",
      "Error-feedback residual buffers dropped mid-run (element count "
      "changed on a live key — refused fusion or reshape — or the store "
      "hit its entry cap); compression quality restarts from zero "
      "feedback");
  // Always-available sampling profiler (docs/profiling.md): the background
  // loop registers itself once it starts; a window runs only on demand
  // (/profz, hvd.profile()) — except under hvdrun --profile, whose
  // prof_path arms a whole-job window right here.
  profiler_.Configure(cfg_.prof, cfg_.prof_hz, cfg_.prof_capacity,
                      static_cast<ProfClock>(cfg_.prof_clock), cfg_.rank);
  if (profiler_.enabled() && !cfg_.prof_path.empty()) profiler_.Start();
  // Memory-occupancy telemetry (docs/profiling.md "Memory telemetry"):
  // fusion-buffer occupancy/high-water, ResidualStore bytes, per-lane shm
  // ring occupancy, and process RSS/peak-RSS — refreshed by the background
  // loop once per second.
  m_fusion_buffer_gauge_ = metrics_.GetGauge(
      "hvdtpu_fusion_buffer_bytes",
      "Payload bytes of the most recent fused allreduce batch (the live "
      "fusion-buffer occupancy)");
  m_fusion_highwater_gauge_ = metrics_.GetGauge(
      "hvdtpu_fusion_buffer_highwater_bytes",
      "Largest fused batch this core has executed (fusion-buffer "
      "high-water mark)");
  m_residual_bytes_gauge_ = metrics_.GetGauge(
      "hvdtpu_residual_store_bytes",
      "Bytes held by the wire-compression error-feedback ResidualStore");
  m_rss_gauge_ = metrics_.GetGauge(
      "hvdtpu_rss_bytes", "Resident set size of this worker process");
  m_rss_peak_gauge_ = metrics_.GetGauge(
      "hvdtpu_rss_peak_bytes",
      "Peak resident set size of this worker process (getrusage ru_maxrss)");
  // Negotiation-cache effectiveness (docs/metrics.md): steady-state cycles
  // over a repeating tensor set should be all hits after the first
  // negotiation — a rising miss rate means eviction churn (capacity too
  // small) or requests that keep changing shape.
  m_cache_hits_ = metrics_.GetCounter(
      "hvdtpu_negotiation_cache_hits_total",
      "Negotiation requests that rode the response-cache bare-name fast "
      "path (workers: sent name-only; rank 0: rematerialized from cache)");
  m_cache_misses_ = metrics_.GetCounter(
      "hvdtpu_negotiation_cache_misses_total",
      "Negotiation requests sent or received in full while the cache was "
      "tracking (first sight, changed request, eviction, or NEED_FULL "
      "repair)");
  // Control-plane batching (docs/metrics.md): frames/batches is the
  // syscall amplification the CtrlOutbox removes — with HVDTPU_CTRL_BATCH=0
  // the two counters advance in lockstep.
  outbox_.set_enabled(cfg_.ctrl_batch != 0);
  outbox_.set_counters(
      metrics_.GetCounter(
          "hvdtpu_ctrl_frames_total",
          "Control-plane frames this rank produced (READY/RESPONSES/CLOCK/"
          "GRADCHECK/...; each is one syscall when batching is off)"),
      metrics_.GetCounter(
          "hvdtpu_ctrl_batches_total",
          "Vectored control-plane sends issued (one per peer per flush "
          "under HVDTPU_CTRL_BATCH=1; equals frames_total when off)"));

  data_plane_.set_allreduce_algo(
      static_cast<AllreduceAlgo>(cfg_.allreduce_algo));
  data_plane_.set_crossover_bytes(cfg_.allreduce_crossover);
  data_plane_.set_segment_bytes(cfg_.allreduce_segment);
  data_plane_.set_sa_min_group(cfg_.allreduce_sa_group);
  data_plane_.set_bcast_flat_max(cfg_.bcast_flat_max);
  data_plane_.set_shm_enabled(cfg_.shm_enabled != 0);
  data_plane_.set_shm_ring_bytes(cfg_.shm_ring_bytes);
  data_plane_.set_hier_mode(static_cast<HierMode>(cfg_.allreduce_hier));
  data_plane_.set_tcp_zerocopy(static_cast<ZeroCopyMode>(cfg_.tcp_zerocopy));
  data_plane_.set_shm_numa(static_cast<ShmNumaMode>(cfg_.shm_numa));
  data_plane_.set_doorbell_batch(cfg_.doorbell_batch);
  // Wire-compression skip list (Python validates the pattern too; a bad
  // regex smuggled past it must fail loudly, not silently compress biases).
  comp_skip_set_ = false;
  if (!cfg_.compression_skip_regex.empty()) {
    try {
      comp_skip_re_ = std::regex(cfg_.compression_skip_regex,
                                 std::regex::icase | std::regex::nosubs);
      comp_skip_set_ = true;
    } catch (const std::regex_error& e) {
      return Status::Error(StatusCode::INVALID_ARGUMENT,
                           std::string("bad HVDTPU_COMPRESSION_SKIP_REGEX: ") +
                               e.what());
    }
  }
  comp_auto_ = 0;  // HVDTPU_COMPRESSION=auto starts dense until tuned
  // (Re)create the wake pipe. The previous pipe, if any, is closed only
  // here and in the destructor — never in Shutdown — so a user thread's
  // Wake() racing a concurrent Shutdown can at worst write one byte into a
  // still-open pipe, not into a closed-and-reused fd.
  CloseFd(wake_pipe_[0]);
  CloseFd(wake_pipe_[1]);
  if (pipe(wake_pipe_) != 0) {
    wake_pipe_[0] = wake_pipe_[1] = -1;
    return Status::Error(StatusCode::ABORTED, "cannot create wake pipe");
  }
  fcntl(wake_pipe_[0], F_SETFL, O_NONBLOCK);
  fcntl(wake_pipe_[1], F_SETFL, O_NONBLOCK);
  Status st = data_plane_.Listen();
  if (!st.ok()) return st;

  // Rendezvous over the control plane (fills the role of the reference's HTTP
  // KV store rendezvous, horovod/runner/http/http_server.py +
  // gloo/http_store.cc): workers HELLO their data-plane endpoint to rank 0,
  // rank 0 broadcasts the peer table.
  std::vector<PeerAddr> peers(cfg_.size);
  peers[cfg_.rank] = {cfg_.my_host, data_plane_.port()};
  if (cfg_.size > 1) {
    if (cfg_.rank == 0) {
      coord_listen_fd_ = TcpListen(cfg_.coord_port, cfg_.size + 4, nullptr);
      if (coord_listen_fd_ < 0) {
        return Status::Error(StatusCode::ABORTED,
                             "coordinator: cannot listen on port " +
                                 std::to_string(cfg_.coord_port));
      }
      worker_fds_.assign(cfg_.size, -1);
      int pending = cfg_.size - 1;
      int rejects = 0;
      // With auth enabled, every malformed / slow / unauthenticated / dup
      // connection is rejected and accepting continues — a stray client
      // must not be able to kill or join the job (reference: secret.py +
      // authenticated driver_service). Without a secret a bad HELLO aborts
      // loudly: it's a real peer bug, not an attack surface. Note: the
      // proof binds (rank, host, port) but has no nonce — a same-boot
      // replay of a captured HELLO is rejected only by the dup-rank check;
      // full replay protection would need challenge-response.
      const bool authed = !cfg_.secret.empty();
      auto reject = [&](int fd, const char* why) -> bool {
        LogWarn(cfg_.rank, "coordinator: rejecting connection (%s)", why);
        CloseFd(fd);
        return ++rejects <= 1000;
      };
      const int formup_ms =
          std::max(1, static_cast<int>(cfg_.formup_timeout_secs * 1000.0));
      while (pending > 0) {
        // Form-up deadline: a worker that died (or never launched) between
        // spawn and HELLO must not wedge rendezvous forever (the elastic
        // driver retries with a fresh epoch on this failure).
        int fd = TcpAcceptTimeout(coord_listen_fd_, formup_ms);
        if (fd < 0) {
          return Status::Error(
              StatusCode::ABORTED,
              errno == ETIMEDOUT
                  ? "coordinator: rendezvous timed out waiting for " +
                        std::to_string(pending) +
                        " worker(s) (HVDTPU_FORMUP_TIMEOUT_SECONDS)"
                  : "coordinator: accept failed");
        }
        if (authed && !Readable(fd, 10000)) {
          if (reject(fd, "no HELLO within 10s")) continue;
          return Status::Error(StatusCode::ABORTED,
                               "coordinator: too many bad connections");
        }
        std::vector<uint8_t> frame;
        if (RecvFrame(fd, &frame) != 0) {
          if (authed && reject(fd, "hello recv failed")) continue;
          return Status::Error(StatusCode::ABORTED, "coordinator: hello failed");
        }
        Reader r(frame);
        if (static_cast<CtrlMsg>(r.I32()) != CtrlMsg::HELLO) {
          if (authed && reject(fd, "not a HELLO frame")) continue;
          return Status::Error(StatusCode::ABORTED, "coordinator: bad hello");
        }
        int32_t rank = r.I32();
        std::string host = r.Str();
        int32_t port = r.I32();
        if (authed) {
          std::string proof = r.ok() && r.pos() < r.size() ? r.Str() : "";
          std::string expect = HmacSha256Hex(
              cfg_.secret, "hvdtpu-hello:" + std::to_string(rank) + ":" +
                               host + ":" + std::to_string(port));
          if (!r.ok() || !ConstTimeEquals(proof, expect)) {
            if (reject(fd, "bad or missing secret proof")) continue;
            return Status::Error(StatusCode::ABORTED,
                                 "coordinator: too many unauthenticated "
                                 "connection attempts");
          }
        }
        if (rank <= 0 || rank >= cfg_.size) {
          if (authed && reject(fd, "rank out of range")) continue;
          return Status::Error(StatusCode::ABORTED, "coordinator: bad rank");
        }
        if (worker_fds_[rank] != -1) {
          // Duplicate rank (double connect or HELLO replay): keep the first.
          if (authed && reject(fd, "duplicate rank")) continue;
          return Status::Error(StatusCode::ABORTED,
                               "coordinator: duplicate rank in HELLO");
        }
        peers[rank] = {host, port};
        worker_fds_[rank] = fd;
        --pending;
      }
      Writer w;
      w.I32(static_cast<int32_t>(CtrlMsg::PEERS));
      for (const auto& p : peers) {
        w.Str(p.host);
        w.I32(p.port);
      }
      std::vector<uint8_t> payload = w.Take();
      for (int rank = 1; rank < cfg_.size; ++rank) {
        if (SendFrame(worker_fds_[rank], payload) != 0) {
          return Status::Error(StatusCode::ABORTED, "coordinator: peers send");
        }
      }
    } else {
      control_fd_ = TcpConnectRetry(
          cfg_.coord_host, cfg_.coord_port,
          std::max(1, static_cast<int>(cfg_.formup_timeout_secs * 1000.0)));
      if (control_fd_ < 0) {
        return Status::Error(StatusCode::ABORTED,
                             "worker: cannot reach coordinator at " +
                                 cfg_.coord_host + ":" +
                                 std::to_string(cfg_.coord_port));
      }
      Writer w;
      w.I32(static_cast<int32_t>(CtrlMsg::HELLO));
      w.I32(cfg_.rank);
      w.Str(cfg_.my_host);
      w.I32(data_plane_.port());
      if (!cfg_.secret.empty()) {
        w.Str(HmacSha256Hex(
            cfg_.secret, "hvdtpu-hello:" + std::to_string(cfg_.rank) + ":" +
                             cfg_.my_host + ":" +
                             std::to_string(data_plane_.port())));
      }
      if (SendFrame(control_fd_, w.buffer()) != 0) {
        return Status::Error(StatusCode::ABORTED, "worker: hello send failed");
      }
      std::vector<uint8_t> frame;
      if (RecvFrame(control_fd_, &frame) != 0) {
        return Status::Error(StatusCode::ABORTED, "worker: peers recv failed");
      }
      Reader r(frame);
      if (static_cast<CtrlMsg>(r.I32()) != CtrlMsg::PEERS) {
        return Status::Error(StatusCode::ABORTED, "worker: expected PEERS");
      }
      for (int rank = 0; rank < cfg_.size; ++rank) {
        peers[rank].host = r.Str();
        peers[rank].port = r.I32();
      }
    }
    // Cross-rank clock alignment (docs/tracing.md): ping-pong CLOCK frames
    // piggybacked on the form-up handshake, before the data-plane mesh
    // forms. The phase is SELF-DESCRIBING so per-rank config cannot
    // deadlock it: each worker sends as many pings as it wants (8 when a
    // timeline/trace is configured, zero otherwise — un-traced jobs pay
    // one done-marker frame per worker, not 8·(N−1) serialized RTTs at
    // the ROADMAP's w64 scale) and closes with a t1 = -1 marker; rank 0
    // echoes pings until it sees each worker's marker. A later worker's
    // first ping just waits in its socket buffer — the min-RTT estimator
    // discards the queued sample. Runtime-started traces on un-synced
    // workers get their offset from the control-plane refresh instead
    // (ApplyTimelineRequest forces a prompt ping).
    constexpr int kClockPings = 8;
    const int clock_ms =
        std::max(1, static_cast<int>(cfg_.formup_timeout_secs * 1000.0));
    const bool want_clock =
        cfg_.trace_sample > 0 || !cfg_.timeline_path.empty();
    if (cfg_.rank == 0) {
      clock_offset_us_.store(0, std::memory_order_relaxed);
      clock_err_us_.store(0, std::memory_order_relaxed);
      flightrec_.SetClock(0, 0);
      m_clock_offset_gauge_->Set(0);
      m_clock_err_gauge_->Set(0);
      for (int rank = 1; rank < cfg_.size; ++rank) {
        // Bounded serve loop: a buggy peer streaming endless pings must
        // trip form-up failure, not wedge rendezvous.
        for (int k = 0; k <= 8 * kClockPings; ++k) {
          std::vector<uint8_t> frame;
          if (k == 8 * kClockPings || !Readable(worker_fds_[rank], clock_ms) ||
              RecvFrame(worker_fds_[rank], &frame) != 0) {
            return Status::Error(StatusCode::ABORTED,
                                 "coordinator: clock sync with rank " +
                                     std::to_string(rank) + " failed");
          }
          Reader r(frame);
          if (static_cast<CtrlMsg>(r.I32()) != CtrlMsg::CLOCK) {
            return Status::Error(StatusCode::ABORTED,
                                 "coordinator: expected CLOCK frame");
          }
          int64_t t1 = r.I64();
          if (!r.ok()) {
            return Status::Error(StatusCode::ABORTED,
                                 "coordinator: bad CLOCK frame");
          }
          if (t1 < 0) break;  // this worker's done marker
          Writer w;
          w.I32(static_cast<int32_t>(CtrlMsg::CLOCK));
          w.I64(t1);
          w.I64(Timeline::SteadyAbsUs());
          if (SendFrame(worker_fds_[rank], w.buffer()) != 0) {
            return Status::Error(StatusCode::ABORTED,
                                 "coordinator: clock reply send failed");
          }
        }
      }
    } else {
      std::vector<ClockSample> samples;
      samples.reserve(kClockPings);
      for (int k = 0; want_clock && k < kClockPings; ++k) {
        ClockSample s;
        s.t1 = Timeline::SteadyAbsUs();
        Writer w;
        w.I32(static_cast<int32_t>(CtrlMsg::CLOCK));
        w.I64(s.t1);
        w.I64(0);
        std::vector<uint8_t> frame;
        if (SendFrame(control_fd_, w.buffer()) != 0 ||
            !Readable(control_fd_, clock_ms) ||
            RecvFrame(control_fd_, &frame) != 0) {
          return Status::Error(StatusCode::ABORTED,
                               "worker: clock sync with rank 0 failed");
        }
        s.t3 = Timeline::SteadyAbsUs();
        Reader r(frame);
        if (static_cast<CtrlMsg>(r.I32()) != CtrlMsg::CLOCK) {
          return Status::Error(StatusCode::ABORTED,
                               "worker: expected CLOCK frame");
        }
        r.I64();  // our t1, echoed
        s.t2 = r.I64();
        if (!r.ok()) {
          LogBadFrame(cfg_.rank, "worker CLOCK", frame);
          continue;
        }
        samples.push_back(s);
      }
      {
        Writer w;  // done marker: sync phase over for this worker
        w.I32(static_cast<int32_t>(CtrlMsg::CLOCK));
        w.I64(-1);
        w.I64(0);
        if (SendFrame(control_fd_, w.buffer()) != 0) {
          return Status::Error(StatusCode::ABORTED,
                               "worker: clock done-marker send failed");
        }
      }
      ClockEstimate est = EstimateClockOffset(samples);
      if (est.valid) {
        clock_offset_us_.store(est.offset_us, std::memory_order_relaxed);
        clock_err_us_.store(est.err_us, std::memory_order_relaxed);
        flightrec_.SetClock(est.offset_us, est.err_us);
        m_clock_offset_gauge_->Set(static_cast<double>(est.offset_us));
        m_clock_err_gauge_->Set(static_cast<double>(est.err_us));
      }
    }
    clock_synced_at_ = NowSeconds();
    clock_adopted_at_ = clock_synced_at_;
    st = data_plane_.Connect(peers);
    if (!st.ok()) return st;
  }

  // Current (possibly previously-autotuned, on a restart) loop parameters.
  double cycle_ms_now;
  int64_t fusion_now;
  {
    MutexLock lk(mu_);
    cycle_ms_now = cycle_time_ms_;
    fusion_now = fusion_threshold_;
  }

  if (cfg_.autotune && cfg_.rank == 0) {
    // After Connect on purpose: the hier switch joins the GP only under
    // AUTO with a topology where the two-level path exists and can matter —
    // 2+ hosts AND some host holding 2+ ranks — judged from the REAL peer
    // table (the launcher-provided local/cross sizes describe only this
    // rank, which may sit alone on its host while other hosts are
    // multi-rank).
    const bool tune_hier = cfg_.allreduce_hier == 2 &&
                           data_plane_.num_hosts() > 1 &&
                           data_plane_.num_hosts() < cfg_.size;
    // The compression categorical joins the GP only under
    // HVDTPU_COMPRESSION=auto (a pinned mode makes the coordinate inert).
    const bool tune_comp =
        cfg_.wire_compression ==
            static_cast<int32_t>(WireCompression::AUTO) &&
        cfg_.size > 1;
    // The scatter-allgather switch joins the GP only when AUTO's
    // big-message dispatch can actually reach it: algorithm unpinned and a
    // world at or past the sa_min_group floor (a smaller world makes the
    // coordinate inert, like the hier/comp gates).
    const bool tune_sa =
        data_plane_.allreduce_algo() == AllreduceAlgo::AUTO &&
        data_plane_.sa_min_group() > 0 &&
        cfg_.size >= data_plane_.sa_min_group();
    param_manager_.Initialize(cycle_ms_now, fusion_now,
                              cfg_.cache_capacity > 0,
                              data_plane_.crossover_bytes(),
                              data_plane_.allreduce_algo() ==
                                  AllreduceAlgo::AUTO,
                              data_plane_.sa_auto(), tune_sa,
                              /*hier_enabled=*/false, tune_hier,
                              /*wire_compression=*/0, tune_comp,
                              cfg_.autotune_log, cfg_.autotune_warmup_samples,
                              cfg_.autotune_cycles_per_sample,
                              cfg_.autotune_max_samples,
                              cfg_.autotune_gp_noise);
  }

  UpdateParamGauges(cycle_ms_now, fusion_now, cache_.enabled(),
                    data_plane_.crossover_bytes());

  // Single-rank worlds ARE rank 0: their clock is the global axis.
  if (cfg_.size == 1) {
    clock_offset_us_.store(0, std::memory_order_relaxed);
    clock_err_us_.store(0, std::memory_order_relaxed);
    flightrec_.SetClock(0, 0);
    m_clock_offset_gauge_->Set(0);
    m_clock_err_gauge_->Set(0);
  }
  // A timeline opened via HVDTPU_TIMELINE/HVDTPU_TRACE gets its metadata
  // now that the clock offset is known (runtime starts emit theirs in
  // ApplyTimelineRequest).
  EmitTraceMeta();

  shutdown_ = false;
  background_ = std::thread([this] { BackgroundLoop(); });
  started_ = true;
  return Status::OK();
}

void Core::Shutdown() {
  if (!started_) return;
  {
    MutexLock lk(mu_);
    shutdown_ = true;  // under mu_: no lost wakeups for waiters
  }
  cv_.NotifyAll();
  Wake();
  if (background_.joinable()) background_.join();
  // Cross-run regression sentry (docs/observability.md): persist this
  // rank's per-key baselines + anomaly log. After the join, the
  // background thread's perf state is quiescent.
  WritePerfProfile();
  // Numerical-health profile (docs/numerics.md): per-key norms/SNR for
  // scripts/grad_diff.py, same quiescence argument.
  WriteGradProfile();
  // Whole-job profile (hvdrun --profile): stop the window and persist
  // prof.<rank>.folded for scripts/prof_report.py. The background thread
  // has unregistered its timer by now; the ring is quiescent.
  profiler_.Stop();
  if (!cfg_.prof_path.empty() && profiler_.enabled() && !prof_written_) {
    prof_written_ = true;
    if (!profiler_.WriteFolded(cfg_.prof_path)) {
      LogWarn(cfg_.rank, "profiler: cannot write %s",
              cfg_.prof_path.c_str());
    }
  }
  // Fail any still-outstanding handles.
  {
    MutexLock lk(mu_);
    for (auto& kv : handles_) {
      done_[kv.first] =
          Status::Error(StatusCode::ABORTED, "shut down before completion");
      delete kv.second;
    }
    handles_.clear();
    outstanding_.clear();
    pending_.clear();
  }
  cv_.NotifyAll();
  data_plane_.Shutdown();
  if (control_fd_ >= 0) CloseFd(control_fd_);
  if (cfg_.rank == 0) {
    for (int fd : worker_fds_) CloseFd(fd);
    CloseFd(coord_listen_fd_);
  }
  timeline_.Shutdown();
  started_ = false;
}

int64_t Core::Enqueue(TensorEntry entry, Status* status) {
  MutexLock lk(mu_);
  if (shutdown_) {
    *status = Status::Error(StatusCode::ABORTED, "core is shut down");
    return -1;
  }
  if (outstanding_.count(entry.name) != 0) {
    // Reference: DUPLICATE_NAME_ERROR (common.h:214, tensor_queue.cc).
    *status = Status::Error(
        StatusCode::DUPLICATE_NAME,
        "Requested to " + std::string("collective on tensor '") + entry.name +
            "' which is already pending; tensor names must be unique among "
            "in-flight operations");
    return -1;
  }
  // AVERAGE == SUM with postscale 1/size (reference: operations.cc:928).
  // Applies to reduce-scatter too: its output chunk is postscaled after the
  // ring phase, exactly like the allreduce's per-entry postscale.
  if ((entry.op_type == OpType::ALLREDUCE ||
       entry.op_type == OpType::REDUCESCATTER) &&
      entry.reduce_op == ReduceOp::AVERAGE) {
    entry.reduce_op = ReduceOp::SUM;
    entry.postscale /= static_cast<double>(cfg_.size);
  }
  auto* e = new TensorEntry(std::move(entry));
  e->enqueued_at_us = Timeline::SteadyAbsUs();
  e->handle = static_cast<int32_t>(next_handle_++);
  handles_[e->handle] = e;
  outstanding_[e->name] = e;
  pending_.push_back(e);
  timeline_.QueueStart(e->name);
  *status = Status::OK();
  int64_t h = e->handle;
  lk.Unlock();
  cv_.NotifyAll();
  Wake();
  return h;
}

void Core::GroupBegin() {
  MutexLock lk(mu_);
  group_hold_ = true;
}

void Core::GroupEnd() {
  {
    MutexLock lk(mu_);
    group_hold_ = false;
  }
  Wake();  // release the whole group into the next announcement cycle
}

Status Core::WaitHandle(int64_t handle) {
  MutexLock lk(mu_);
  while (done_.count(handle) == 0 && !shutdown_.load()) cv_.Wait(lk);
  auto it = done_.find(handle);
  if (it == done_.end()) {
    return Status::Error(StatusCode::ABORTED, "core shut down while waiting");
  }
  return it->second;
}

int Core::PollHandle(int64_t handle) {
  MutexLock lk(mu_);
  return done_.count(handle) != 0 ? 1 : 0;
}

int64_t Core::ResultBytes(int64_t handle) {
  MutexLock lk(mu_);
  auto it = handles_.find(handle);
  if (it == handles_.end()) return -1;
  return static_cast<int64_t>(it->second->output.size());
}

Status Core::CopyResult(int64_t handle, void* dst, int64_t capacity) {
  MutexLock lk(mu_);
  auto hit = handles_.find(handle);
  auto dit = done_.find(handle);
  if (hit == handles_.end() || dit == done_.end()) {
    return Status::Error(StatusCode::INVALID_ARGUMENT, "unknown handle");
  }
  Status st = dit->second;
  TensorEntry* e = hit->second;
  if (st.ok()) {
    if (capacity < static_cast<int64_t>(e->output.size())) {
      return Status::Error(StatusCode::INVALID_ARGUMENT,
                           "result buffer too small");
    }
    memcpy(dst, e->output.data(), e->output.size());
  }
  delete e;
  handles_.erase(hit);
  done_.erase(dit);
  return st;
}

int64_t Core::Join() {
  {
    MutexLock lk(mu_);
    join_pending_local_ = true;
    join_done_ = false;
  }
  cv_.NotifyAll();
  Wake();
  MutexLock lk(mu_);
  while (!join_done_.load() && !shutdown_.load()) cv_.Wait(lk);
  if (!join_done_.load()) return -2;  // woken by a broken world, not a join
  return last_joined_rank_.load();
}

void Core::Wake() {
  if (wake_pipe_[1] >= 0) {
    char b = 1;
    // Nonblocking: a full pipe already guarantees a pending wakeup.
    (void)!write(wake_pipe_[1], &b, 1);
  }
}

void Core::WaitForWork() {
  // Event-driven cycle gate (replaces the reference's fixed RunLoopOnce
  // sleep, operations.cc:591): poll the wake pipe (local enqueue/join/
  // shutdown) plus every control-plane fd, so both coordinator and workers
  // react to frames the moment they land instead of sleeping out the cycle
  // time. The cycle time remains the idle-poll timeout (autotune still owns
  // it; its floor is poll's 1 ms granularity).
  std::vector<pollfd> pfds;
  pfds.push_back({wake_pipe_[0], POLLIN, 0});
  if (cfg_.rank == 0) {
    for (int fd : worker_fds_) {
      if (fd >= 0) pfds.push_back({fd, POLLIN, 0});
    }
  } else if (control_fd_ >= 0) {
    pfds.push_back({control_fd_, POLLIN, 0});
  }
  double cycle_ms;
  {
    MutexLock lk(mu_);
    cycle_ms = cycle_time_ms_;
  }
  int timeout = std::max(1, static_cast<int>(std::lround(cycle_ms)));
  (void)poll(pfds.data(), pfds.size(), timeout);
  // Drain the pipe: it is level-triggered bookkeeping, not a byte count.
  char buf[256];
  while (read(wake_pipe_[0], buf, sizeof(buf)) > 0) {
  }
}

HVDTPU_ROLE(background)
void Core::BackgroundLoop() {
  // Sampling profiler: this is the collective-driving thread — the one the
  // flamegraphs are about. Registration creates its (disarmed) per-thread
  // timer; the unregister below pairs with it before the thread exits.
  profiler_.RegisterThread();
  while (!shutdown_) {
    if (worker_failover_pending_.exchange(false)) {
      // A data-plane failure was detected last cycle; the entry walk that
      // detected it has fully unwound, so failing every outstanding handle
      // (and waking user threads) is safe now.
      FailAllOutstanding("a peer process failed during a collective");
      {
        MutexLock lk(mu_);
        shutdown_ = true;
      }
      cv_.NotifyAll();
      break;
    }
    WaitForWork();
    if (shutdown_) break;
    ApplyTimelineRequest();
    if (cfg_.timeline_mark_cycles) timeline_.MarkCycle();
    const double t0 = NowSeconds();
    PumpControlPlane();
    // End-of-cycle flush: whatever the pump queued and no collective forced
    // out earlier (CLOCK pings/echoes, GRADCHECK piggybacks, PARAMS, READY
    // lists on quiet cycles) ships as one vectored send per peer.
    FlushCtrlOutbox();
    // Coordination-tick accounting: latency of the productive part of the
    // cycle (the idle poll in WaitForWork is deliberately excluded — an
    // idle worker would otherwise bury the signal under cycle_time_ms
    // observations) plus the queue-depth/outstanding gauges.
    m_cycles_->Inc();
    m_cycle_hist_->Observe(NowSeconds() - t0);
    m_queue_depth_->Set(static_cast<double>(message_table_.size()));
    m_dead_ranks_->Set(static_cast<double>(dead_ranks_.size()));
    {
      MutexLock lk(mu_);
      m_outstanding_->Set(static_cast<double>(outstanding_.size()));
    }
    UpdateMemoryGauges();
  }
  profiler_.UnregisterThread();
}

void Core::UpdateMemoryGauges(bool force) {
  // Once per second: /proc and the per-lane walks are microseconds, but the
  // loop can cycle every millisecond under load.
  const double now = NowSeconds();
  if (!force && now - last_mem_update_at_ < 1.0) return;
  last_mem_update_at_ = now;
  if (m_residual_bytes_gauge_ != nullptr) {
    m_residual_bytes_gauge_->Set(
        static_cast<double>(residual_store_.TotalBytes()));
  }
  // Per-lane shm-ring occupancy. The gauge handle resolution is a mutex-map
  // lookup per lane — fine at this cadence; lanes are fixed after Connect.
  data_plane_.ShmOccupancy(&shm_occupancy_scratch_);
  for (const auto& lane : shm_occupancy_scratch_) {
    metrics_
        .GetGauge("hvdtpu_shm_ring_occupancy_bytes",
                  "Bytes buffered in the shared-memory rings to one peer "
                  "(both directions; head minus tail)",
                  MetricLabels{{"peer", std::to_string(lane.first)}})
        ->Set(static_cast<double>(lane.second));
  }
  // RSS (current from /proc/self/statm, peak from getrusage): the gauges
  // that catch a fusion-buffer or ring leak growing the process.
  if (m_rss_gauge_ != nullptr) {
    FILE* f = fopen("/proc/self/statm", "r");
    if (f != nullptr) {
      long total = 0, resident = 0;
      if (fscanf(f, "%ld %ld", &total, &resident) == 2) {
        m_rss_gauge_->Set(static_cast<double>(resident) *
                          static_cast<double>(sysconf(_SC_PAGESIZE)));
      }
      fclose(f);
    }
  }
  if (m_rss_peak_gauge_ != nullptr) {
    struct rusage ru;
    if (getrusage(RUSAGE_SELF, &ru) == 0) {
      m_rss_peak_gauge_->Set(static_cast<double>(ru.ru_maxrss) * 1024.0);
    }
  }
}

void Core::PumpControlPlane() {
  // Move newly enqueued entries into the announcement.
  std::vector<Request> reqs;
  bool announce_join = false;
  {
    MutexLock lk(mu_);
    // A grouped-enqueue window is open: leave the queue intact so the whole
    // group announces together in the cycle after GroupEnd releases it.
    while (!group_hold_ && !pending_.empty()) {
      TensorEntry* e = pending_.front();
      pending_.pop_front();
      Request q;
      q.rank = cfg_.rank;
      q.op_type = e->op_type;
      q.reduce_op = e->reduce_op;
      q.dtype = e->dtype;
      q.name = e->name;
      q.shape = e->shape;
      q.prescale = e->prescale;
      q.postscale = e->postscale;
      q.root_rank = e->root_rank;
      q.splits = e->splits;
      reqs.push_back(std::move(q));
      timeline_.NegotiateStart(e->name);
    }
    if (join_pending_local_) {
      join_pending_local_ = false;
      announce_join = true;
    }
  }

  if (cfg_.size == 1) {
    // Single rank: every op is immediately ready; execute locally.
    std::vector<Response> list;
    for (auto& q : reqs) {
      HandleReadyRequests({q});
    }
    if (announce_join) joined_ranks_.insert(0);
    CoordinatorEmitResponses();
    return;
  }

  if (cfg_.rank == 0) {
    if (!reqs.empty()) HandleReadyRequests(std::move(reqs));
    if (announce_join) {
      joined_ranks_.insert(0);
      last_joined_rank_ = 0;
    }
    CoordinatorIngest();
    CheckStalls();
    CoordinatorEmitResponses();
  } else {
    if (!reqs.empty()) {
      // Response-cache fast path: a request identical to the last one for the
      // same name travels as just its name (reference: ResponseCache hit
      // skipping negotiation, response_cache.cc; see RequestCache above).
      std::vector<Request> fulls;
      std::vector<std::string> cached;
      for (auto& q : reqs) {
        // CheckAndPut always tracks (keeps this side's entry fresh across
        // autotune cache toggles); enabled() only gates the bare-name wire
        // fast path.
        bool hit = cache_.tracking() && cache_.CheckAndPut(q);
        if (hit && cache_.enabled()) {
          cached.push_back(q.name);
          m_cache_hits_->Inc();
        } else {
          if (cache_.tracking()) m_cache_misses_->Inc();
          fulls.push_back(std::move(q));
        }
      }
      WorkerSendReady(std::move(fulls), std::move(cached));
    }
    if (announce_join) {
      Writer w;
      w.I32(static_cast<int32_t>(CtrlMsg::JOIN));
      w.I32(cfg_.rank);
      outbox_.Queue(control_fd_, w.Take());
    }
    // Periodic clock-sync refresh while a timeline runs (docs/tracing.md):
    // at most one CLOCK ping in flight; the reply is handled in the drain
    // loop below. Gated on the timeline alone — an op-phases-only trace
    // (HVDTPU_TRACE_SAMPLE=0) still needs fresh offsets for the merge.
    // The refresh rides the busy control plane, so its RTT (and error
    // bound) is worse than the quiet form-up sync — the adoption logic
    // keeps the tighter estimate unless it has aged out.
    // A lost reply must not disable refreshing forever: re-arm once the
    // outstanding ping has aged past two intervals.
    if (control_fd_ >= 0 && timeline_.Initialized() &&
        (!clock_ping_inflight_ ||
         NowSeconds() - clock_ping_sent_at_ >
             2.0 * cfg_.clock_sync_interval_secs) &&
        NowSeconds() - clock_synced_at_ > cfg_.clock_sync_interval_secs) {
      Writer w;
      w.I32(static_cast<int32_t>(CtrlMsg::CLOCK));
      w.I64(Timeline::SteadyAbsUs());
      w.I64(0);
      // Queued frames ship at the next flush (same pump); a batched send
      // failure surfaces there, and the two-interval re-arm above recovers
      // the lost ping either way.
      if (outbox_.Queue(control_fd_, w.Take()) == 0) {
        clock_ping_inflight_ = true;
        clock_ping_sent_at_ = NowSeconds();
      }
    }
    // Drain response lists.
    while (control_fd_ >= 0 && Readable(control_fd_, 0)) {
      std::vector<uint8_t> frame;
      if (RecvFrame(control_fd_, &frame) != 0) {
        if (!shutdown_) {
          // EOF with nothing in flight is a peer shutting down at job end;
          // only a mid-operation loss is an error worth failing over.
          bool have_outstanding;
          {
            MutexLock lk(mu_);
            have_outstanding = !outstanding_.empty();
            shutdown_ = true;  // under mu_: no lost wakeups for waiters
          }
          if (have_outstanding) {
            LogWarn(cfg_.rank, "lost connection to coordinator");
          }
          cv_.NotifyAll();
        }
        return;
      }
      Reader r(frame);
      CtrlMsg type = static_cast<CtrlMsg>(r.I32());
      if (type == CtrlMsg::NEED_FULL) {
        // Coordinator evicted a cache entry we hit on — resend in full from
        // the still-outstanding entry (race-free repair path).
        int64_t n = r.I64();
        std::vector<Request> fulls;
        {
          MutexLock lk(mu_);
          for (int64_t i = 0; i < n && r.ok(); ++i) {
            std::string name = r.Str();
            if (!r.ok()) break;
            auto it = outstanding_.find(name);
            if (it == outstanding_.end()) continue;
            TensorEntry* e = it->second;
            Request q;
            q.rank = cfg_.rank;
            q.op_type = e->op_type;
            q.reduce_op = e->reduce_op;
            q.dtype = e->dtype;
            q.name = e->name;
            q.shape = e->shape;
            q.prescale = e->prescale;
            q.postscale = e->postscale;
            q.root_rank = e->root_rank;
            q.splits = e->splits;
            fulls.push_back(std::move(q));
          }
        }
        if (cache_.tracking()) {
          for (auto& q : fulls) cache_.CheckAndPut(q);  // refresh local entry
        }
        if (!fulls.empty()) WorkerSendReady(std::move(fulls), {});
        continue;
      }
      if (type == CtrlMsg::CLOCK) {
        // Refresh reply: recompute the offset from this single ping-pong.
        ClockSample s;
        s.t3 = Timeline::SteadyAbsUs();
        s.t1 = r.I64();
        s.t2 = r.I64();
        if (!r.ok()) {
          LogBadFrame(cfg_.rank, "worker CLOCK", frame);
          continue;
        }
        clock_ping_inflight_ = false;
        clock_synced_at_ = NowSeconds();
        ClockEstimate est = EstimateClockOffset({s});
        const int64_t cur_err =
            clock_err_us_.load(std::memory_order_relaxed);
        // Adopt when at least as tight as the current bound, or when the
        // current estimate has aged out — past ~10 refresh periods clock
        // drift beats a stale tight bound.
        if (est.valid &&
            (cur_err < 0 || est.err_us <= cur_err ||
             NowSeconds() - clock_adopted_at_ >
                 10.0 * cfg_.clock_sync_interval_secs)) {
          clock_offset_us_.store(est.offset_us, std::memory_order_relaxed);
          clock_err_us_.store(est.err_us, std::memory_order_relaxed);
          flightrec_.SetClock(est.offset_us, est.err_us);
          m_clock_offset_gauge_->Set(static_cast<double>(est.offset_us));
          m_clock_err_gauge_->Set(static_cast<double>(est.err_us));
          clock_adopted_at_ = NowSeconds();
          EmitTraceMeta();
        }
        continue;
      }
      if (type == CtrlMsg::PARAMS) {
        double cycle = r.F64();
        int64_t fusion = r.I64();
        bool cache_on = r.I32() != 0;
        int64_t crossover = r.I64();
        bool hier_on = r.I32() != 0;
        int32_t comp = r.I32();
        bool sa_on = r.I32() != 0;
        if (!r.ok()) {
          LogBadFrame(cfg_.rank, "worker PARAMS", frame);
          continue;
        }
        // data_plane_ is driven by this (background) thread only.
        data_plane_.set_crossover_bytes(crossover);
        data_plane_.set_sa_auto(sa_on);
        data_plane_.set_hier_auto(hier_on);
        comp_auto_ = comp;
        {
          MutexLock lk(mu_);
          cycle_time_ms_ = cycle;
          fusion_threshold_ = fusion;
          cache_.SetEnabled(cache_on);
        }
        UpdateParamGauges(cycle, fusion, cache_on,
                          data_plane_.crossover_bytes());
        continue;
      }
      if (type != CtrlMsg::RESPONSES) continue;
      int64_t n = r.I64();
      std::vector<Response> list;
      for (int64_t i = 0; i < n && r.ok(); ++i) {
        list.push_back(DeserializeResponse(&r));
      }
      if (!r.ok()) {
        LogBadFrame(cfg_.rank, "worker RESPONSES", frame);
        continue;
      }
      ExecuteResponseList(list);
    }
  }
}

void Core::WorkerSendReady(std::vector<Request> reqs,
                           std::vector<std::string> cached) {
  Writer w;
  w.I32(static_cast<int32_t>(CtrlMsg::READY));
  w.I64(static_cast<int64_t>(reqs.size()));
  for (const auto& q : reqs) SerializeRequest(q, &w);
  w.I64(static_cast<int64_t>(cached.size()));
  for (const auto& name : cached) w.Str(name);
  if (outbox_.Queue(control_fd_, w.Take()) != 0 && !shutdown_) {
    LogWarn(cfg_.rank, "failed to send ready list to coordinator");
  }
}

void Core::FlushCtrlOutbox() {
  if (!outbox_.pending()) return;
  int bad_fd = -1;
  if (outbox_.Flush(&bad_fd) != 0 && !shutdown_) {
    // Same policy as the unbatched sends: a failed control write is only
    // logged — the authoritative disconnect signal is the RecvFrame EOF
    // (coordinator ingest / worker drain), which runs the failover path.
    LogWarn(cfg_.rank, "control-plane flush failed (fd %d)", bad_fd);
  }
}

void Core::CoordinatorIngest() {
  for (int rank = 1; rank < cfg_.size; ++rank) {
    int fd = worker_fds_[rank];
    if (fd < 0) continue;
    while (Readable(fd, 0)) {
      std::vector<uint8_t> frame;
      if (RecvFrame(fd, &frame) != 0) {
        if (!shutdown_) {
          // A worker vanished. With ops pending anywhere this breaks the
          // world: fail everything coherently on every rank so elastic mode
          // can catch HvdTpuInternalError and re-rendezvous (reference:
          // HorovodInternalError semantics, horovod/common/exceptions.py).
          bool have_outstanding;
          {
            MutexLock lk(mu_);
            have_outstanding = !outstanding_.empty();
          }
          if (!message_table_.empty() || have_outstanding) {
            LogWarn(0, "worker rank %d disconnected with ops pending", rank);
            m_failures_detected_->Inc();
            world_broken_ = true;
          }
          // Even with nothing in flight, the rank is gone for good (unless it
          // Joined first): any collective announced later can never complete,
          // so it must fail over, not hang (HandleReadyRequests checks this).
          if (!joined_ranks_.count(rank)) dead_ranks_.insert(rank);
          worker_fds_[rank] = -1;
          outbox_.Forget(fd);
          CloseFd(fd);
        }
        break;
      }
      Reader r(frame);
      CtrlMsg type = static_cast<CtrlMsg>(r.I32());
      if (type == CtrlMsg::READY) {
        int64_t n = r.I64();
        std::vector<Request> reqs;
        for (int64_t i = 0; i < n && r.ok(); ++i) {
          Request q = DeserializeRequest(&r);
          if (!r.ok()) break;
          if (cache_.tracking()) cache_.PutRank(q);
          reqs.push_back(std::move(q));
        }
        // Cache-hit names: re-materialize the full request this rank last
        // sent; on a miss (entry evicted here) ask the worker to resend.
        int64_t ncached = r.I64();
        std::vector<std::string> need_full;
        for (int64_t i = 0; i < ncached && r.ok(); ++i) {
          std::string name = r.Str();
          if (!r.ok()) break;
          Request q;
          if (cache_.GetRank(name, rank, &q)) {
            m_cache_hits_->Inc();
            reqs.push_back(std::move(q));
          } else {
            m_cache_misses_->Inc();
            need_full.push_back(std::move(name));
          }
        }
        if (!r.ok()) {
          LogBadFrame(cfg_.rank, "coordinator READY", frame);
          continue;
        }
        if (!need_full.empty()) {
          Writer w;
          w.I32(static_cast<int32_t>(CtrlMsg::NEED_FULL));
          w.I64(static_cast<int64_t>(need_full.size()));
          for (const auto& name : need_full) w.Str(name);
          outbox_.Queue(fd, w.Take());
        }
        HandleReadyRequests(std::move(reqs));
      } else if (type == CtrlMsg::JOIN) {
        int32_t who = r.I32();
        joined_ranks_.insert(who);
        last_joined_rank_ = who;
      } else if (type == CtrlMsg::CLOCK) {
        // Clock-sync refresh ping: echo the worker's t1 with our steady
        // now. Served inline — the timestamp is taken here, so coordinator
        // scheduling latency lands in the worker's RTT (and its error
        // bound), never in the offset unnoticed.
        int64_t t1 = r.I64();
        if (!r.ok()) {
          LogBadFrame(cfg_.rank, "coordinator CLOCK", frame);
          continue;
        }
        Writer w;
        w.I32(static_cast<int32_t>(CtrlMsg::CLOCK));
        w.I64(t1);
        w.I64(Timeline::SteadyAbsUs());
        outbox_.Queue(fd, w.Take());
      } else if (type == CtrlMsg::GRADCHECK) {
        // Divergence probe report (docs/numerics.md): one sampled op's
        // post-allreduce fingerprint from this worker.
        int64_t seq = r.I64();
        int64_t crc = r.I64();
        std::string name = r.Str();
        if (!r.ok()) {
          LogBadFrame(cfg_.rank, "coordinator GRADCHECK", frame);
          continue;
        }
        RecordFingerprint(seq, rank, static_cast<uint32_t>(crc), name, 0);
      }
    }
  }
}

void Core::HandleReadyRequests(std::vector<Request> reqs) {
  // A request arriving after a (non-joined) peer died can never reach world
  // count — break the world now instead of hanging until the stall timeout.
  if (!reqs.empty() && !dead_ranks_.empty()) {
    LogWarn(0, "collective announced after a peer died; failing over");
    world_broken_ = true;
  }
  // Reference: IncrementTensorCount (controller.cc:838).
  for (auto& q : reqs) {
    auto& slot = message_table_[q.name];
    if (slot.requests.empty()) {
      slot.first_seen = NowSeconds();
      slot.stall_warned = false;
    }
    slot.requests.push_back(std::move(q));
  }
  // Promote names whose count (plus joined ranks) reached world size.
  for (auto& kv : message_table_) {
    size_t have = kv.second.requests.size() + joined_ranks_.size();
    if (have >= static_cast<size_t>(cfg_.size) &&
        std::find(ready_names_.begin(), ready_names_.end(), kv.first) ==
            ready_names_.end()) {
      ready_names_.push_back(kv.first);
    }
  }
}

Response Core::BuildResponse(const std::string& name) {
  // Reference: ConstructResponse (controller.cc:380) — validate that every
  // rank agreed on op/dtype/shape before any data moves, and surface ONE
  // coherent error on all ranks otherwise.
  auto& slot = message_table_[name];
  auto& reqs = slot.requests;
  Response resp;
  resp.names.push_back(name);
  const Request& first = reqs[0];
  resp.op_type = first.op_type;
  resp.reduce_op = first.reduce_op;
  resp.dtype = first.dtype;
  resp.root_rank = first.root_rank;
  resp.shapes.push_back(first.shape);
  resp.prescales.push_back(first.prescale);
  resp.postscales.push_back(first.postscale);

  auto error = [&](const std::string& msg) {
    resp.type = ResponseType::ERROR;
    resp.error_message = msg;
    return resp;
  };

  for (size_t i = 1; i < reqs.size(); ++i) {
    const Request& q = reqs[i];
    if (q.op_type != first.op_type) {
      return error("Mismatched collective operations: rank " +
                   std::to_string(first.rank) + " requested op " +
                   std::to_string(static_cast<int>(first.op_type)) +
                   " but rank " + std::to_string(q.rank) + " requested op " +
                   std::to_string(static_cast<int>(q.op_type)) +
                   " for tensor '" + name + "'");
    }
    if (q.dtype != first.dtype) {
      return error("Mismatched data types: rank " +
                   std::to_string(first.rank) + " has " +
                   DataTypeName(first.dtype) + " but rank " +
                   std::to_string(q.rank) + " has " + DataTypeName(q.dtype) +
                   " for tensor '" + name + "'");
    }
  }

  switch (first.op_type) {
    case OpType::ALLREDUCE:
    case OpType::REDUCESCATTER: {
      for (size_t i = 1; i < reqs.size(); ++i) {
        if (reqs[i].shape != first.shape) {
          return error("Mismatched " +
                       std::string(first.op_type == OpType::ALLREDUCE
                                       ? "allreduce"
                                       : "reducescatter") +
                       " tensor shapes: rank " + std::to_string(first.rank) +
                       " has " + ShapeStr(first.shape) + " but rank " +
                       std::to_string(reqs[i].rank) + " has " +
                       ShapeStr(reqs[i].shape) + " for tensor '" + name + "'");
        }
        if (reqs[i].reduce_op != first.reduce_op) {
          return error("Mismatched reduce ops for tensor '" + name + "'");
        }
      }
      if (first.op_type == OpType::REDUCESCATTER) {
        if (!first.shape.empty() && first.shape[0] % cfg_.size != 0) {
          return error("reducescatter first dimension (" +
                       std::to_string(first.shape[0]) +
                       ") must be divisible by world size (" +
                       std::to_string(cfg_.size) + ") for tensor '" + name +
                       "'");
        }
        // RESPONSES carry the per-rank output shape (dim 0 of each rank's
        // chunk), like allgather — uniform today, but on the wire so the
        // execute path and any future ragged extension key off the
        // negotiated value, not a recomputation.
        resp.first_dims.assign(
            cfg_.size,
            first.shape.empty() ? 0 : first.shape[0] / cfg_.size);
      }
      break;
    }
    case OpType::ALLGATHER: {
      // Ranks may differ in dim 0 only (reference: controller.cc:812-832).
      resp.first_dims.assign(cfg_.size, first.shape.empty() ? 1 : first.shape[0]);
      for (const auto& q : reqs) {
        if (q.shape.size() != first.shape.size()) {
          return error("Mismatched allgather tensor ranks: rank " +
                       std::to_string(first.rank) + " has rank-" +
                       std::to_string(first.shape.size()) +
                       " tensor but rank " + std::to_string(q.rank) +
                       " has rank-" + std::to_string(q.shape.size()) +
                       " tensor for '" + name + "'");
        }
        for (size_t d = 1; d < first.shape.size(); ++d) {
          if (q.shape[d] != first.shape[d]) {
            return error(
                "Mismatched allgather tensor shapes beyond the first "
                "dimension: rank " +
                std::to_string(first.rank) + " has " + ShapeStr(first.shape) +
                " but rank " + std::to_string(q.rank) + " has " +
                ShapeStr(q.shape) + " for tensor '" + name + "'");
          }
        }
        resp.first_dims[q.rank] = q.shape.empty() ? 1 : q.shape[0];
      }
      // Joined ranks contribute zero rows.
      for (int r : joined_ranks_) resp.first_dims[r] = 0;
      break;
    }
    case OpType::BROADCAST: {
      for (const auto& q : reqs) {
        if (q.root_rank != first.root_rank) {
          return error("Mismatched broadcast root ranks: rank " +
                       std::to_string(first.rank) + " has root " +
                       std::to_string(first.root_rank) + " but rank " +
                       std::to_string(q.rank) + " has root " +
                       std::to_string(q.root_rank) + " for tensor '" + name +
                       "'");
        }
        if (q.shape != first.shape) {
          return error("Mismatched broadcast tensor shapes: rank " +
                       std::to_string(first.rank) + " has " +
                       ShapeStr(first.shape) + " but rank " +
                       std::to_string(q.rank) + " has " + ShapeStr(q.shape) +
                       " for tensor '" + name + "'");
        }
      }
      if (joined_ranks_.count(first.root_rank) != 0) {
        return error("broadcast root rank " +
                     std::to_string(first.root_rank) + " has joined");
      }
      break;
    }
    case OpType::ALLTOALL: {
      resp.all_splits.assign(static_cast<size_t>(cfg_.size) * cfg_.size, 0);
      for (const auto& q : reqs) {
        std::vector<int32_t> splits = q.splits;
        int64_t dim0 = q.shape.empty() ? 0 : q.shape[0];
        if (splits.empty()) {
          if (dim0 % cfg_.size != 0) {
            return error("alltoall first dimension (" + std::to_string(dim0) +
                         ") is not divisible by world size (" +
                         std::to_string(cfg_.size) +
                         ") and no splits were given for tensor '" + name +
                         "'");
          }
          splits.assign(cfg_.size,
                        static_cast<int32_t>(dim0 / cfg_.size));
        }
        if (static_cast<int>(splits.size()) != cfg_.size) {
          return error("alltoall splits length (" +
                       std::to_string(splits.size()) +
                       ") != world size for tensor '" + name + "'");
        }
        int64_t total = 0;
        for (auto s : splits) total += s;
        if (total != dim0) {
          return error("alltoall splits sum (" + std::to_string(total) +
                       ") != first dimension (" + std::to_string(dim0) +
                       ") for tensor '" + name + "'");
        }
        for (size_t d = 1; d < q.shape.size(); ++d) {
          if (q.shape[d] != first.shape[d]) {
            return error("Mismatched alltoall tensor shapes beyond the first "
                         "dimension for tensor '" + name + "'");
          }
        }
        for (int r = 0; r < cfg_.size; ++r) {
          resp.all_splits[static_cast<size_t>(q.rank) * cfg_.size + r] =
              splits[r];
        }
      }
      break;
    }
    case OpType::JOIN:
      break;
  }
  return resp;
}

void Core::FailAllOutstanding(const std::string& reason) {
  // The abort cascade reached this rank: freeze the flight ring to disk
  // before anything else unwinds. Latched — a later stall/signal on the
  // same incarnation must not overwrite the first post-mortem.
  if (flightrec_.DumpToFile(DumpReason::ABORT, data_plane_.failed_peer(),
                            "", /*fatal_once=*/true) &&
      m_flightrec_dumps_ != nullptr) {
    m_flightrec_dumps_->Inc();
  }
  MutexLock lk(mu_);
  for (auto& kv : handles_) {
    if (done_.count(kv.first) == 0) {
      done_[kv.first] = Status::Error(StatusCode::ABORTED, reason);
      outstanding_.erase(kv.second->name);
    }
  }
  pending_.clear();
  cv_.NotifyAll();
}

void Core::CoordinatorEmitResponses() {
  // A join barrier in progress can never reach world count once a non-joined
  // peer died: JOIN announcements bypass HandleReadyRequests, so check here.
  if (!joined_ranks_.empty() && !dead_ranks_.empty()) {
    LogWarn(0, "join barrier cannot complete after a peer died; failing over");
    world_broken_ = true;
  }
  if (world_broken_.exchange(false)) {
    // Tell every surviving rank the world is broken, then fail locally.
    Response dead;
    dead.type = ResponseType::SHUTDOWN;
    dead.error_message = "a peer process failed during a collective";
    Writer w;
    w.I32(static_cast<int32_t>(CtrlMsg::RESPONSES));
    w.I64(1);
    SerializeResponse(dead, &w);
    std::vector<uint8_t> payload = w.Take();
    for (int rank = 1; rank < cfg_.size; ++rank) {
      if (worker_fds_[rank] >= 0) SendFrame(worker_fds_[rank], payload);
    }
    message_table_.clear();
    ready_names_.clear();
    FailAllOutstanding("a peer process failed during a collective");
    {
      MutexLock lk(mu_);
      shutdown_ = true;
    }
    cv_.NotifyAll();
    return;
  }

  std::vector<Response> list;

  // Fuse ready allreduces with matching (dtype, reduce_op) under the fusion
  // threshold (reference: FuseResponses, controller.cc:686). Snapshot the
  // (autotune-mutable) threshold once per emit pass — the only writer is
  // this same background thread, so it cannot move mid-loop.
  int64_t fusion_threshold_now;
  {
    MutexLock lk(mu_);
    fusion_threshold_now = fusion_threshold_;
  }
  while (!ready_names_.empty()) {
    std::string name = ready_names_.front();
    ready_names_.pop_front();
    Response resp = BuildResponse(name);
    message_table_.erase(name);
    if (resp.type == ResponseType::ERROR) {
      // Don't let future bare-name hits resurrect disagreeing requests.
      cache_.Erase(name);
    }
    if (resp.type == ResponseType::OK &&
        (resp.op_type == OpType::ALLREDUCE ||
         resp.op_type == OpType::BROADCAST)) {
      int64_t fused_bytes =
          NumElements(resp.shapes[0]) *
          static_cast<int64_t>(DataTypeSize(resp.dtype));
      // Look ahead over the remaining ready names for fusable partners.
      // Broadcasts fuse too (PR 19, the grouped-enqueue payoff): same dtype
      // AND same root — the fused batch packs into one buffer and ships as
      // ONE tree broadcast (shapes may differ; they're independent
      // tensors). Alltoalls stay per-tensor: each carries its own split
      // matrix and packing them would serialize nothing the pairwise
      // schedule doesn't already overlap.
      for (auto it = ready_names_.begin(); it != ready_names_.end();) {
        Response peek = BuildResponse(*it);
        bool fusable =
            peek.type == ResponseType::OK &&
            peek.op_type == resp.op_type &&
            peek.dtype == resp.dtype && peek.reduce_op == resp.reduce_op &&
            (resp.op_type != OpType::BROADCAST ||
             peek.root_rank == resp.root_rank);
        if (fusable) {
          int64_t extra = NumElements(peek.shapes[0]) *
                          static_cast<int64_t>(DataTypeSize(peek.dtype));
          if (fused_bytes + extra > fusion_threshold_now) {
            ++it;
            continue;
          }
          resp.names.push_back(peek.names[0]);
          resp.shapes.push_back(peek.shapes[0]);
          resp.prescales.push_back(peek.prescales[0]);
          resp.postscales.push_back(peek.postscales[0]);
          fused_bytes += extra;
          message_table_.erase(*it);
          it = ready_names_.erase(it);
        } else {
          ++it;
        }
      }
    }
    list.push_back(std::move(resp));
  }

  // Join barrier complete?
  if (static_cast<int>(joined_ranks_.size()) == cfg_.size) {
    Response j;
    j.type = ResponseType::JOIN_DONE;
    j.op_type = OpType::JOIN;
    j.last_joined_rank = last_joined_rank_.load();
    list.push_back(std::move(j));
    joined_ranks_.clear();
  }

  if (list.empty()) return;

  if (cfg_.size > 1) {
    Writer w;
    w.I32(static_cast<int32_t>(CtrlMsg::RESPONSES));
    w.I64(static_cast<int64_t>(list.size()));
    for (const auto& resp : list) SerializeResponse(resp, &w);
    std::vector<uint8_t> payload = w.Take();
    for (int rank = 1; rank < cfg_.size; ++rank) {
      if (worker_fds_[rank] >= 0) outbox_.Queue(worker_fds_[rank], payload);
    }
  }

  // Execute BEFORE adopting any new autotuned parameters: the RESPONSES
  // frame for this list is already on the wire, and workers apply a PARAMS
  // frame only after executing it — if rank 0 adopted a new algo crossover
  // first, both sides could pick different allreduce algorithms for the
  // same tensor and desynchronize the data plane.
  ExecuteResponseList(list);

  if (param_manager_.active()) {
    // Score this cycle by payload bytes moved; adopt + broadcast any new
    // parameters (reference: ParameterManager::Update scored bytes/sec,
    // SynchronizeParameters broadcast, controller.cc:34-48).
    int64_t bytes = 0;
    for (const auto& resp : list) {
      if (resp.type != ResponseType::OK) continue;
      for (const auto& s : resp.shapes) {
        bytes += NumElements(s) * static_cast<int64_t>(DataTypeSize(resp.dtype));
      }
    }
    // Zero-byte lists (ERROR/JOIN_DONE only) are not data cycles; letting
    // them advance the sample would dilute the bytes/sec score with idle
    // time (reference advances samples by per-tensor step counts,
    // parameter_manager.cc:142-160).
    if (bytes > 0 && param_manager_.Update(bytes, NowSeconds())) {
      ParameterManager::Params p = param_manager_.Current();
      data_plane_.set_crossover_bytes(p.algo_crossover);
      data_plane_.set_sa_auto(p.sa_enabled);
      data_plane_.set_hier_auto(p.hier_enabled);
      comp_auto_ = p.wire_compression;
      {
        MutexLock lk(mu_);
        cycle_time_ms_ = p.cycle_time_ms;
        fusion_threshold_ = p.fusion_threshold;
        cache_.SetEnabled(p.cache_enabled);
      }
      UpdateParamGauges(p.cycle_time_ms, p.fusion_threshold, p.cache_enabled,
                        data_plane_.crossover_bytes());
      if (cfg_.size > 1) {
        Writer w;
        w.I32(static_cast<int32_t>(CtrlMsg::PARAMS));
        w.F64(p.cycle_time_ms);
        w.I64(p.fusion_threshold);
        w.I32(p.cache_enabled ? 1 : 0);
        w.I64(p.algo_crossover);
        w.I32(p.hier_enabled ? 1 : 0);
        w.I32(p.wire_compression);
        w.I32(p.sa_enabled ? 1 : 0);
        std::vector<uint8_t> payload = w.Take();
        for (int rank = 1; rank < cfg_.size; ++rank) {
          // Queued (flushed at pump end): a PARAMS frame lands on the wire
          // strictly after the RESPONSES list it was adopted behind.
          if (worker_fds_[rank] >= 0) outbox_.Queue(worker_fds_[rank], payload);
        }
      }
    }
  }
}

void Core::ExecuteResponseList(const std::vector<Response>& list) {
  // Everything queued so far MUST hit the wire before any collective below
  // can block: on rank 0 that includes the RESPONSES list itself (workers
  // cannot join the collective they never heard about), on workers the
  // READY/NEED_FULL repairs the coordinator is polling for.
  FlushCtrlOutbox();
  for (const auto& resp : list) ExecuteResponse(resp);
}

void Core::CompleteEntry(TensorEntry* e, const Status& st) {
  MutexLock lk(mu_);
  outstanding_.erase(e->name);
  done_[e->handle] = st;
  cv_.NotifyAll();
}

void Core::ExecuteResponse(const Response& resp) {
  if (resp.type == ResponseType::SHUTDOWN) {
    // Coordinator declared the world broken (a peer died mid-collective).
    FailAllOutstanding(resp.error_message.empty()
                           ? "a peer process failed during a collective"
                           : resp.error_message);
    {
      MutexLock lk(mu_);
      shutdown_ = true;
    }
    cv_.NotifyAll();
    return;
  }
  if (resp.type == ResponseType::JOIN_DONE) {
    {
      // Flag writes must happen under mu_ or a waiter that just evaluated its
      // predicate (false) can block after this notify and hang forever.
      MutexLock lk(mu_);
      last_joined_rank_ = resp.last_joined_rank;
      join_done_ = true;
    }
    cv_.NotifyAll();
    return;
  }

  // Collect local entries (may be absent on joined ranks -> zero tensors,
  // reference: tensor_queue.cc GetTensorEntriesFromResponse).
  std::vector<TensorEntry*> entries;
  std::vector<std::unique_ptr<TensorEntry>> zombies;  // zero stand-ins
  {
    MutexLock lk(mu_);
    for (size_t i = 0; i < resp.names.size(); ++i) {
      auto it = outstanding_.find(resp.names[i]);
      if (it != outstanding_.end()) {
        entries.push_back(it->second);
      } else {
        auto z = std::make_unique<TensorEntry>();
        z->name = resp.names[i];
        z->op_type = resp.op_type;
        z->reduce_op = resp.reduce_op;
        z->dtype = resp.dtype;
        z->shape = resp.shapes[i];
        z->prescale = resp.prescales[i];
        z->postscale = resp.postscales[i];
        z->root_rank = resp.root_rank;
        z->input = nullptr;  // zeros
        z->handle = -1;
        entries.push_back(z.get());
        zombies.push_back(std::move(z));
      }
    }
  }

  for (auto* e : entries) timeline_.NegotiateEnd(e->name);

  if (resp.type == ResponseType::ERROR) {
    Status st = Status::Error(StatusCode::INVALID_ARGUMENT,
                              resp.error_message);
    for (auto* e : entries) {
      if (e->handle >= 0) CompleteEntry(e, st);
    }
    return;
  }

  // Transport tag per op (timeline arg): which lane mix carried it, and
  // whether the allreduce took the hierarchical two-level path. The
  // compression tag sits next to it: the effective wire mode resolved for
  // this (fused) allreduce — identical on every rank (see
  // EffectiveCompression).
  std::string lane = data_plane_.transport_label();
  // Whole negotiated batch in bytes (all fused shapes): the compression
  // gate and the flight ring's OP_BEGIN/OP_END both key on it.
  int64_t batch_bytes = 0;
  for (const auto& s : resp.shapes) {
    batch_bytes +=
        NumElements(s) * static_cast<int64_t>(DataTypeSize(resp.dtype));
  }
  WireCompression comp = WireCompression::NONE;
  if (resp.op_type == OpType::ALLREDUCE && data_plane_.hier_active()) {
    lane += "+hier";
  }
  // Every data-moving op carries the wire-compression dimension now that
  // broadcast ships quantize-once root codes and alltoall quantizes each
  // block for its single receiver (PR 19); EffectiveCompression still
  // returns NONE for JOIN and for non-fp32 payloads.
  const bool comp_capable = resp.op_type != OpType::JOIN;
  if (comp_capable) comp = EffectiveCompression(resp, batch_bytes);
  const char* opname = resp.op_type == OpType::ALLREDUCE ? "ALLREDUCE"
                       : resp.op_type == OpType::ALLGATHER ? "ALLGATHER"
                       : resp.op_type == OpType::BROADCAST ? "BROADCAST"
                       : resp.op_type == OpType::ALLTOALL ? "ALLTOALL"
                                                          : "REDUCESCATTER";
  for (auto* e : entries) {
    timeline_.ActivityStart(e->name, opname, lane,
                            comp_capable ? WireCompressionName(comp) : "");
  }

  // Flight ring: one OP_BEGIN per dispatched collective under its primary
  // tensor name (fused batches share one data-plane op, like the trace
  // rows). arg = the OpType code; the matching OP_END carries the status;
  // bytes = the whole negotiated batch, same figure ExecuteFusedAllreduce
  // reports at OP_END.
  const int fr_name =
      entries.empty() ? -1 : flightrec_.InternName(entries[0]->name);
  {
    const int64_t now = Timeline::SteadyAbsUs();
    flightrec_.Record(FlightEvent::OP_BEGIN, fr_name, batch_bytes, -1, -1,
                      now, now, static_cast<int64_t>(resp.op_type), 0);
  }

  const double op_t0 = NowSeconds();
  const int64_t fr_t0 = Timeline::SteadyAbsUs();
  // Profiler op tag: samples during this op fold under the (first) tensor's
  // name; the data plane's phase scopes refine WALL into wire/wait/reduce/
  // codec slices underneath it.
  ProfOpScope prof_op(profiler_.InternOp(
      resp.names.empty() ? std::string("<unnamed>") : resp.names[0]));
  Status st = Status::OK();
  switch (resp.op_type) {
    case OpType::ALLREDUCE: {
      // Completion AND timeline finalization happen inside: once
      // CompleteEntry runs, the user thread may CopyResult and free the
      // entry, so nothing here may touch `entries` afterwards.
      ExecuteFusedAllreduce(resp, entries, comp);
      return;
    }
    case OpType::ALLGATHER: {
      TensorEntry* e = entries[0];
      size_t elem = DataTypeSize(e->dtype);
      int64_t row_bytes = static_cast<int64_t>(elem);
      for (size_t d = 1; d < resp.shapes[0].size(); ++d) {
        row_bytes *= resp.shapes[0][d];
      }
      int64_t my_first = e->shape.empty() ? 1 : e->shape[0];
      if (e->input == nullptr) my_first = 0;
      std::vector<int64_t> block_bytes(cfg_.size);
      for (int r = 0; r < cfg_.size; ++r) {
        block_bytes[r] = resp.first_dims[r] * row_bytes;
      }
      // Compressed allgather (PR 18): quantize-once owner codes on the ring
      // rotation — fp32 only (EffectiveCompression), no error-feedback
      // residual (a gathered payload is a value, not a gradient stream the
      // next iteration can correct).
      const bool grad_on =
          gradstats_.enabled() && resp.dtype == DataType::FLOAT32;
      if (comp != WireCompression::NONE) {
        data_plane_.BeginCompressedOp(comp, nullptr,
                                      grad_on ? &grad_quality_ : nullptr);
      }
      ByteBuf out;
      st = data_plane_.Allgatherv(e->input, my_first * row_bytes, block_bytes,
                                  &out);
      data_plane_.EndCompressedOp();
      if (st.ok()) {
        if (grad_on && comp != WireCompression::NONE) {
          gradstats_.RecordQuality(gradstats_.KeySlot(e->name), comp,
                                   grad_quality_);
        }
        // Divergence probe on the GATHERED vector (PR-12 extension): every
        // rank holds bitwise-identical bytes — the raw paths move exact
        // blocks, the compressed ring decodes the owners' codes verbatim —
        // which is exactly the allgathered-params invariant the ZeRO-1
        // sharded update stands on (docs/numerics.md).
        MaybeGradcheck(e->name, out.data(),
                       static_cast<int64_t>(out.size()));
        e->output = std::move(out);
      }
      break;
    }
    case OpType::BROADCAST: {
      // May carry multiple fused entries (grouped broadcast, PR 19): pack
      // the batch into one buffer at the root, run ONE tree broadcast, and
      // slice the result back out — the grouped-enqueue counterpart of
      // ExecuteFusedAllreduce. The single-entry path broadcasts in place.
      const bool grad_on =
          gradstats_.enabled() && resp.dtype == DataType::FLOAT32;
      // Compressed broadcast (PR 19): quantize-once at the root with
      // self-decode — fp32 only (EffectiveCompression), no error-feedback
      // residual (a broadcast payload is a value, not a gradient stream).
      if (comp != WireCompression::NONE) {
        data_plane_.BeginCompressedOp(comp, nullptr,
                                      grad_on ? &grad_quality_ : nullptr);
      }
      if (entries.size() == 1) {
        TensorEntry* e = entries[0];
        e->output.resize(static_cast<size_t>(e->byte_size()));
        if (cfg_.rank == resp.root_rank && e->input != nullptr) {
          memcpy(e->output.data(), e->input, e->output.size());
        }
        st = data_plane_.Broadcast(e->output.data(),
                                   static_cast<int64_t>(e->output.size()),
                                   resp.root_rank);
        data_plane_.EndCompressedOp();
        if (st.ok()) {
          // Every rank holds bitwise-identical broadcast bytes (raw moves
          // exact bytes; compressed decodes the root's codes verbatim) —
          // the same PR-12 fingerprint invariant allgather rides.
          MaybeGradcheck(e->name, e->output.data(),
                         static_cast<int64_t>(e->output.size()));
        }
      } else {
        ByteBuf packed(static_cast<size_t>(batch_bytes));
        if (cfg_.rank == resp.root_rank) {
          size_t off = 0;
          for (auto* e : entries) {
            const size_t n = static_cast<size_t>(e->byte_size());
            if (e->input != nullptr) {
              memcpy(packed.data() + off, e->input, n);
            } else {
              memset(packed.data() + off, 0, n);
            }
            off += n;
          }
        }
        st = data_plane_.Broadcast(packed.data(), batch_bytes,
                                   resp.root_rank);
        data_plane_.EndCompressedOp();
        if (st.ok()) {
          MaybeGradcheck(entries[0]->name, packed.data(), batch_bytes);
          size_t off = 0;
          for (auto* e : entries) {
            const size_t n = static_cast<size_t>(e->byte_size());
            e->output.assign(packed.data() + off, packed.data() + off + n);
            off += n;
          }
        }
      }
      if (st.ok() && grad_on && comp != WireCompression::NONE &&
          cfg_.rank == resp.root_rank) {
        // Only the root ran the quantizer; other ranks' accumulators are
        // empty and would dilute the per-key quality baselines.
        gradstats_.RecordQuality(gradstats_.KeySlot(entries[0]->name), comp,
                                 grad_quality_);
      }
      break;
    }
    case OpType::ALLTOALL: {
      TensorEntry* e = entries[0];
      size_t elem = DataTypeSize(e->dtype);
      int64_t row_bytes = static_cast<int64_t>(elem);
      for (size_t d = 1; d < resp.shapes[0].size(); ++d) {
        row_bytes *= resp.shapes[0][d];
      }
      std::vector<int64_t> send_bytes(cfg_.size, 0), recv_bytes(cfg_.size, 0);
      for (int r = 0; r < cfg_.size; ++r) {
        send_bytes[r] =
            resp.all_splits[static_cast<size_t>(cfg_.rank) * cfg_.size + r] *
            row_bytes;
        recv_bytes[r] =
            resp.all_splits[static_cast<size_t>(r) * cfg_.size + cfg_.rank] *
            row_bytes;
      }
      // Joined rank: no input buffer, but the negotiated split matrix says
      // this rank sends nothing (its Request never existed), so a null
      // input only backs zero-byte sends. Guard anyway: a zombie with
      // nonzero sends must contribute zeros, not garbage.
      std::vector<uint8_t> zero_input;
      const void* src = e->input;
      if (src == nullptr) {
        zero_input.assign(static_cast<size_t>(e->byte_size()), 0);
        src = zero_input.data();
      }
      // Compressed alltoall (PR 19): every block is quantized once at its
      // sender and decoded at its single receiver — fp32 only, no residual
      // (routed activations are values, not gradient streams).
      const bool grad_on =
          gradstats_.enabled() && resp.dtype == DataType::FLOAT32;
      if (comp != WireCompression::NONE) {
        data_plane_.BeginCompressedOp(comp, nullptr,
                                      grad_on ? &grad_quality_ : nullptr);
      }
      ByteBuf out;
      st = data_plane_.Alltoallv(src, send_bytes, recv_bytes, &out);
      data_plane_.EndCompressedOp();
      if (st.ok()) {
        if (grad_on && comp != WireCompression::NONE) {
          gradstats_.RecordQuality(gradstats_.KeySlot(e->name), comp,
                                   grad_quality_);
        }
        // NO MaybeGradcheck here: alltoall outputs legitimately differ per
        // rank — fingerprint-comparing them would convict healthy ranks.
        e->output = std::move(out);
      }
      break;
    }
    case OpType::REDUCESCATTER: {
      TensorEntry* e = entries[0];
      const int64_t total_elems = NumElements(resp.shapes[0]);
      std::vector<uint8_t> input_copy;
      const void* src = e->input;
      if (src == nullptr) {
        input_copy.assign(static_cast<size_t>(e->byte_size()), 0);
        src = input_copy.data();
      } else if (e->prescale != 1.0) {
        // Prescale without touching the user's pinned input buffer.
        input_copy.assign(static_cast<const uint8_t*>(src),
                          static_cast<const uint8_t*>(src) + e->byte_size());
        ScaleBuffer(input_copy.data(), total_elems, resp.dtype, e->prescale);
        src = input_copy.data();
      }
      // Compressed reduce-scatter (PR 18): the compressed ring allreduce's
      // first half, with the same per-tensor error-feedback residual.
      const bool grad_on =
          gradstats_.enabled() && resp.dtype == DataType::FLOAT32;
      if (comp != WireCompression::NONE) {
        bool residual_reset = false;
        float* residual =
            residual_store_.Get(e->name, total_elems, &residual_reset);
        if (residual_reset) {
          m_residual_resets_->Inc();
          gradstats_.NoteResidualReset();
        }
        data_plane_.BeginCompressedOp(comp, residual,
                                      grad_on ? &grad_quality_ : nullptr);
      }
      ByteBuf out;
      st = data_plane_.ReduceScatter(src, total_elems, e->dtype,
                                     e->reduce_op, &out);
      data_plane_.EndCompressedOp();
      if (st.ok()) {
        if (grad_on && comp != WireCompression::NONE) {
          gradstats_.RecordQuality(gradstats_.KeySlot(e->name), comp,
                                   grad_quality_);
        }
        // AVERAGE arrives as SUM + postscale 1/size (Enqueue), applied to
        // this rank's chunk only — the reduced full vector never exists.
        ScaleBuffer(out.data(),
                    static_cast<int64_t>(out.size()) /
                        static_cast<int64_t>(DataTypeSize(resp.dtype)),
                    resp.dtype, e->postscale);
        e->output = std::move(out);
      }
      break;
    }
    case OpType::JOIN:
      break;
  }

  // Every op carries real algorithm + compression labels now (PR 18 for
  // reduce-scatter/allgather, PR 19 for broadcast's bcast_tree/bcast_flat
  // and alltoall's pairwise) — the same dimensions the per-op perf
  // baselines key on.
  if (!entries.empty()) {
    ObserveOp(opname, NowSeconds() - op_t0, entries[0]->byte_size(),
              comp_capable ? data_plane_.last_algo_label() : "none",
              data_plane_.transport_label(), false,
              comp_capable ? WireCompressionName(comp) : "none", resp.dtype,
              st.ok(), entries[0]->name);
  }
  flightrec_.Record(FlightEvent::OP_END, fr_name, batch_bytes, -1, -1,
                    fr_t0, Timeline::SteadyAbsUs(), st.ok() ? 0 : 1, 0);
  if (!st.ok() && data_plane_.aborted()) HandleDataPlaneFailure(st);

  // Reduce-scatter/allgather (PR 18) and broadcast/alltoall (PR 19) all
  // feed the cumulative raw/wire byte counters (their data-plane entry
  // points reset + publish the per-op accumulators), so their timeline
  // op-done events must carry the same figures — /metrics and the timeline
  // tell one story (tests/data/metrics_worker.py pins sum(timeline) ==
  // counter). Only JOIN (no data-plane entry) stays omitted; ALLREDUCE
  // completes inside ExecuteFusedAllreduce, which meters its own.
  const bool byte_metered = resp.op_type == OpType::REDUCESCATTER ||
                            resp.op_type == OpType::ALLGATHER ||
                            resp.op_type == OpType::BROADCAST ||
                            resp.op_type == OpType::ALLTOALL;
  const int64_t done_raw = byte_metered ? data_plane_.op_raw_bytes() : -1;
  const int64_t done_wire = byte_metered ? data_plane_.op_wire_bytes() : -1;
  for (auto* e : entries) {
    timeline_.ActivityEnd(e->name);
    timeline_.OpDone(e->name, st.ok() ? "ok" : st.reason, done_raw,
                     done_wire);
    if (e->handle >= 0) CompleteEntry(e, st);
  }
}

namespace {

// Apply a scalar factor in place (reference: prescale/postscale hooks,
// collective_operations.h:106-136 — incl. the fp16 path). Halp-precision
// scales through float; integers scale in double with round-to-nearest so
// AVERAGE(int) behaves like the framework-side true-division the reference
// falls back to.
void ScaleBuffer(void* data, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::FLOAT32: {
      float* p = static_cast<float*>(data);
      for (int64_t i = 0; i < count; ++i) p[i] *= static_cast<float>(factor);
      break;
    }
    case DataType::FLOAT64: {
      double* p = static_cast<double*>(data);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::FLOAT16:
    case DataType::BFLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(data);
      const bool bf = dtype == DataType::BFLOAT16;
      for (int64_t i = 0; i < count; ++i) {
        float f = bf ? Bf16ToFloatPublic(p[i]) : HalfToFloatPublic(p[i]);
        f = static_cast<float>(f * factor);
        p[i] = bf ? FloatToBf16Public(f) : FloatToHalfPublic(f);
      }
      break;
    }
    case DataType::INT32: {
      int32_t* p = static_cast<int32_t*>(data);
      for (int64_t i = 0; i < count; ++i) {
        p[i] = static_cast<int32_t>(llround(p[i] * factor));
      }
      break;
    }
    case DataType::INT64: {
      int64_t* p = static_cast<int64_t*>(data);
      for (int64_t i = 0; i < count; ++i) {
        p[i] = static_cast<int64_t>(llround(p[i] * factor));
      }
      break;
    }
    case DataType::UINT8: {
      uint8_t* p = static_cast<uint8_t*>(data);
      for (int64_t i = 0; i < count; ++i) {
        p[i] = static_cast<uint8_t>(llround(p[i] * factor));
      }
      break;
    }
    case DataType::INT8: {
      int8_t* p = static_cast<int8_t*>(data);
      for (int64_t i = 0; i < count; ++i) {
        p[i] = static_cast<int8_t>(llround(p[i] * factor));
      }
      break;
    }
    case DataType::BOOL:
      break;  // scaling a bool mask is meaningless; leave untouched
  }
}

}  // namespace

WireCompression Core::EffectiveCompression(const Response& resp,
                                           int64_t total_bytes) {
  int32_t mode = cfg_.wire_compression;
  if (mode == static_cast<int32_t>(WireCompression::AUTO)) mode = comp_auto_;
  if (mode == static_cast<int32_t>(WireCompression::NONE)) {
    return WireCompression::NONE;
  }
  if (resp.dtype != DataType::FLOAT32) return WireCompression::NONE;
  // Every data-moving op has a compressed schedule now: the reducing ops
  // and allgather since PR 18, broadcast (quantize-once root codes) and
  // alltoall (per-block sender codes) since PR 19. JOIN moves no data.
  if (resp.op_type == OpType::JOIN) return WireCompression::NONE;
  // Adasum's adaptive combine needs the exact partials; MIN/MAX/PRODUCT
  // have no meaningful quantized-sum form. reduce_op is per-response (all
  // fused entries share it); allgather/broadcast/alltoall carry no
  // reduction to gate on.
  if ((resp.op_type == OpType::ALLREDUCE ||
       resp.op_type == OpType::REDUCESCATTER) &&
      resp.reduce_op != ReduceOp::SUM &&
      resp.reduce_op != ReduceOp::AVERAGE) {
    return WireCompression::NONE;
  }
  // Small-tensor bypass: below this size the quantization headers and the
  // extra passes cost more than the bytes they save.
  if (total_bytes < cfg_.compression_min_bytes) return WireCompression::NONE;
  // Sensitive-layer skip list (biases/norms): one match anywhere in the
  // fused batch keeps the whole op dense — the batch shares a wire format.
  if (comp_skip_set_) {
    for (const auto& name : resp.names) {
      auto it = comp_skip_memo_.find(name);
      if (it == comp_skip_memo_.end()) {
        if (comp_skip_memo_.size() >= 4096) comp_skip_memo_.clear();
        it = comp_skip_memo_
                 .emplace(name, std::regex_search(name, comp_skip_re_))
                 .first;
      }
      if (it->second) return WireCompression::NONE;
    }
  }
  return static_cast<WireCompression>(mode);
}

void Core::ExecuteFusedAllreduce(const Response& resp,
                                 std::vector<TensorEntry*>& entries,
                                 WireCompression comp) {
  // Reference: fused MemcpyInFusionBuffer -> collective -> MemcpyOut
  // (collective_operations.cc + mpi_operations.cc).
  size_t elem = DataTypeSize(resp.dtype);
  int64_t total_elems = 0;
  for (const auto& s : resp.shapes) total_elems += NumElements(s);
  const int64_t total_bytes = total_elems * static_cast<int64_t>(elem);

  // Fusion-buffer utilization: how full each negotiated batch ran against
  // the (possibly autotuned) threshold. Single-tensor batches count too —
  // a utilization histogram stuck near 0 is the "raise the threshold or
  // slow the cycle" signal the reference surfaces only via timeline
  // archaeology.
  m_fusion_batch_bytes_->Observe(static_cast<double>(total_bytes));
  // Memory telemetry: live fusion-buffer occupancy + high-water mark
  // (docs/profiling.md "Memory telemetry").
  if (m_fusion_buffer_gauge_ != nullptr) {
    m_fusion_buffer_gauge_->Set(static_cast<double>(total_bytes));
    if (total_bytes > fusion_highwater_bytes_) {
      fusion_highwater_bytes_ = total_bytes;
      m_fusion_highwater_gauge_->Set(static_cast<double>(total_bytes));
    }
  }
  {
    int64_t threshold;
    {
      MutexLock lk(mu_);
      threshold = fusion_threshold_;
    }
    if (threshold > 0) {
      m_fusion_utilization_->Observe(static_cast<double>(total_bytes) /
                                     static_cast<double>(threshold));
    }
  }
  if (entries.size() > 1) {
    m_fused_tensors_->Add(static_cast<int64_t>(entries.size()));
  }
  const double op_t0 = NowSeconds();
  const int64_t exec_start_us = Timeline::SteadyAbsUs();
  // FUSION-WAIT trace spans (emitted after the collective, once the data
  // plane has rolled its sampling decision): each tensor's enqueue-to-
  // execution wait on its own row — how long it sat queued/fusing before
  // the batch ran (docs/tracing.md).
  auto emit_fusion_wait = [&](const std::vector<TensorEntry*>& es) {
    for (TensorEntry* te : es) {
      if (te->enqueued_at_us > 0) {
        // Flight ring: unsampled, every batch (arg = tensors in the batch).
        flightrec_.Record(FlightEvent::FUSION_WAIT,
                          flightrec_.InternName(te->name), total_bytes, -1,
                          -1, te->enqueued_at_us, exec_start_us,
                          static_cast<int64_t>(es.size()), 0);
      }
    }
    if (!data_plane_.trace_sampling_op()) return;
    const std::string args =
        "{\"tensors\": " + std::to_string(es.size()) +
        ", \"batch_bytes\": " + std::to_string(total_bytes) + "}";
    for (TensorEntry* te : es) {
      if (te->enqueued_at_us > 0) {
        timeline_.Span(te->name, "FUSION-WAIT", te->enqueued_at_us,
                       exec_start_us, args);
      }
    }
  };

  // Error-feedback residuals live at the compressing rank, keyed by the
  // fused batch's name signature (steady-state fusions reuse the buffer;
  // a changed composition starts fresh — best-effort, like the reference's
  // per-entry feedback buffers).
  float* residual = nullptr;
  if (comp != WireCompression::NONE) {
    std::string key = resp.names.empty() ? std::string() : resp.names[0];
    for (size_t i = 1; i < resp.names.size(); ++i) {
      key += ';';
      key += resp.names[i];
    }
    bool residual_reset = false;
    residual = residual_store_.Get(key, total_elems, &residual_reset);
    if (residual_reset) {
      // A live key's error feedback was dropped (element count changed —
      // refused fusion or reshape — or the store hit its cap). Quality
      // telemetry, not bookkeeping: the accumulated correction restarts
      // from zero, so make it visible (docs/numerics.md).
      m_residual_resets_->Inc();
      gradstats_.NoteResidualReset();
      LogWarn(cfg_.rank,
              "error-feedback residual reset for '%s' (element count "
              "changed mid-run or store overflow); compression restarts "
              "with zero feedback",
              key.c_str());
    }
  }
  // Gradient-health instrumentation for this op (docs/numerics.md):
  // moments fold into the fp32 copy-in below; the compressed hops fill
  // grad_quality_ through the data plane.
  const bool grad_on =
      gradstats_.enabled() && resp.dtype == DataType::FLOAT32;
  data_plane_.BeginCompressedOp(
      comp, residual,
      grad_on && comp != WireCompression::NONE ? &grad_quality_ : nullptr);
  // The per-key signature the health stats are keyed by: the primary
  // tensor for unfused ops (per-layer granularity), primary + batch width
  // for fused batches (same convention as the perf baselines). Built only
  // when gradstats will consume it — off must stay one branch per op.
  const std::string grad_key =
      !grad_on || entries.empty()
          ? std::string()
          : (entries.size() == 1
                 ? entries[0]->name
                 : entries[0]->name + "(+" +
                       std::to_string(entries.size() - 1) + ")");
  // Fail-fast path for HVDTPU_NANCHECK=abort: complete every entry with
  // one coherent error BEFORE any data moves, then break the world — a
  // rank that keeps collectives running on NaN gradients just burns the
  // fleet to diverge the loss.
  auto nan_abort = [&](const std::string& tensor) {
    data_plane_.EndCompressedOp();
    Status st = Status::Error(
        StatusCode::INVALID_ARGUMENT,
        "non-finite gradient in tensor '" + tensor +
            "' (HVDTPU_NANCHECK=abort)");
    flightrec_.Record(
        FlightEvent::OP_END,
        entries.empty() ? -1 : flightrec_.InternName(entries[0]->name),
        total_bytes, -1, -1, exec_start_us, Timeline::SteadyAbsUs(), 1, 0);
    for (auto* e : entries) {
      timeline_.ActivityEnd(e->name);
      timeline_.OpDone(e->name, st.reason);
      if (e->handle >= 0) CompleteEntry(e, st);
    }
    // Break every lane so peers blocked in this collective cascade-fail
    // within one detect slice instead of hanging; then fail over like a
    // data-plane failure (the coordinator broadcasts SHUTDOWN).
    data_plane_.Abort();
    if (cfg_.rank == 0) {
      world_broken_ = true;
    } else {
      worker_failover_pending_ = true;
    }
  };

  if (entries.size() == 1) {
    // Unfused: the entry's output buffer IS the working buffer — one big
    // copy (and one allocation) less than staging through a fusion buffer.
    TensorEntry* e = entries[0];
    const size_t nbytes = static_cast<size_t>(total_elems) * elem;
    if (e->input != nullptr) {
      const uint8_t* in = static_cast<const uint8_t*>(e->input);
      // ByteBuf resize is malloc-only (no zero-fill pass — every byte is
      // about to be overwritten); explicit memcpy keeps glibc's
      // large-copy non-temporal path, which a range insert through the
      // custom allocator would lose.
      e->output.resize(nbytes);
      if (grad_on) {
        // Single-pass fused copy + moments scan (docs/numerics.md): the
        // scan rides the copy's load stream, within the A/B-measured
        // noise of plain memcpy.
        GradMoments m;
        CopyMomentsF32(reinterpret_cast<float*>(e->output.data()),
                       reinterpret_cast<const float*>(in), total_elems,
                       &m);
        const int slot = gradstats_.KeySlot(e->name);
        gradstats_.RecordMoments(slot, m);
        if (NoteNonfinite(e->name, m, slot)) {
          nan_abort(e->name);
          return;
        }
      } else {
        memcpy(e->output.data(), in, nbytes);
      }
      ScaleBuffer(e->output.data(), total_elems, resp.dtype, e->prescale);
    } else {
      e->output.assign(nbytes, 0);
    }
    Status st;
    if (resp.reduce_op == ReduceOp::ADASUM) {
      st = data_plane_.AdasumAllreduce(e->output.data(), total_elems,
                                       resp.dtype);
    } else {
      st = data_plane_.Allreduce(e->output.data(), total_elems, resp.dtype,
                                 resp.reduce_op);
    }
    data_plane_.EndCompressedOp();
    if (st.ok() && grad_on) {
      if (comp != WireCompression::NONE) {
        gradstats_.RecordQuality(gradstats_.KeySlot(grad_key), comp,
                                 grad_quality_);
      }
      // Fingerprint BEFORE postscale: AVERAGE's 1/size postscale is
      // per-entry, the pre-postscale reduction is the bitwise-identical
      // artifact every rank holds.
      if (resp.reduce_op != ReduceOp::ADASUM) {
        MaybeGradcheck(e->name, e->output.data(),
                       static_cast<int64_t>(nbytes));
      }
    }
    ObserveOp("ALLREDUCE", NowSeconds() - op_t0, total_bytes,
              data_plane_.last_algo_label(), data_plane_.transport_label(),
              data_plane_.hier_active(), WireCompressionName(comp),
              resp.dtype, st.ok(), e->name);
    flightrec_.Record(FlightEvent::OP_END, flightrec_.InternName(e->name),
                      total_bytes, -1, -1, exec_start_us,
                      Timeline::SteadyAbsUs(), st.ok() ? 0 : 1, 0);
    if (!st.ok() && data_plane_.aborted()) HandleDataPlaneFailure(st);
    if (st.ok()) {
      ScaleBuffer(e->output.data(), total_elems, resp.dtype, e->postscale);
    }
    emit_fusion_wait(entries);
    timeline_.ActivityEnd(e->name);
    timeline_.OpDone(e->name, st.ok() ? "ok" : st.reason,
                     data_plane_.op_raw_bytes(),
                     data_plane_.op_wire_bytes());
    if (e->handle >= 0) CompleteEntry(e, st);
    return;
  }

  // ByteBuf: malloc-only sizing — every segment is either copied over
  // below or explicitly zeroed (zombie stand-ins), so the old whole-buffer
  // zero-fill pass was pure waste.
  ByteBuf fusion;
  fusion.resize(static_cast<size_t>(total_elems) * elem);

  int64_t off = 0;
  for (size_t i = 0; i < entries.size(); ++i) {
    TensorEntry* e = entries[i];
    int64_t n = NumElements(resp.shapes[i]);
    if (e->input != nullptr) {
      if (grad_on) {
        // Moments fold into the copy-in the fusion buffer already pays
        // for — per TENSOR, so each layer keeps its own norm baseline
        // even inside a fused batch (docs/numerics.md).
        GradMoments m;
        CopyMomentsF32(reinterpret_cast<float*>(fusion.data() + off * elem),
                       reinterpret_cast<const float*>(e->input), n, &m);
        const int slot = gradstats_.KeySlot(e->name);
        gradstats_.RecordMoments(slot, m);
        if (NoteNonfinite(e->name, m, slot)) {
          nan_abort(e->name);
          return;
        }
      } else {
        memcpy(fusion.data() + off * elem, e->input,
               static_cast<size_t>(n) * elem);
      }
      ScaleBuffer(fusion.data() + off * elem, n, resp.dtype, e->prescale);
    } else {
      // Joined rank's zero stand-in: only these segments need zeroing.
      memset(fusion.data() + off * elem, 0, static_cast<size_t>(n) * elem);
    }
    off += n;
  }

  Status st;
  if (resp.reduce_op == ReduceOp::ADASUM) {
    st = data_plane_.AdasumAllreduce(fusion.data(), total_elems, resp.dtype);
  } else {
    st = data_plane_.Allreduce(fusion.data(), total_elems, resp.dtype,
                               resp.reduce_op);
  }
  data_plane_.EndCompressedOp();
  if (st.ok() && grad_on) {
    if (comp != WireCompression::NONE) {
      gradstats_.RecordQuality(gradstats_.KeySlot(grad_key), comp,
                               grad_quality_);
    }
    if (resp.reduce_op != ReduceOp::ADASUM) {
      MaybeGradcheck(grad_key, fusion.data(), total_bytes);
    }
  }
  const int64_t op_raw = data_plane_.op_raw_bytes();
  const int64_t op_wire = data_plane_.op_wire_bytes();
  // Fused batches key their perf baseline on the primary tensor plus the
  // batch width: steady-state fusions recur with the same composition, and
  // a re-fused batch must not be judged against a different one's baseline.
  ObserveOp("ALLREDUCE", NowSeconds() - op_t0, total_bytes,
            data_plane_.last_algo_label(), data_plane_.transport_label(),
            data_plane_.hier_active(), WireCompressionName(comp), resp.dtype,
            st.ok(),
            entries.empty()
                ? std::string()
                : entries[0]->name + "(+" +
                      std::to_string(entries.size() - 1) + ")");
  flightrec_.Record(
      FlightEvent::OP_END,
      entries.empty() ? -1 : flightrec_.InternName(entries[0]->name),
      total_bytes, -1, -1, exec_start_us, Timeline::SteadyAbsUs(),
      st.ok() ? 0 : 1, 0);
  if (!st.ok() && data_plane_.aborted()) HandleDataPlaneFailure(st);
  emit_fusion_wait(entries);

  off = 0;
  for (size_t i = 0; i < entries.size(); ++i) {
    TensorEntry* e = entries[i];
    int64_t n = NumElements(resp.shapes[i]);
    if (st.ok()) {
      ScaleBuffer(fusion.data() + off * elem, n, resp.dtype, e->postscale);
      // resize + memcpy, NOT range-assign: through the ByteBuf's custom
      // allocator a range copy loses libstdc++'s memmove lowering (see
      // common.h) — this is the copy-OUT of up to a full fusion batch.
      e->output.resize(static_cast<size_t>(n) * elem);
      memcpy(e->output.data(), fusion.data() + off * elem,
             static_cast<size_t>(n) * elem);
    }
    off += n;
    // Timeline events BEFORE CompleteEntry: completion hands ownership to
    // the user thread, which may free the entry immediately.
    timeline_.ActivityEnd(e->name);
    timeline_.OpDone(e->name, st.ok() ? "ok" : st.reason, op_raw, op_wire);
    if (e->handle >= 0) CompleteEntry(e, st);
  }
}

void Core::HandleDataPlaneFailure(const Status& st) {
  // Freeze the flight ring NOW, synchronously at detection: the deferred
  // world_broken_/failover consumption runs a background cycle later, and
  // a user thread that sees the op error first may Shutdown() the loop
  // before that cycle happens — losing the post-mortem to a race. The
  // fatal-once latch keeps this and FailAllOutstanding's dump (the
  // SHUTDOWN-response path, whose plane never aborted locally) idempotent.
  if (flightrec_.DumpToFile(DumpReason::ABORT, data_plane_.failed_peer(),
                            "", /*fatal_once=*/true) &&
      m_flightrec_dumps_ != nullptr) {
    m_flightrec_dumps_->Inc();
  }
  if (!failure_counted_) {
    failure_counted_ = true;
    m_failures_detected_->Inc();
    const int peer = data_plane_.failed_peer();
    LogWarn(cfg_.rank,
            "data-plane failure detected%s: %s",
            peer >= 0 ? (" (suspect rank " + std::to_string(peer) + ")")
                            .c_str()
                      : "",
            st.reason.c_str());
    if (peer >= 0 && cfg_.rank == 0) dead_ranks_.insert(peer);
  }
  // Make sure EVERY lane is broken (idempotent): the half-closed sockets
  // and woken futex waiters are how detection cascades rank-to-rank within
  // one detect slice per hop, even to ranks idling between collectives.
  data_plane_.Abort();
  if (cfg_.rank == 0) {
    // Consumed by the next CoordinatorEmitResponses: broadcast SHUTDOWN to
    // every surviving worker, fail local handles, stop the loop.
    world_broken_ = true;
  } else {
    // Worker: fail over so the user thread raises HvdTpuInternalError and
    // elastic mode can re-rendezvous. DEFERRED to the top of the next
    // background cycle (like rank 0's world_broken_): failing the
    // outstanding handles HERE would wake the user thread while the caller
    // (ExecuteResponse) is still walking this response's entries — and a
    // woken waiter may CopyResult and free them mid-walk. The coordinator
    // learns of the failure through its own data plane (it participates in
    // the same collective) or the control-plane EOF.
    worker_failover_pending_ = true;
  }
}

void Core::CheckStalls() {
  // Reference: StallInspector (stall_inspector.{h,cc}) — rank 0 warns when
  // some ranks announced a tensor and others have not for stall_warn_secs,
  // and force-shuts-down after stall_shutdown_secs (stall_inspector.cc
  // ShutdownIfStalled).
  double now = NowSeconds();
  // `stalled` gauge: 1 while ANY tensor sits past the warning threshold
  // (not just at the warning edge — it stays up until the laggard arrives
  // and the slot leaves message_table_, so a scrape can't miss the window).
  bool any_stalled = false;
  for (const auto& kv : message_table_) {
    if (now - kv.second.first_seen >= cfg_.stall_warn_secs) {
      any_stalled = true;
      break;
    }
  }
  m_stalled_->Set(any_stalled ? 1 : 0);
  // AUTO (< 0) resolves to 10x the warning threshold so the escalation is
  // never dead code: a wedged world always breaks eventually instead of
  // hanging until an operator notices (the reference defaults this OFF).
  const double shutdown_secs = EffectiveStallShutdownSecs();
  for (auto& kv : message_table_) {
    auto& slot = kv.second;
    if (shutdown_secs > 0 && now - slot.first_seen > shutdown_secs) {
      LogWarn(0,
              "tensor '%s' stalled for over %.0f s "
              "(HVDTPU_STALL_SHUTDOWN_TIME_SECONDS); aborting the job",
              kv.first.c_str(), shutdown_secs);
      m_failures_detected_->Inc();
      {
        // send_peer = the first rank that never announced the tensor: the
        // post-mortem verdict's prime suspect for a wedged world. Joined
        // ranks legitimately never announce (their contribution is zeros)
        // and dead ranks are already convicted elsewhere — skip both, or
        // the verdict would blame a healthy rank that finished training.
        std::unordered_set<int> ready;
        for (const auto& q : slot.requests) ready.insert(q.rank);
        int missing = -1;
        for (int r = 0; r < cfg_.size; ++r) {
          if (ready.count(r) == 0 && joined_ranks_.count(r) == 0 &&
              dead_ranks_.count(r) == 0) {
            missing = r;
            break;
          }
        }
        const int64_t t = Timeline::SteadyAbsUs();
        flightrec_.Record(FlightEvent::STALL,
                          flightrec_.InternName(kv.first), 0, missing, -1,
                          t, t, /*escalated=*/1, 0);
      }
      if (flightrec_.DumpToFile(DumpReason::STALL, -1, "",
                                /*fatal_once=*/true) &&
          m_flightrec_dumps_ != nullptr) {
        m_flightrec_dumps_->Inc();
      }
      world_broken_ = true;
      return;
    }
    if (slot.stall_warned ||
        now - slot.first_seen < cfg_.stall_warn_secs) {
      continue;
    }
    std::string have, missing;
    std::unordered_set<int> ready_ranks;
    for (const auto& q : slot.requests) ready_ranks.insert(q.rank);
    for (int r = 0; r < cfg_.size; ++r) {
      std::string& tgt = ready_ranks.count(r) ? have : missing;
      if (!tgt.empty()) tgt += ", ";
      tgt += std::to_string(r);
    }
    LogWarn(0,
            "One or more tensors were submitted to be reduced/gathered but "
            "some ranks have not yet done so: tensor '%s' ready on ranks "
            "[%s], waiting on ranks [%s] for %.0f s",
            kv.first.c_str(), have.c_str(), missing.c_str(),
            now - slot.first_seen);
    slot.stall_warned = true;
    m_stall_warnings_->Inc();
    {
      const int64_t t = Timeline::SteadyAbsUs();
      flightrec_.Record(FlightEvent::STALL, flightrec_.InternName(kv.first),
                        0, -1, -1, t, t, /*escalated=*/0, 0);
    }
  }
}

}  // namespace hvdtpu

// ---------------------------------------------------------------------------
// C API (ctypes surface; reference: operations.cc:705-913)
// ---------------------------------------------------------------------------

using hvdtpu::Core;
using hvdtpu::CoreConfig;
using hvdtpu::Status;
using hvdtpu::TensorEntry;

namespace {

void FillErr(const Status& st, char* err, int errlen) {
  if (err != nullptr && errlen > 0) {
    snprintf(err, static_cast<size_t>(errlen), "%s", st.reason.c_str());
  }
}

}  // namespace

extern "C" {

void* hvdtpu_create(int rank, int size, int local_rank, int local_size,
                    int cross_rank, int cross_size, const char* coord_host,
                    int coord_port, const char* my_host, double cycle_time_ms,
                    long long fusion_threshold, const char* timeline_path,
                    int timeline_mark_cycles, double stall_warn_secs) {
  hvdtpu::InstallTerminateHandlerOnce();
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.local_rank = local_rank;
  cfg.local_size = local_size;
  cfg.cross_rank = cross_rank;
  cfg.cross_size = cross_size;
  cfg.coord_host = coord_host ? coord_host : "127.0.0.1";
  cfg.coord_port = coord_port;
  cfg.my_host = my_host ? my_host : "127.0.0.1";
  cfg.cycle_time_ms = cycle_time_ms;
  cfg.fusion_threshold = fusion_threshold;
  cfg.timeline_path = timeline_path ? timeline_path : "";
  cfg.timeline_mark_cycles = timeline_mark_cycles != 0;
  cfg.stall_warn_secs = stall_warn_secs;
  return new Core(cfg);
}

int hvdtpu_start(void* core, char* err, int errlen) {
  Status st = static_cast<Core*>(core)->Start();
  FillErr(st, err, errlen);
  return st.ok() ? 0 : -1;
}

void hvdtpu_shutdown(void* core) { static_cast<Core*>(core)->Shutdown(); }

void hvdtpu_destroy(void* core) { delete static_cast<Core*>(core); }

long long hvdtpu_enqueue(void* core, const char* name, int op_type,
                         int reduce_op, int dtype, const long long* shape,
                         int ndim, const void* data, double prescale,
                         double postscale, int root_rank, const int* splits,
                         int nsplits, char* err, int errlen) {
  TensorEntry e;
  e.name = name;
  e.op_type = static_cast<hvdtpu::OpType>(op_type);
  e.reduce_op = static_cast<hvdtpu::ReduceOp>(reduce_op);
  e.dtype = static_cast<hvdtpu::DataType>(dtype);
  e.shape.assign(shape, shape + ndim);
  e.input = data;
  e.prescale = prescale;
  e.postscale = postscale;
  e.root_rank = root_rank;
  if (splits != nullptr && nsplits > 0) {
    e.splits.assign(splits, splits + nsplits);
  }
  Status st;
  long long h = static_cast<Core*>(core)->Enqueue(std::move(e), &st);
  FillErr(st, err, errlen);
  return st.ok() ? h : -1;
}

// Dedicated entry points for the first-class reduce-scatter / allgather
// collectives (docs/collectives.md "Reduce-scatter & allgather") — thin
// delegates over hvdtpu_enqueue so ctypes callers get a stable narrow
// signature and a probe-able symbol (basics.py hasattr-gates on these).
long long hvdtpu_enqueue_reducescatter(void* core, const char* name,
                                       int reduce_op, int dtype,
                                       const long long* shape, int ndim,
                                       const void* data, double prescale,
                                       double postscale, char* err,
                                       int errlen) {
  return hvdtpu_enqueue(core, name,
                        static_cast<int>(hvdtpu::OpType::REDUCESCATTER),
                        reduce_op, dtype, shape, ndim, data, prescale,
                        postscale, 0, nullptr, 0, err, errlen);
}

long long hvdtpu_enqueue_allgather(void* core, const char* name, int dtype,
                                   const long long* shape, int ndim,
                                   const void* data, char* err, int errlen) {
  return hvdtpu_enqueue(core, name,
                        static_cast<int>(hvdtpu::OpType::ALLGATHER),
                        static_cast<int>(hvdtpu::ReduceOp::SUM), dtype, shape,
                        ndim, data, 1.0, 1.0, 0, nullptr, 0, err, errlen);
}

// Broadcast / alltoall entry points (docs/collectives.md "Broadcast &
// alltoall") — same thin-delegate pattern. Broadcast: data is the input on
// the root and ignored elsewhere (shape must still agree; the result buffer
// is what every rank reads back). Alltoall: splits is the caller's dim-0
// send-split row, one entry per rank; nullptr means even 1/n splits.
long long hvdtpu_enqueue_broadcast(void* core, const char* name, int dtype,
                                   const long long* shape, int ndim,
                                   const void* data, int root_rank, char* err,
                                   int errlen) {
  return hvdtpu_enqueue(core, name,
                        static_cast<int>(hvdtpu::OpType::BROADCAST),
                        static_cast<int>(hvdtpu::ReduceOp::SUM), dtype, shape,
                        ndim, data, 1.0, 1.0, root_rank, nullptr, 0, err,
                        errlen);
}

long long hvdtpu_enqueue_alltoall(void* core, const char* name, int dtype,
                                  const long long* shape, int ndim,
                                  const void* data, const int* splits,
                                  int nsplits, char* err, int errlen) {
  return hvdtpu_enqueue(core, name,
                        static_cast<int>(hvdtpu::OpType::ALLTOALL),
                        static_cast<int>(hvdtpu::ReduceOp::SUM), dtype, shape,
                        ndim, data, 1.0, 1.0, 0, splits, nsplits, err,
                        errlen);
}

// Grouped-collective window (docs/collectives.md "Grouped enqueue"):
// between begin/end, Enqueue() parks requests without letting the
// background cycle drain them, so the whole group rides one READY /
// RESPONSES round (and, for same-op/dtype lists, one fused execution).
void hvdtpu_group_begin(void* core) {
  static_cast<Core*>(core)->GroupBegin();
}

void hvdtpu_group_end(void* core) { static_cast<Core*>(core)->GroupEnd(); }

int hvdtpu_wait(void* core, long long handle, char* err, int errlen) {
  Status st = static_cast<Core*>(core)->WaitHandle(handle);
  FillErr(st, err, errlen);
  return st.ok() ? 0 : -1;
}

int hvdtpu_poll(void* core, long long handle) {
  return static_cast<Core*>(core)->PollHandle(handle);
}

long long hvdtpu_result_bytes(void* core, long long handle) {
  return static_cast<Core*>(core)->ResultBytes(handle);
}

int hvdtpu_copy_result(void* core, long long handle, void* dst,
                       long long capacity, char* err, int errlen) {
  Status st = static_cast<Core*>(core)->CopyResult(handle, dst, capacity);
  FillErr(st, err, errlen);
  return st.ok() ? 0 : -1;
}

long long hvdtpu_join(void* core) {
  return static_cast<Core*>(core)->Join();
}

// Pre-Start() configuration (reference: env knobs parsed at init,
// operations.cc:456-532 — here Python parses env and pushes values down).
int hvdtpu_set_cache_capacity(void* core, long long capacity) {
  static_cast<Core*>(core)->mutable_config()->cache_capacity = capacity;
  return 0;
}

int hvdtpu_hmac_hex(const char* key, const char* msg, char* out,
                    int outlen) {
  // Exposed for tests and the Python side's proof checks.
  std::string hex = hvdtpu::HmacSha256Hex(key ? key : "", msg ? msg : "");
  if (outlen < static_cast<int>(hex.size()) + 1) return -1;
  std::memcpy(out, hex.c_str(), hex.size() + 1);
  return 0;
}

int hvdtpu_set_secret(void* core, const char* secret) {
  static_cast<Core*>(core)->mutable_config()->secret = secret ? secret : "";
  return 0;
}

// Allreduce algorithm selection (data_plane.h AllreduceAlgo: 0 auto, 1 ring,
// 2 recursive_doubling, 3 tree, 4 scatter_allgather, 5 parameter_server).
// crossover_bytes tunes the AUTO ring/latency switchover, segment_bytes the
// ring pipeline granularity; values <= 0 keep the defaults (and AUTO's
// crossover stays under autotune ownership).
int hvdtpu_set_allreduce_tuning(void* core, int algo,
                                long long crossover_bytes,
                                long long segment_bytes) {
  if (algo < 0 || algo > 5) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->allreduce_algo = algo;
  cfg->allreduce_crossover = crossover_bytes;
  cfg->allreduce_segment = segment_bytes;
  return 0;
}

// Scale-out knobs (docs/collectives.md "Scaling out"). sa_group: group-size
// floor at which AUTO's big-message dispatch prefers scatter-allgather over
// the ring (HVDTPU_ALLREDUCE_SA_GROUP; < 0 keeps the default, 0 removes it
// from the AUTO menu). ctrl_batch: nonzero coalesces each background
// cycle's control-plane frames into one vectored send per peer
// (HVDTPU_CTRL_BATCH). Pre-Start() only.
int hvdtpu_set_scale_tuning(void* core, long long sa_group, int ctrl_batch) {
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->allreduce_sa_group = sa_group;
  cfg->ctrl_batch = ctrl_batch;
  return 0;
}

// Broadcast schedule floor (docs/collectives.md "Broadcast & alltoall"):
// payloads at or under flat_max_bytes use the flat root-sends-to-all
// schedule (one hop of latency); larger ones take the binomial tree
// (ceil(log2 n) depth, n-1 total sends either way). < 0 keeps the
// default (HVDTPU_BCAST_FLAT_MAX). Pre-Start() only.
int hvdtpu_set_bcast_tuning(void* core, long long flat_max_bytes) {
  static_cast<Core*>(core)->mutable_config()->bcast_flat_max =
      flat_max_bytes;
  return 0;
}

// Transport subsystem knobs (data_plane.h): shm_enabled toggles the POSIX
// shared-memory lanes for same-host pairs (on by default), ring_bytes sizes
// each per-direction ring (<= 0 keeps the default), hier_mode selects the
// hierarchical two-level allreduce (0 off, 1 on, 2 auto/autotuned).
int hvdtpu_set_transport(void* core, int shm_enabled,
                         long long shm_ring_bytes, int hier_mode) {
  if (hier_mode < 0 || hier_mode > 2) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->shm_enabled = shm_enabled;
  cfg->shm_ring_bytes = shm_ring_bytes;
  cfg->allreduce_hier = hier_mode;
  return 0;
}

// Zero-copy transport lane knobs (docs/collectives.md "Zero-copy TCP
// lane"): tcp_zerocopy = transport.h ZeroCopyMode (0 auto, 1 on, 2 off,
// 3 uring — the lane is runtime-probed either way and falls back to the
// copy path); shm_numa = shm_transport.h ShmNumaMode (0 auto, 1 on,
// 2 off); doorbell_batch = futex-doorbell coalescing window in bytes
// (<= 0 keeps the lane default, 1 restores wake-per-advance). Pre-Start()
// only: the TCP lanes probe at Connect, the shm lanes take their policy at
// negotiation.
int hvdtpu_set_transport_ext(void* core, int tcp_zerocopy, int shm_numa,
                             long long doorbell_batch) {
  if (tcp_zerocopy < 0 || tcp_zerocopy > 3) return -1;
  if (shm_numa < 0 || shm_numa > 2) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->tcp_zerocopy = tcp_zerocopy;
  cfg->shm_numa = shm_numa;
  cfg->doorbell_batch = doorbell_batch;
  return 0;
}

int hvdtpu_set_stall_shutdown(void* core, double secs) {
  static_cast<Core*>(core)->mutable_config()->stall_shutdown_secs = secs;
  return 0;
}

// Failure-detection knobs (docs/fault-tolerance.md). detect_ms bounds how
// long peer death can go unnoticed on a blocked transport op (poll slice =
// detect_ms/5, clamped); read_deadline_secs declares an open-but-silent
// lane dead after that long with zero progress (0 disables — the only way
// to catch a hung-but-alive peer or a blackholed route); formup_secs
// bounds rendezvous + data-plane mesh establishment. Values <= 0 keep the
// defaults (except read_deadline_secs, where 0 disables). Pre-Start() only.
int hvdtpu_set_failure_detection(void* core, long long detect_ms,
                                 double read_deadline_secs,
                                 double formup_secs) {
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  if (detect_ms > 0) cfg->failure_detect_ms = detect_ms;
  if (read_deadline_secs >= 0) cfg->read_deadline_secs = read_deadline_secs;
  if (formup_secs > 0) cfg->formup_timeout_secs = formup_secs;
  return 0;
}

// Arm one fault injection (HVDTPU_CHAOS -> horovod_tpu/chaos.py; the spec
// grammar lives in Python, the native side sees resolved integers). action:
// 0 none, 1 kill, 2 hang, 3 delay, 4 drop, 5 corrupt (flip one byte of the
// triggering op's post-allreduce output — the seeded SDC the divergence
// probe must catch). Fires once, at the op_index-th allreduce this rank
// starts or the hop_index-th pairwise exchange it runs (1-based; 0 = not
// gated on that counter). Pre-Start() only.
int hvdtpu_set_chaos(void* core, int action, long long op_index,
                     long long hop_index, long long delay_ms, int peer) {
  if (action < 0 || action > 5) return -1;
  if (action != 0 && op_index <= 0 && hop_index <= 0) return -1;
  hvdtpu::ChaosSpec spec;
  spec.action = static_cast<hvdtpu::ChaosSpec::Action>(action);
  spec.op_index = op_index;
  spec.hop_index = hop_index;
  spec.delay_ms = delay_ms;
  spec.peer = peer;
  static_cast<Core*>(core)->mutable_config()->chaos = spec;
  return 0;
}

// Elastic recovery accounting: the Python runtime measures failure
// detection -> successful re-init and records it against the NEW core
// (hvdtpu_recovery_seconds + hvdtpu_failures_detected_total), so a
// post-recovery hvd.metrics() shows the whole episode. Post-Start() only
// (the registry handles resolve in Start).
int hvdtpu_observe_recovery(void* core, double secs) {
  if (secs < 0) return -1;
  static_cast<Core*>(core)->ObserveRecovery(secs);
  return 0;
}

// Wire compression for the native data plane (compressed.h): mode 0 none,
// 1 fp16, 2 int8, 3 int4, 4 auto (autotuner-owned categorical). min_bytes
// is the small-tensor bypass (< 0 keeps the default); skip_regex a
// case-insensitive regex over tensor names that keeps matching ops dense
// (empty/null = no skip list). Pre-Start() only.
int hvdtpu_set_compression(void* core, int mode, long long min_bytes,
                           const char* skip_regex) {
  if (mode < 0 || mode > 4) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->wire_compression = mode;
  if (min_bytes >= 0) cfg->compression_min_bytes = min_bytes;
  cfg->compression_skip_regex = skip_regex ? skip_regex : "";
  return 0;
}

// ZeRO-1 memory attestation (docs/optimizer.md "Sharded optimizer state"):
// the Python sharded optimizer reports its resident optimizer-state bytes
// so /metrics can prove the 1/world footprint. Callable from any thread at
// any point in the core lifecycle.
int hvdtpu_set_optimizer_state_bytes(void* core, long long bytes) {
  static_cast<Core*>(core)->SetOptimizerStateBytes(bytes);
  return 0;
}

// Cumulative bytes-on-wire accounting for this rank's allreduce payloads
// (reduce-scatter and allgather feed the same counters — their raw/wire
// accounting shares the allreduce series so the equal-wire-bytes claim is
// checkable from one pair of numbers):
// raw = what the data plane would have sent uncompressed, wire = what it
// actually sent (equal when compression is off). Thin shim over the metrics
// registry's hvdtpu_allreduce_{raw,wire}_bytes_total counters — the single
// source of truth also served by hvdtpu_metrics_dump / the /metrics
// endpoint. The per-op values ride the timeline (docs/timeline.md
// raw_bytes/wire_bytes).
void hvdtpu_wire_stats(void* core, long long* raw_bytes,
                       long long* wire_bytes) {
  int64_t raw = 0, wire = 0;
  static_cast<Core*>(core)->WireStats(&raw, &wire);
  if (raw_bytes != nullptr) *raw_bytes = raw;
  if (wire_bytes != nullptr) *wire_bytes = wire;
}

// Live-metrics dump (metrics.h): renders every registered series in
// Prometheus text exposition format 0.0.4. Copies up to `buflen` bytes into
// `buf` (NUL-terminated when there is room) and returns the FULL rendered
// length — callers probe with (NULL, 0), allocate, and call again, looping
// if the registry grew in between. Callable from any thread.
long long hvdtpu_metrics_dump(void* core, char* buf, long long buflen) {
  std::string text = static_cast<Core*>(core)->MetricsDump();
  if (buf != nullptr && buflen > 0) {
    long long n = std::min<long long>(buflen, text.size());
    std::memcpy(buf, text.data(), static_cast<size_t>(n));
    if (n < buflen) buf[n] = '\0';
  }
  return static_cast<long long>(text.size());
}

// Always-on flight recorder (flightrec.h; docs/fault-tolerance.md
// "Post-mortem debugging"). hvdtpu_set_flightrec: pre-Start() config —
// `events` is the ring capacity in records (0 disables; < 0 keeps the
// default 4096), `dump_dir` the directory for the automatic
// flightrec.<rank>.bin dumps on abort cascade / stall escalation / fatal
// signals (NULL or empty = in-memory only; snapshots still work).
int hvdtpu_set_flightrec(void* core, long long events,
                         const char* dump_dir) {
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  if (events >= 0) cfg->flightrec_events = events;
  cfg->flightrec_dir = dump_dir != nullptr ? dump_dir : "";
  return 0;
}

// On-demand dump to `path` (NULL/empty = the configured
// <dump_dir>/flightrec.<rank>.bin). Returns 0 on success, -1 when the
// recorder is disabled or no destination is known. Callable any thread.
int hvdtpu_flightrec_dump(void* core, const char* path) {
  return static_cast<Core*>(core)->FlightDumpToFile(path) ? 0 : -1;
}

// Always-on perf attribution (perfstats.h; docs/observability.md).
// hvdtpu_set_perfstats: pre-Start() config — enabled toggles the streaming
// baselines (default on), slowdown_pct is the sentry threshold in percent
// over the rolling baseline (<= 0 keeps baselines but disables the sentry;
// < 0 keeps the default 50), min_samples the per-key warmup before the
// sentry may fire (<= 0 keeps the default 20), profile_path where Shutdown
// writes perf_profile.<rank>.json for scripts/perf_diff.py (NULL/empty =
// skip).
int hvdtpu_set_perfstats(void* core, int enabled, double slowdown_pct,
                         long long min_samples, const char* profile_path) {
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->perfstats = enabled != 0;
  if (slowdown_pct >= 0) cfg->perf_slowdown_pct = slowdown_pct;
  if (min_samples > 0) cfg->perf_min_samples = min_samples;
  cfg->perf_profile_path = profile_path != nullptr ? profile_path : "";
  return 0;
}

// Always-available sampling profiler (profiler.h; docs/profiling.md).
// hvdtpu_set_profiler: pre-Start() config — enabled toggles the subsystem
// (default on; off compiles every entry point down to one branch), hz the
// SIGPROF rate (<= 0 keeps the default 97; clamped to 1000), capacity the
// sample-ring size (<= 0 keeps the default 16384), clock 0 = per-thread
// CPU time (flamegraph contract), 1 = wall (blocked time sampled too),
// folded_path where Shutdown writes prof.<rank>.folded (NULL/empty = skip;
// non-empty also starts a whole-job window at Start — hvdrun --profile).
int hvdtpu_set_profiler(void* core, int enabled, int hz,
                        long long capacity, int clock_mode,
                        const char* folded_path) {
  if (clock_mode < 0 || clock_mode > 1) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->prof = enabled != 0;
  cfg->prof_hz = hz;
  cfg->prof_capacity = capacity;
  cfg->prof_clock = clock_mode;
  cfg->prof_path = folded_path != nullptr ? folded_path : "";
  return 0;
}

// Runtime sampling-window control (the /profz endpoint and hvd.profile()
// ride these). Start clears the ring and arms every registered thread's
// timer; both are idempotent no-ops when the profiler is disabled.
// Callable from any thread.
int hvdtpu_profiler_start(void* core) {
  static_cast<Core*>(core)->ProfilerStart();
  return 0;
}

int hvdtpu_profiler_stop(void* core) {
  static_cast<Core*>(core)->ProfilerStop();
  return 0;
}

int hvdtpu_profiler_running(void* core) {
  return static_cast<Core*>(core)->ProfilerRunning() ? 1 : 0;
}

// Folded-stacks JSON snapshot (horovod_tpu/profiler.py decodes it — the
// /profz payload and hvd.profile()'s return). Same probe-then-copy
// contract as hvdtpu_metrics_dump. Callable from any thread, live.
long long hvdtpu_profiler_snapshot(void* core, char* buf, long long buflen) {
  std::string img = static_cast<Core*>(core)->ProfilerSnapshot();
  if (buf != nullptr && buflen > 0) {
    long long n = std::min<long long>(buflen, img.size());
    std::memcpy(buf, img.data(), static_cast<size_t>(n));
    if (n < buflen) buf[n] = '\0';
  }
  return static_cast<long long>(img.size());
}

// Numerical-health observability (gradstats.h; docs/numerics.md).
// hvdtpu_set_gradstats: pre-Start() config — enabled toggles the whole
// subsystem (default on; off compiles every entry point down to one
// branch), nancheck is the NanPolicy code (0 off, 1 warn, 2 abort; < 0
// keeps the default warn), gradcheck_sample the divergence probe's
// every-Nth-op rate (0 disables the probe; < 0 keeps the default 64;
// must be uniform across ranks), profile_path where Shutdown writes
// grad_profile.<rank>.json for scripts/grad_diff.py (NULL/empty = skip).
int hvdtpu_set_gradstats(void* core, int enabled, int nancheck,
                         long long gradcheck_sample,
                         const char* profile_path) {
  if (nancheck > 2) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->gradstats = enabled != 0;
  if (nancheck >= 0) cfg->nancheck = nancheck;
  if (gradcheck_sample >= 0) cfg->gradcheck_sample = gradcheck_sample;
  cfg->grad_profile_path = profile_path != nullptr ? profile_path : "";
  return 0;
}

// Keyed numerical-health snapshot as JSON (horovod_tpu/gradstats.py
// decodes it — hvd.grad_report() and the /gradz endpoint's data source).
// Same probe-then-copy contract as hvdtpu_metrics_dump. Callable any
// thread.
long long hvdtpu_gradstats_snapshot(void* core, char* buf, long long buflen) {
  std::string img = static_cast<Core*>(core)->GradSnapshot();
  if (buf != nullptr && buflen > 0) {
    long long n = std::min<long long>(buflen, img.size());
    std::memcpy(buf, img.data(), static_cast<size_t>(n));
    if (n < buflen) buf[n] = '\0';
  }
  return static_cast<long long>(img.size());
}

// Keyed-baseline snapshot as JSON (horovod_tpu/perfstats.py decodes it —
// hvd.perf_report() and the /perfz endpoint's data source). Same
// probe-then-copy contract as hvdtpu_metrics_dump. Callable any thread.
long long hvdtpu_perfstats_snapshot(void* core, char* buf, long long buflen) {
  std::string img = static_cast<Core*>(core)->PerfSnapshot();
  if (buf != nullptr && buflen > 0) {
    long long n = std::min<long long>(buflen, img.size());
    std::memcpy(buf, img.data(), static_cast<size_t>(n));
    if (n < buflen) buf[n] = '\0';
  }
  return static_cast<long long>(img.size());
}

// Serialized dump image (binary; horovod_tpu/flightrec.py decodes it —
// the /debugz endpoint's data source). Same probe-then-copy contract as
// hvdtpu_metrics_dump: copies up to `buflen` bytes and returns the FULL
// image size (0 = recorder disabled). Callable from any thread.
long long hvdtpu_flightrec_snapshot(void* core, char* buf, long long buflen) {
  std::string img = static_cast<Core*>(core)->FlightSnapshot();
  if (buf != nullptr && buflen > 0) {
    long long n = std::min<long long>(buflen, img.size());
    std::memcpy(buf, img.data(), static_cast<size_t>(n));
  }
  return static_cast<long long>(img.size());
}

// Standalone quantizer entry points (no core instance needed): the
// cross-implementation parity tests pin these against the JAX-level
// MaxMinQuantizer (compression/quantize.py) — same bucket-512 (min, unit)
// encoding, same codes. `residual` (nullable, count floats) applies and
// updates error feedback exactly like the data plane's compressed hops.
long long hvdtpu_wire_compressed_bytes(int mode, long long count) {
  if (mode < 0 || mode > 4 || count < 0) return -1;
  return hvdtpu::WireBytes(static_cast<hvdtpu::WireCompression>(mode), count);
}

int hvdtpu_wire_compress(int mode, const float* src, long long count,
                         unsigned char* dst, float* residual) {
  if (mode <= 0 || mode > 3 || count < 0) return -1;
  hvdtpu::WireCompress(static_cast<hvdtpu::WireCompression>(mode), src, count,
                       dst, residual, nullptr);
  return 0;
}

int hvdtpu_wire_decompress(int mode, const unsigned char* src,
                           long long count, float* dst) {
  if (mode <= 0 || mode > 3 || count < 0) return -1;
  hvdtpu::WireDecompress(static_cast<hvdtpu::WireCompression>(mode), src,
                         count, dst);
  return 0;
}

int hvdtpu_set_autotune(void* core, int enabled, const char* log_path,
                        int warmup_samples, int cycles_per_sample,
                        int max_samples, double gp_noise) {
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->autotune = enabled != 0;
  cfg->autotune_log = log_path ? log_path : "";
  if (warmup_samples > 0) cfg->autotune_warmup_samples = warmup_samples;
  if (cycles_per_sample > 0) cfg->autotune_cycles_per_sample = cycles_per_sample;
  if (max_samples > 0) cfg->autotune_max_samples = max_samples;
  if (gp_noise > 0) cfg->autotune_gp_noise = gp_noise;
  return 0;
}

// Runtime timeline control (reference: horovod_start_timeline /
// horovod_stop_timeline, operations.cc:735-790).
void hvdtpu_start_timeline(void* core, const char* path, int mark_cycles) {
  static_cast<Core*>(core)->RequestTimeline(true, path ? path : "",
                                            mark_cycles != 0);
}

void hvdtpu_stop_timeline(void* core) {
  static_cast<Core*>(core)->RequestTimeline(false, "", false);
}

// Distributed tracing (docs/tracing.md). hvdtpu_set_trace: pre-Start()
// span-sampling config — sample_every = emit per-hop child spans for every
// Nth collective op (0 disables; op-level phases always ride a running
// timeline); clock_sync_interval_secs > 0 overrides the control-plane
// clock-refresh period (default 30 s). hvdtpu_start_trace: runtime
// start_timeline variant that also (re)targets the sampler (sample_every
// < 0 keeps the configured rate). hvdtpu_clock_offset: this rank's steady
// clock offset ± error vs rank 0 in microseconds (err < 0 = never synced);
// callable from any thread.
int hvdtpu_set_trace(void* core, long long sample_every,
                     double clock_sync_interval_secs) {
  if (sample_every < 0) return -1;
  hvdtpu::CoreConfig* cfg = static_cast<Core*>(core)->mutable_config();
  cfg->trace_sample = sample_every;
  if (clock_sync_interval_secs > 0) {
    cfg->clock_sync_interval_secs = clock_sync_interval_secs;
  }
  return 0;
}

void hvdtpu_start_trace(void* core, const char* path, int mark_cycles,
                        long long sample_every) {
  static_cast<Core*>(core)->RequestTimeline(true, path ? path : "",
                                            mark_cycles != 0, sample_every);
}

void hvdtpu_clock_offset(void* core, long long* offset_us,
                         long long* err_us) {
  int64_t off = 0, err = -1;
  static_cast<Core*>(core)->ClockOffset(&off, &err);
  if (offset_us != nullptr) *offset_us = off;
  if (err_us != nullptr) *err_us = err;
}

double hvdtpu_cycle_time_ms(void* core) {
  return static_cast<Core*>(core)->CurrentCycleTimeMs();
}

long long hvdtpu_fusion_threshold(void* core) {
  return static_cast<Core*>(core)->CurrentFusionThreshold();
}

}  // extern "C"
