// Wire compression for the process-mode data plane.
//
// Reference: the IST-DASLab fork's horovod/common/ops/compressed/ subsystem —
// CPUMaxMinQuantizer (compressor.h:168): bucket-wise uniform max-min
// quantization of fp32 payloads to b bits with per-bucket (min, unit)
// headers, plus error-feedback residuals accumulated at the compressing rank
// (compressor.cc ApplyErrorFeedback). This rebuild keeps the reference wire
// semantics byte-compatible with the JAX-level quantizer
// (horovod_tpu/compression/quantize.py MaxMinQuantizer, bucket 512): same
// bucket size, same (min, unit) encoding, same round-to-nearest-even codes —
// tests/test_wire_compression.py pins the two implementations to each other.
//
// The data plane (data_plane.cpp) uses these kernels for "compressed hops":
// each ring / recursive-doubling exchange ships the quantized form, the
// receiver dequantizes and reduces in fp32, and the next hop re-quantizes
// (with the residual applied wherever data is quantized).
//
// Wire layout for a block of `count` fp32 elements:
//   FP16: count x uint16 IEEE half codes (round-to-nearest-even, the PR-1
//         F16C/RNE kernels).
//   INT8 / INT4: ceil(count/512) per-bucket headers (fp32 min, fp32 unit,
//         8 bytes each), then the codes — 1 byte per element (int8) or two
//         elements per byte, low nibble first (int4, matching
//         quantize.py pack_bits bit order). A short tail bucket is treated
//         as zero-padded to 512 for the min/max scan (quantize.py
//         _bucketize parity), but padding codes are never stored.
#pragma once

#include <cstdint>
#include <string>
#include <unordered_map>
#include <vector>

#include "gradstats.h"  // GradQuality (quantization-quality accumulation)

namespace hvdtpu {

// Matches the Python surface (envvars.WIRE_COMPRESSION_MODES) and the
// hvdtpu_set_compression C API. AUTO is a config-only value: the Bayesian
// autotuner owns the effective choice (none/fp16/int8) and broadcasts it via
// PARAMS; the data plane only ever sees a concrete mode.
enum class WireCompression : int32_t {
  NONE = 0,
  FP16 = 1,
  INT8 = 2,
  INT4 = 3,
  AUTO = 4,
};

// Matches compression/quantize.py DEFAULT_BUCKET_SIZE (reference:
// compressor.h:11).
constexpr int64_t kWireBucketSize = 512;

const char* WireCompressionName(WireCompression c);

// Bytes on the wire for `count` fp32 elements under mode `c` (NONE/AUTO:
// the raw 4 * count).
int64_t WireBytes(WireCompression c, int64_t count);

// Compress `count` fp32 elements into `dst` (WireBytes(c, count) bytes).
//
// residual (optional, `count` floats): error feedback — each element is
// quantized as x = src[i] + residual[i] and the new residual is x minus its
// dequantized value (reference: error feedback accumulated at the
// compressing rank).
//
// self_decode (optional, `count` floats, MAY alias src): receives the
// dequantized values, so a rank can replace its own copy with exactly what
// peers will decode — cross-rank bitwise consistency for the compressed
// collectives.
//
// quality (optional): accumulates sum (x - dequantized)^2 and sum x^2 over
// every quantized element (docs/numerics.md) — the kernels already compute
// the dequantized value for error feedback, so the accumulation costs two
// FMAs per lane, not an extra pass.
//
// c must be a concrete mode (not NONE/AUTO).
void WireCompress(WireCompression c, const float* src, int64_t count,
                  uint8_t* dst, float* residual, float* self_decode,
                  GradQuality* quality = nullptr);

// dst[i] = decoded[i].
void WireDecompress(WireCompression c, const uint8_t* src, int64_t count,
                    float* dst);

// dst[i] += decoded[i] (the fused decompress-and-reduce used by the
// compressed reduce-scatter hops; fp32 accumulation).
void WireDecompressAdd(WireCompression c, const uint8_t* src, int64_t count,
                       float* dst);

// Per-tensor error-feedback residual buffers, keyed by the (fused) op's
// name signature. Local to the compressing rank; nothing is negotiated.
// Concurrency contract: background-loop-owned (error feedback is applied
// inside the serialized collective path), so it carries no lock — the same
// single-driver rule as DataPlane, enforced socially and by `make analyze`
// finding any new mutex-free cross-thread state it would take to break it.
class ResidualStore {
 public:
  // The residual buffer for `key`, zero-initialized when new or when the
  // element count changed (a refused fusion or reshape drops the residual —
  // best-effort, like the reference's per-entry feedback buffers).
  // Bounded: fusion composition is timing-dependent (and the autotuner
  // varies the threshold), so distinct keys can proliferate — past
  // kMaxEntries the store resets rather than leak a full-size fp32 buffer
  // per stale signature (EF restarts from zero; it is best-effort state).
  // *reset (optional) is set true when EXISTING feedback state was dropped
  // — a live key resized (refused fusion / reshape) or the whole store
  // cleared at the cap — so the caller can count and WARN
  // (hvdtpu_residual_resets_total; docs/numerics.md): silently restarting
  // error feedback mid-run is a quality event, not bookkeeping.
  float* Get(const std::string& key, int64_t count, bool* reset = nullptr);
  size_t size() const { return buf_.size(); }
  // Total bytes held across every residual buffer — the memory-occupancy
  // telemetry's hvdtpu_residual_store_bytes gauge (refreshed at 1 Hz by
  // the background loop; docs/metrics.md documents the staleness window).
  // O(entries), entries are capped at kMaxEntries; background thread only,
  // like Get.
  int64_t TotalBytes() const {
    int64_t total = 0;
    for (const auto& kv : buf_) {
      total += static_cast<int64_t>(kv.second.size() * sizeof(float));
    }
    return total;
  }

  static constexpr size_t kMaxEntries = 256;

 private:
  std::unordered_map<std::string, std::vector<float>> buf_;
};

}  // namespace hvdtpu
