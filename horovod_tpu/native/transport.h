// Transport abstraction for the host data plane.
//
// The collective algorithms in data_plane.cpp speak to every peer through
// this interface; the concrete lane is chosen per pair at Connect() time:
// TcpTransport (the PR-1 socket path, loopback or cross-host, with an
// optional zero-copy send engine) or ShmTransport (shm_transport.h — POSIX
// shared-memory rings for ranks that share a host). This is the seam later
// transports (TPU ICI-aware, RDMA) plug into: implement the five methods and
// register a lane in DataPlane::Connect. Fills the role of the reference
// fork's communicator menu (horovod/common/ops/compressed/: MPI / NCCL /
// CUDA-IPC SHM / P2P).
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>

#include "socket_util.h"
#include "thread_roles.h"

namespace hvdtpu {

// In-order, disjoint completion callback for segmented receives:
// (data, offset, length). `data` points at the segment's payload bytes and
// is valid only for the duration of the call; landing transports pass
// recv_buf + offset, while zero-copy transports (the shm rings) pass views
// into their own storage and may skip writing recv_buf entirely — callers
// that pass a callback must treat recv_buf as scratch and consume the
// payload through the views. Offsets are monotonic and disjoint; lengths
// are multiples of the caller's view alignment (see view_align below), but
// otherwise transport-chosen (segment-sized for TCP, ring-run-sized for
// shm). Runs on the caller's thread.
using SegmentFn = std::function<void(const uint8_t*, size_t, size_t)>;

// TCP zero-copy send mode (HVDTPU_TCP_ZEROCOPY; mirrored by
// envvars.TCP_ZEROCOPY_MODES — scripts/check_invariants.py ENUM-MIRROR).
// AUTO probes SO_ZEROCOPY at Connect and backs off to the copy path when
// the kernel reports it copied anyway (loopback, unsupported NICs); ON
// keeps the lane armed wherever the probe succeeds; OFF never probes;
// URING probes an io_uring submission ring first and falls back down the
// same ladder (docs/collectives.md "Zero-copy TCP lane" has the full probe
// order).
enum class ZeroCopyMode : int32_t {
  AUTO = 0,
  ON = 1,
  OFF = 2,
  URING = 3,
};

// Per-fd zero-copy send engine (MSG_ZEROCOPY + errqueue completion reaping,
// optional io_uring submission lane). Owned by a TcpTransport; single-driver
// like its owner — only the thread running the send may call SendAll.
//
// Correctness contract: SendAll returns only after every queued byte's
// zero-copy completion has been reaped from the socket error queue, so the
// caller may immediately reuse the buffer (the collectives re-fill send
// buffers every hop). Completion waits are folded into IoControl-style poll
// slices: a plane abort, peer death, or the no-progress deadline breaks a
// blocked drain within one slice, exactly like the copy path.
class ZeroCopySender {
 public:
  ~ZeroCopySender();

  // Probe and arm the lane (Connect-time, before any traffic). Probe order:
  // URING -> io_uring ring with IORING_OP_SEND (falls through to
  // MSG_ZEROCOPY when io_uring_setup is unavailable — seccomp'd containers,
  // old kernels); AUTO/ON -> setsockopt(SO_ZEROCOPY) (EOPNOTSUPP/ENOPROTOOPT
  // leaves the engine disabled: AF_UNIX pairs, pre-4.14 kernels). OFF never
  // probes. Idempotent.
  HVDTPU_CALLED_ON(background)
  void Init(int fd, ZeroCopyMode mode);

  // Lane armed (post-probe, not auto-disabled)?
  HVDTPU_CALLED_ON(any)
  bool enabled() const { return lane_ != Lane::NONE; }
  // Engage for this payload? Small sends stay on the copy path: page
  // pinning + completion reaping cost more than one memcpy below this.
  HVDTPU_CALLED_ON(background)
  bool ShouldUse(size_t len) const {
    return lane_ != Lane::NONE && len >= kMinBytes;
  }

  // Exact-length zero-copy send. 0 = success (all completions drained),
  // -1 = error/abort (errno set), +1 = lane declined before any byte moved
  // (runtime EOPNOTSUPP) — the caller must fall back to the copy path and
  // the engine disables itself. AUTO mode also self-disables after the
  // first drain whose completions all carry SO_EE_CODE_ZEROCOPY_COPIED
  // (the kernel copied anyway — loopback): later sends take the copy path.
  HVDTPU_CALLED_ON(background)
  int SendAll(const void* buf, size_t len, IoControl* ctl);

  // Completed zero-copy sends / sends-that-fell-back since Init, for the
  // data plane's hvdtpu_zerocopy_{sends,fallbacks}_total counters.
  HVDTPU_CALLED_ON(any)
  int64_t sends() const { return sends_; }
  HVDTPU_CALLED_ON(any)
  int64_t kernel_copied_events() const { return copied_notifs_; }

  static constexpr size_t kMinBytes = 128 * 1024;

 private:
  enum class Lane { NONE, MSG_ZC, URING };

  // Reap whatever completions sit in the error queue right now (never
  // blocks). -1 on a genuine socket error.
  int ReapCompletions();
  // Block (in ctl slices) until every issued send's completion arrived.
  int DrainCompletions(IoControl* ctl);
  int UringSubmitSend(const void* buf, size_t len, IoControl* ctl);
  void UringClose();

  Lane lane_ = Lane::NONE;
  ZeroCopyMode mode_ = ZeroCopyMode::OFF;
  int fd_ = -1;
  bool probed_ = false;
  // MSG_ZEROCOPY accounting: one notification per successful zc send call.
  int64_t issued_ = 0;
  int64_t completed_ = 0;
  int64_t copied_notifs_ = 0;  // completions flagged "kernel copied anyway"
  int64_t sends_ = 0;
  // io_uring state (raw syscalls; no liburing dependency).
  int ring_fd_ = -1;
  void* sq_mem_ = nullptr;
  void* cq_mem_ = nullptr;
  void* sqe_mem_ = nullptr;
  size_t sq_mem_bytes_ = 0;
  size_t cq_mem_bytes_ = 0;
  size_t sqe_mem_bytes_ = 0;
  struct UringLayout;
  UringLayout* uring_ = nullptr;
};

class Transport {
 public:
  virtual ~Transport() = default;

  // Lane tag for the timeline / introspection ("tcp", "tcp-zc", "shm", ...).
  HVDTPU_CALLED_ON(any)
  virtual const char* kind() const = 0;

  // Exact-length transfers; 0 on success, -1 on error or abort.
  // (Vectored scatter-gather sends are a socket-level facility — SendAllVec
  // in socket_util.h, used by the control plane's SendFrame — not a lane
  // method: every collective payload is a single contiguous region, so a
  // per-lane Sendv would be interface weight with no caller.)
  HVDTPU_CALLED_ON(background)
  virtual int Send(const void* buf, size_t len) = 0;
  HVDTPU_CALLED_ON(background)
  virtual int Recv(void* buf, size_t len) = 0;

  // Receive with segment callbacks so per-segment work (reduction) overlaps
  // the transfer. A null on_segment degrades to Recv; with a callback, the
  // payload is delivered through the callback views (see SegmentFn) and
  // `buf` is scratch a zero-copy lane may skip. view_align: every view
  // length/offset is a multiple of this (the caller's element size), so
  // in-place reducers never see a torn element.
  HVDTPU_CALLED_ON(background)
  virtual int RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                            size_t view_align, const SegmentFn& on_segment) = 0;

  // Full-duplex exchange with the SAME peer (both sides may send first
  // without deadlock) plus optional segment callbacks on the receive side
  // (same view semantics as RecvSegmented).
  HVDTPU_CALLED_ON(background)
  virtual int SendRecv(const void* send_buf, size_t send_bytes,
                       void* recv_buf, size_t recv_bytes,
                       size_t segment_bytes, size_t view_align,
                       const SegmentFn& on_segment) = 0;

  // True when Send(bytes) completes without any peer progress (the payload
  // fits the transport's own buffering): callers may send inline before a
  // blocking receive with no deadlock risk, skipping the sender thread that
  // dominates small-message latency.
  HVDTPU_CALLED_ON(background)
  virtual bool InlineSendSafe(size_t bytes) const = 0;

  // Break any blocked op on this lane (world abort / peer failure). The TCP
  // lane needs nothing here — DataPlane::Abort shuts the socket down and the
  // sliced reads observe the shared IoControl; the shm lane overrides to
  // flip its cross-process abort flag and wake futex waiters.
  HVDTPU_CALLED_ON(any)
  virtual void Abort() {}

  // Bytes currently buffered inside the lane's own storage (the shm rings'
  // head-tail spread; 0 for lanes that buffer in the kernel) — the memory-
  // occupancy telemetry's per-lane gauge (docs/profiling.md). Any thread;
  // weakly consistent like the metrics it feeds.
  HVDTPU_CALLED_ON(any)
  virtual int64_t OccupancyBytes() const { return 0; }
};

// The PR-1 socket path behind the interface. Does NOT own the fd (the
// DataPlane's mesh teardown closes it). With a non-null `ctl` every
// blocking read/write is interruptible: sliced polls observe the plane
// abort flag, peer death fails within one slice, and a silent-but-open
// socket trips the no-progress deadline (docs/fault-tolerance.md).
// zc_mode arms the zero-copy send engine (probed in the constructor; large
// sends ride MSG_ZEROCOPY / io_uring, small ones and failed probes the
// plain copy path — ZeroCopySender above).
class TcpTransport : public Transport {
 public:
  TcpTransport(int fd, int64_t inline_max_bytes, IoControl* ctl = nullptr,
               ZeroCopyMode zc_mode = ZeroCopyMode::OFF)
      : fd_(fd), inline_max_(inline_max_bytes), ctl_(ctl), zc_mode_(zc_mode) {
    zc_.Init(fd, zc_mode);
  }

  HVDTPU_CALLED_ON(any)
  const char* kind() const override {
    return zc_.enabled() ? "tcp-zc" : "tcp";
  }
  HVDTPU_CALLED_ON(background)
  int Send(const void* buf, size_t len) override;
  HVDTPU_CALLED_ON(background)
  int Recv(void* buf, size_t len) override;
  HVDTPU_CALLED_ON(background)
  int RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                    size_t view_align, const SegmentFn& on_segment) override;
  HVDTPU_CALLED_ON(background)
  int SendRecv(const void* send_buf, size_t send_bytes, void* recv_buf,
               size_t recv_bytes, size_t segment_bytes, size_t view_align,
               const SegmentFn& on_segment) override;
  HVDTPU_CALLED_ON(background)
  bool InlineSendSafe(size_t bytes) const override {
    return static_cast<int64_t>(bytes) <= inline_max_;
  }

  // Zero-copy introspection/accounting (the data plane scrapes these into
  // the metrics registry after each op; background thread only).
  HVDTPU_CALLED_ON(any)
  bool zerocopy_enabled() const { return zc_.enabled(); }
  HVDTPU_CALLED_ON(any)
  int64_t zerocopy_sends() const { return zc_.sends(); }
  HVDTPU_CALLED_ON(any)
  int64_t zerocopy_fallbacks() const { return zc_fallbacks_; }

 private:
  int fd_;
  int64_t inline_max_;
  IoControl* ctl_;  // nullable; shared with the owning DataPlane
  ZeroCopyMode zc_mode_;
  ZeroCopySender zc_;
  // Large sends that wanted the zero-copy lane but took the copy path
  // (failed probe, runtime decline, kernel-copies auto-disable).
  int64_t zc_fallbacks_ = 0;
};

}  // namespace hvdtpu
