// Transport abstraction for the host data plane.
//
// The collective algorithms in data_plane.cpp speak to every peer through
// this interface; the concrete lane is chosen per pair at Connect() time:
// TcpTransport (the PR-1 socket path, loopback or cross-host) or
// ShmTransport (shm_transport.h — POSIX shared-memory rings for ranks that
// share a host). This is the seam later transports (TPU ICI-aware, RDMA)
// plug into: implement the five methods and register a lane in
// DataPlane::Connect. Fills the role of the reference fork's communicator
// menu (horovod/common/ops/compressed/: MPI / NCCL / CUDA-IPC SHM / P2P).
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>

#include "socket_util.h"

namespace hvdtpu {

// In-order, disjoint completion callback for segmented receives:
// (offset, length) with offsets at multiples of the segment size and only
// the final segment short. Runs on the caller's thread.
using SegmentFn = std::function<void(size_t, size_t)>;

class Transport {
 public:
  virtual ~Transport() = default;

  // Lane tag for the timeline / introspection ("tcp", "shm", ...).
  virtual const char* kind() const = 0;

  // Exact-length transfers; 0 on success, -1 on error or abort.
  virtual int Send(const void* buf, size_t len) = 0;
  virtual int Recv(void* buf, size_t len) = 0;

  // Receive with segment callbacks so per-segment work (reduction) overlaps
  // the transfer. A null on_segment degrades to Recv.
  virtual int RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                            const SegmentFn& on_segment) = 0;

  // Full-duplex exchange with the SAME peer (both sides may send first
  // without deadlock) plus optional segment callbacks on the receive side.
  virtual int SendRecv(const void* send_buf, size_t send_bytes,
                       void* recv_buf, size_t recv_bytes,
                       size_t segment_bytes, const SegmentFn& on_segment) = 0;

  // True when Send(bytes) completes without any peer progress (the payload
  // fits the transport's own buffering): callers may send inline before a
  // blocking receive with no deadlock risk, skipping the sender thread that
  // dominates small-message latency.
  virtual bool InlineSendSafe(size_t bytes) const = 0;

  // Break any blocked op on this lane (world abort / peer failure). The TCP
  // lane needs nothing here — DataPlane::Abort shuts the socket down and the
  // sliced reads observe the shared IoControl; the shm lane overrides to
  // flip its cross-process abort flag and wake futex waiters.
  virtual void Abort() {}
};

// The PR-1 socket path behind the interface. Does NOT own the fd (the
// DataPlane's mesh teardown closes it). With a non-null `ctl` every
// blocking read/write is interruptible: sliced polls observe the plane
// abort flag, peer death fails within one slice, and a silent-but-open
// socket trips the no-progress deadline (docs/fault-tolerance.md).
class TcpTransport : public Transport {
 public:
  TcpTransport(int fd, int64_t inline_max_bytes, IoControl* ctl = nullptr)
      : fd_(fd), inline_max_(inline_max_bytes), ctl_(ctl) {}

  const char* kind() const override { return "tcp"; }
  int Send(const void* buf, size_t len) override;
  int Recv(void* buf, size_t len) override;
  int RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                    const SegmentFn& on_segment) override;
  int SendRecv(const void* send_buf, size_t send_bytes, void* recv_buf,
               size_t recv_bytes, size_t segment_bytes,
               const SegmentFn& on_segment) override;
  bool InlineSendSafe(size_t bytes) const override {
    return static_cast<int64_t>(bytes) <= inline_max_;
  }

 private:
  int fd_;
  int64_t inline_max_;
  IoControl* ctl_;  // nullable; shared with the owning DataPlane
};

}  // namespace hvdtpu
