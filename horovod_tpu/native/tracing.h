// Cross-rank distributed tracing support (docs/tracing.md).
//
// Two pure components shared by the core and the data plane:
//
//  * TraceSampler — "every Nth op" gate for the per-hop span firehose
//    (HVDTPU_TRACE_SAMPLE). Op-level phases (NEGOTIATE / QUEUE / the op
//    activity) always ride the timeline; the per-hop SEND/RECV/REDUCE/
//    QUANTIZE child spans are emitted only for sampled ops so the hot path
//    stays at the PR-4 ≈0% overhead budget.
//
//  * Clock-offset estimation — per-pair offset between this rank's
//    steady clock and rank 0's, from ping-pong samples piggybacked on the
//    form-up handshake (CtrlMsg::CLOCK in core.cpp) and refreshed
//    periodically through the control plane. The classic NTP-style
//    estimator: for the sample with the smallest round trip,
//    offset = t2 - (t1 + t3) / 2, with |error| bounded by half the round
//    trip (the reply can sit anywhere inside it). The offset ± error is
//    recorded into each rank's trace metadata so scripts/trace_analyze.py
//    can merge per-rank traces onto one global time axis.
//
// No reference analog: horovod/common/timeline.cc is strictly per-rank and
// leaves cross-rank correlation to the reader's eyeballs.
#pragma once

#include <cstdint>
#include <vector>

#include "thread_roles.h"

namespace hvdtpu {

// One ping-pong: t1 = local steady us at send, t2 = peer steady us at its
// reply, t3 = local steady us at receipt. All absolute microseconds.
struct ClockSample {
  int64_t t1 = 0;
  int64_t t2 = 0;
  int64_t t3 = 0;
};

// offset_us: peer_steady - local_steady (add to local timestamps to land on
// the peer's axis). err_us: half the best sample's round trip + 1 us of
// clock granularity — the bound recorded into the trace metadata.
struct ClockEstimate {
  int64_t offset_us = 0;
  int64_t err_us = 0;
  bool valid = false;
};

// Min-RTT estimator over `samples` (invalid samples — t3 < t1 — are
// skipped). Returns valid=false when nothing usable was measured.
ClockEstimate EstimateClockOffset(const std::vector<ClockSample>& samples);

// Every-Nth-op sampling gate. every_n <= 0 disables (SampleOp always
// false); every_n == 1 samples every op. The FIRST op is always sampled
// when enabled, so short jobs still produce hop spans. Single-driver like
// the DataPlane that owns it.
class TraceSampler {
 public:
  HVDTPU_CALLED_ON(background)
  void set_every_n(int64_t n) { every_n_ = n; }
  HVDTPU_CALLED_ON(background)
  int64_t every_n() const { return every_n_; }
  HVDTPU_CALLED_ON(background)
  bool enabled() const { return every_n_ > 0; }

  HVDTPU_CALLED_ON(background)
  bool SampleOp() {
    if (every_n_ <= 0) return false;
    return ops_++ % every_n_ == 0;
  }

 private:
  int64_t every_n_ = 0;
  int64_t ops_ = 0;
};

}  // namespace hvdtpu
