// Live-observability metrics registry: counters, gauges, and fixed-bucket
// histograms with Prometheus text exposition.
//
// The reference ships no live-metrics surface at all — its only runtime
// visibility is the post-hoc Chrome-trace timeline (horovod/common/timeline.cc)
// plus log lines. This registry is the rebuild's pull-based replacement: the
// background loop and data plane instrument themselves through it, the C API
// (hvdtpu_metrics_dump) renders the text exposition format, and the Python
// layer serves it over a per-worker /metrics HTTP endpoint
// (horovod_tpu/observability.py) that hvdrun's driver aggregator scrapes.
//
// Concurrency model: metric HANDLES (Counter*/Gauge*/Histogram*) are resolved
// once through the registry (mutex-guarded map insert, cold path) and then
// updated lock-free — plain atomic adds for counters, atomic stores for
// gauges, per-bucket atomic adds + a CAS loop on the double sum for
// histograms. Dump() walks the maps under the registry mutex; readers only
// ever see torn *sets* of metrics (e.g. a count updated before its sum),
// never torn values — the same weak-consistency contract Prometheus client
// libraries give. Handles stay valid for the registry's lifetime (metrics are
// never deleted).
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Sorted label set rendered as {k="v",...}. A std::map keeps the rendering
// (and therefore Dump()) deterministic regardless of insertion order.
using MetricLabels = std::map<std::string, std::string>;

class Counter {
 public:
  void Add(int64_t d) { v_.fetch_add(d, std::memory_order_relaxed); }
  void Inc() { Add(1); }
  int64_t Get() const { return v_.load(std::memory_order_relaxed); }

 private:
  std::atomic<int64_t> v_{0};  // atomic: relaxed-counter
};

class Gauge {
 public:
  void Set(double v) { v_.store(v, std::memory_order_relaxed); }
  double Get() const { return v_.load(std::memory_order_relaxed); }

 private:
  std::atomic<double> v_{0.0};  // atomic: relaxed-counter
};

// Fixed-bucket histogram. Bounds are the upper edges of the non-infinite
// buckets (ascending); an implicit +Inf bucket catches the rest. Bucket
// counts are stored per-bucket (not cumulative) and rendered cumulative at
// dump time, Prometheus-style.
class Histogram {
 public:
  explicit Histogram(std::vector<double> bounds)
      : bounds_(std::move(bounds)),
        buckets_(new std::atomic<int64_t>[bounds_.size() + 1]) {
    for (size_t i = 0; i <= bounds_.size(); ++i)
      buckets_[i].store(0, std::memory_order_relaxed);
  }

  void Observe(double v) {
    size_t i = 0;
    while (i < bounds_.size() && v > bounds_[i]) ++i;
    buckets_[i].fetch_add(1, std::memory_order_relaxed);
    // Atomic double accumulation via CAS on the bit pattern (fetch_add on
    // atomic<double> is C++20; this must build as C++17).
    double cur = sum_.load(std::memory_order_relaxed);
    while (!sum_.compare_exchange_weak(cur, cur + v,
                                       std::memory_order_relaxed)) {
    }
  }

  const std::vector<double>& bounds() const { return bounds_; }
  int64_t BucketCount(size_t i) const {
    return buckets_[i].load(std::memory_order_relaxed);
  }
  int64_t Count() const {
    int64_t n = 0;
    for (size_t i = 0; i <= bounds_.size(); ++i) n += BucketCount(i);
    return n;
  }
  double Sum() const { return sum_.load(std::memory_order_relaxed); }

 private:
  std::vector<double> bounds_;
  std::unique_ptr<std::atomic<int64_t>[]> buckets_;  // atomic: relaxed-counter
  std::atomic<double> sum_{0.0};  // atomic: relaxed-counter
};

// Canonical bucket menus for the instrumented subsystems (exponential;
// seconds ones start at poll()'s 1 ms floor territory).
std::vector<double> LatencyBuckets();  // 100us .. ~100s, x4
std::vector<double> BytesBuckets();    // 256B .. 1GB, x4

class Metrics {
 public:
  // Resolve-or-create. `help` is recorded on first creation; the returned
  // handle is stable for the registry's lifetime. Type mismatches on an
  // existing name abort in debug builds and return a fresh unnamed metric
  // otherwise (a programming error, not a runtime condition).
  Counter* GetCounter(const std::string& name, const std::string& help,
                      const MetricLabels& labels = {}) EXCLUDES(mu_);
  Gauge* GetGauge(const std::string& name, const std::string& help,
                  const MetricLabels& labels = {}) EXCLUDES(mu_);
  Histogram* GetHistogram(const std::string& name, const std::string& help,
                          const std::vector<double>& bounds,
                          const MetricLabels& labels = {}) EXCLUDES(mu_);

  // Prometheus text exposition format, version 0.0.4: # HELP / # TYPE lines
  // followed by one sample line per series (histograms expand into
  // cumulative _bucket{le=...} + _sum + _count). Deterministic: families
  // sorted by name, series by label string.
  std::string Dump() const EXCLUDES(mu_);

  // Number of distinct (name, labels) series — bounds cardinality in tests.
  size_t SeriesCount() const EXCLUDES(mu_);

 private:
  enum class Kind { COUNTER, GAUGE, HISTOGRAM };
  struct Series {
    std::unique_ptr<Counter> counter;
    std::unique_ptr<Gauge> gauge;
    std::unique_ptr<Histogram> histogram;
  };
  struct Family {
    Kind kind = Kind::COUNTER;
    std::string help;
    std::map<std::string, Series> series;  // key: rendered label string
  };

  Family* Resolve(const std::string& name, const std::string& help,
                  Kind kind) REQUIRES(mu_);

  mutable Mutex mu_;
  std::map<std::string, Family> families_ GUARDED_BY(mu_);
};

// {k="v",k2="v2"} (empty string for no labels). Values are escaped per the
// exposition format (backslash, double-quote, newline).
std::string RenderLabels(const MetricLabels& labels);

}  // namespace hvdtpu
