// Minimal TCP plumbing: listeners, retrying connects, length-prefixed frames.
//
// Fills the role of the reference's gloo TCP device + HTTPStore rendezvous
// (horovod/common/gloo/) with plain POSIX sockets — the control plane and the
// loopback/CPU data plane both ride these.
#pragma once

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace hvdtpu {

// All functions return >= 0 on success, -1 on error (errno preserved).

// Create a listening socket bound to 0.0.0.0:port (port 0 = ephemeral).
// On success stores the actual port in *out_port.
int TcpListen(int port, int backlog, int* out_port);

// Accept one connection (blocking). Returns connected fd.
int TcpAccept(int listen_fd);

// Connect to host:port, retrying for up to timeout_ms (covers peer startup
// races during rendezvous). Returns connected fd.
int TcpConnectRetry(const std::string& host, int port, int timeout_ms);

// Exact-length send/recv (loop over partial transfers). 0 on success.
int SendAll(int fd, const void* buf, size_t len);
int RecvAll(int fd, void* buf, size_t len);

// Full-duplex segmented transfer: streams send_bytes out of send_fd while
// receiving recv_bytes into recv_buf, invoking on_segment(offset, length) on
// the CALLING thread as each received segment lands — later segments keep
// streaming in a background thread, so per-segment work (e.g. reduction)
// overlaps the wire time. Offsets/lengths are multiples of segment_bytes
// except the final segment. segment_bytes == 0 means one segment; a null
// on_segment degrades to a plain concurrent send+recv. 0 on success.
int SendRecvSegmented(int send_fd, const void* send_buf, size_t send_bytes,
                      int recv_fd, void* recv_buf, size_t recv_bytes,
                      size_t segment_bytes,
                      const std::function<void(size_t, size_t)>& on_segment);

// Length-prefixed frame: [u64 length][payload].
int SendFrame(int fd, const std::vector<uint8_t>& payload);
int RecvFrame(int fd, std::vector<uint8_t>* payload);

// True if the fd has readable data (poll with timeout_ms; 0 = nonblocking).
bool Readable(int fd, int timeout_ms);

void CloseFd(int fd);

}  // namespace hvdtpu
