// Minimal TCP plumbing: listeners, retrying connects, length-prefixed frames.
//
// Fills the role of the reference's gloo TCP device + HTTPStore rendezvous
// (horovod/common/gloo/) with plain POSIX sockets — the control plane and the
// loopback/CPU data plane both ride these.
#pragma once

#include <sys/uio.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace hvdtpu {

// Monotonic clock as seconds (progress/deadline bookkeeping across the
// transports and the data plane).
inline double MonoSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Shared fault-detection control block for one data plane's transports
// (docs/fault-tolerance.md). Every blocking transport read/write that gets a
// pointer to one becomes interruptible: it polls in `detect_slice_ms` slices
// so a plane-wide abort is observed within one slice, fails fast on peer
// death (EOF/RST/POLLHUP), and — when `read_deadline_secs` > 0 — declares a
// peer dead after that long with zero progress (the transport-level stall
// escalation; a hung-but-alive rank produces no EOF). The flags are relaxed
// atomics any thread may read; writers use MarkPeerFailed/store-release.
// The plain-int tuning fields are written before Connect only.
struct IoControl {
  std::atomic<uint32_t> aborted{0};      // plane-wide: fail every lane op  // atomic: acquire-read
  std::atomic<uint32_t> peer_failed{0};  // a lane observed peer death  // atomic: release-publish
  int64_t detect_slice_ms = 100;         // poll slice (abort latency bound)
  double read_deadline_secs = 0;         // 0 = no no-progress deadline
  // Cumulative peer-wait time: microseconds every controlled op spent
  // blocked for the peer (sliced polls on an empty/full socket, futex waits
  // on the shm rings, zero-copy completion drains) rather than moving
  // bytes. The distributed-tracing layer snapshots it around each hop to
  // split hop time into wait vs wire (docs/tracing.md straggler
  // attribution). Relaxed adds on the already-slow blocked path: free on
  // the hot path.
  std::atomic<int64_t> wait_us{0};  // atomic: relaxed-counter

  bool is_aborted() const {
    return aborted.load(std::memory_order_acquire) != 0;
  }
  void MarkPeerFailed() {
    peer_failed.store(1, std::memory_order_release);
    aborted.store(1, std::memory_order_release);
  }
  void AddWaitUs(int64_t us) {
    if (us > 0) wait_us.fetch_add(us, std::memory_order_relaxed);
  }
  int64_t WaitUs() const {
    return wait_us.load(std::memory_order_relaxed);
  }
};

// Poll-slice length in ms for a controlled blocking op: the control's
// detect_slice_ms clamped to [1, 1000] (100 with no control block). One
// clamp policy for every sliced wait — SendAll/RecvAll here, the
// zero-copy completion drains in transport.cpp.
int IoSliceMs(const IoControl* ctl);

// All functions return >= 0 on success, -1 on error (errno preserved).

// Create a listening socket bound to 0.0.0.0:port (port 0 = ephemeral).
// On success stores the actual port in *out_port.
int TcpListen(int port, int backlog, int* out_port);

// Accept one connection (blocking). Returns connected fd.
int TcpAccept(int listen_fd);

// Accept with a deadline: -1 with errno ETIMEDOUT when no connection lands
// within timeout_ms (bounds world form-up so a vanished peer cannot wedge
// rendezvous forever; docs/fault-tolerance.md).
int TcpAcceptTimeout(int listen_fd, int timeout_ms);

// Connect to host:port, retrying for up to timeout_ms (covers peer startup
// races during rendezvous). Returns connected fd.
int TcpConnectRetry(const std::string& host, int port, int timeout_ms);

// Exact-length send/recv (loop over partial transfers). 0 on success.
// With a non-null `ctl` the loop becomes interruptible (see IoControl): the
// hot path still issues one recv/send syscall per chunk (MSG_DONTWAIT), and
// only an empty/full socket buffer drops to a sliced poll that watches the
// abort flag, peer death, and the no-progress deadline.
int SendAll(int fd, const void* buf, size_t len, IoControl* ctl = nullptr);
int RecvAll(int fd, void* buf, size_t len, IoControl* ctl = nullptr);

// Vectored exact-length send (sendmsg scatter-gather): every byte of every
// iovec is shipped, partial transfers advance the (caller-owned, mutated)
// iovec array in place — one syscall per kernel-buffer-ful instead of one
// per iovec, so header+payload pairs (length-prefixed frames, quantized
// header+codes) leave without a staging copy or a second syscall. Same
// IoControl semantics as SendAll. 0 on success.
int SendAllVec(int fd, struct iovec* iov, int iovcnt,
               IoControl* ctl = nullptr);

// Full-duplex segmented transfer: streams send_bytes out of send_fd while
// receiving recv_bytes into recv_buf, invoking on_segment(data, offset,
// length) on the CALLING thread as each received segment lands (data ==
// recv_buf + offset here; the shm transport's zero-copy override passes
// in-ring views instead) — later segments keep streaming in a background
// thread, so per-segment work (e.g. reduction) overlaps the wire time.
// Offsets/lengths are multiples of segment_bytes except the final segment.
// segment_bytes == 0 means one segment; a null on_segment degrades to a
// plain concurrent send+recv. 0 on success.
int SendRecvSegmented(
    int send_fd, const void* send_buf, size_t send_bytes, int recv_fd,
    void* recv_buf, size_t recv_bytes, size_t segment_bytes,
    const std::function<void(const uint8_t*, size_t, size_t)>& on_segment,
    IoControl* ctl = nullptr);

// Length-prefixed frame: [u64 length][payload].
int SendFrame(int fd, const std::vector<uint8_t>& payload);
int RecvFrame(int fd, std::vector<uint8_t>* payload);

// True if the fd has readable data (poll with timeout_ms; 0 = nonblocking).
bool Readable(int fd, int timeout_ms);

void CloseFd(int fd);

}  // namespace hvdtpu
