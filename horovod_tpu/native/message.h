// Control-plane wire format: Request / Response (+ serialization).
//
// Fills the role of the reference's flatbuffers wire format
// (horovod/common/wire/message.fbs, horovod/common/message.{h,cc}) with a
// hand-rolled little-endian binary encoding — the only consumers are this
// runtime's own ranks, so schema evolution machinery is unnecessary.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// A rank's announcement that a named tensor is ready
// (reference: Request, message.h:48).
struct Request {
  int32_t rank = 0;
  OpType op_type = OpType::ALLREDUCE;
  ReduceOp reduce_op = ReduceOp::SUM;
  DataType dtype = DataType::FLOAT32;
  std::string name;
  std::vector<int64_t> shape;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t root_rank = 0;
  std::vector<int32_t> splits;  // alltoall send splits (empty = even)
};

enum class ResponseType : int32_t {
  OK = 0,
  ERROR = 1,
  JOIN_DONE = 2,
  SHUTDOWN = 3,
};

// Coordinator's instruction to execute (possibly fused) collectives
// (reference: Response, message.h:145; fusion in controller.cc:686).
struct Response {
  ResponseType type = ResponseType::OK;
  OpType op_type = OpType::ALLREDUCE;
  ReduceOp reduce_op = ReduceOp::SUM;
  DataType dtype = DataType::FLOAT32;
  std::string error_message;
  // One entry per fused tensor. Shapes are the *coordinator-agreed* shapes so
  // joined ranks can materialize zero tensors (reference: tensor_queue.cc
  // GetTensorEntriesFromResponse).
  std::vector<std::string> names;
  std::vector<std::vector<int64_t>> shapes;
  std::vector<double> prescales;
  std::vector<double> postscales;
  int32_t root_rank = 0;
  // Alltoall: per-rank send splits for every rank (size * size entries,
  // [sender * size + receiver]), negotiated by the coordinator
  // (reference: controller AlltoallGetRecvSplits).
  std::vector<int32_t> all_splits;
  // Allgather: per-rank first-dimension sizes (reference: controller.cc:812).
  std::vector<int64_t> first_dims;
  int32_t last_joined_rank = -1;  // JOIN_DONE
};

// ---- serialization -------------------------------------------------------

class Writer {
 public:
  void I32(int32_t v) { Raw(&v, sizeof(v)); }
  void I64(int64_t v) { Raw(&v, sizeof(v)); }
  void F64(double v) { Raw(&v, sizeof(v)); }
  void Str(const std::string& s) {
    I64(static_cast<int64_t>(s.size()));
    Raw(s.data(), s.size());
  }
  void VecI64(const std::vector<int64_t>& v) {
    I64(static_cast<int64_t>(v.size()));
    Raw(v.data(), v.size() * sizeof(int64_t));
  }
  void VecI32(const std::vector<int32_t>& v) {
    I64(static_cast<int64_t>(v.size()));
    Raw(v.data(), v.size() * sizeof(int32_t));
  }
  void VecF64(const std::vector<double>& v) {
    I64(static_cast<int64_t>(v.size()));
    Raw(v.data(), v.size() * sizeof(double));
  }
  const std::vector<uint8_t>& buffer() const { return buf_; }
  std::vector<uint8_t> Take() { return std::move(buf_); }

 private:
  void Raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  std::vector<uint8_t> buf_;
};

class Reader {
 public:
  explicit Reader(const std::vector<uint8_t>& buf) : buf_(buf) {}
  int32_t I32() { int32_t v = 0; Raw(&v, sizeof(v)); return v; }
  int64_t I64() { int64_t v = 0; Raw(&v, sizeof(v)); return v; }
  double F64() { double v = 0; Raw(&v, sizeof(v)); return v; }
  std::string Str() {
    int64_t n = Len(1);
    std::string s(static_cast<size_t>(n), '\0');
    Raw(s.data(), static_cast<size_t>(n));
    return s;
  }
  std::vector<int64_t> VecI64() {
    int64_t n = Len(sizeof(int64_t));
    std::vector<int64_t> v(static_cast<size_t>(n));
    Raw(v.data(), v.size() * sizeof(int64_t));
    return v;
  }
  std::vector<int32_t> VecI32() {
    int64_t n = Len(sizeof(int32_t));
    std::vector<int32_t> v(static_cast<size_t>(n));
    Raw(v.data(), v.size() * sizeof(int32_t));
    return v;
  }
  std::vector<double> VecF64() {
    int64_t n = Len(sizeof(double));
    std::vector<double> v(static_cast<size_t>(n));
    Raw(v.data(), v.size() * sizeof(double));
    return v;
  }
  bool ok() const { return ok_; }
  size_t pos() const { return pos_; }
  size_t size() const { return buf_.size(); }

 private:
  // A length prefix can never exceed the bytes remaining in the frame; a
  // bigger value means the frame is corrupt — flag it instead of letting a
  // garbage allocation size throw std::length_error.
  int64_t Len(size_t elem_size) {
    int64_t n = I64();
    if (n < 0 ||
        static_cast<size_t>(n) > (buf_.size() - pos_) / elem_size) {
      ok_ = false;
      return 0;
    }
    return n;
  }
  void Raw(void* p, size_t n) {
    if (pos_ + n > buf_.size()) { ok_ = false; return; }
    if (n > 0)  // memcpy with null dst is UB even for n == 0
      memcpy(p, buf_.data() + pos_, n);
    pos_ += n;
  }
  const std::vector<uint8_t>& buf_;
  size_t pos_ = 0;
  bool ok_ = true;
};

void SerializeRequest(const Request& r, Writer* w);
Request DeserializeRequest(Reader* r);
void SerializeResponse(const Response& r, Writer* w);
Response DeserializeResponse(Reader* r);

}  // namespace hvdtpu
