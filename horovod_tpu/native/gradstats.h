// Numerical-health observability: in-band gradient + compression-quality
// telemetry (docs/numerics.md).
//
// PRs 4-11 built a complete *systems* observability stack (metrics, traces,
// flight recorder, perf attribution, profiler); this subsystem is the first
// one that watches the MODEL rather than the machine. Three signals, all
// fed from existing data-plane touch points at near-zero extra cost:
//
//  * Gradient moments — L2 norm, absmax, NaN/Inf counts — computed in the
//    SAME pass as the fusion copy-in (CopyMomentsF32 fuses the scan into
//    the copy; AppendCopyMomentsF32 cache-blocks it against a vector
//    append, so the extra read comes from L2, not DRAM), streamed into
//    per-tensor EWMA baselines.
//  * Quantization quality — MSE and SNR of every compressed hop vs the
//    pre-quantized values, accumulated INSIDE the quantize kernels
//    (compressed.cpp already computes the dequantized value for error
//    feedback; the accumulation is two FMAs per lane), plus the
//    error-feedback residual norm — EQuARX (arxiv 2506.17615) shows
//    quantized-allreduce quality must be measured per-layer to be tuned
//    safely, and residual blowup is visible here before the loss diverges.
//  * Cross-rank divergence — every HVDTPU_GRADCHECK_SAMPLE-th op each rank
//    fingerprints its post-allreduce output (Crc32c below) and reports it
//    to rank 0 through a piggybacked control-plane frame; any mismatch is
//    silent data corruption or non-determinism (upstream Horovod, arxiv
//    1802.05799, ASSUMES bitwise-identical outputs and never verifies).
//
// On top of the moments sits the non-finite sentinel: the first NaN/Inf
// gradient emits a NONFINITE flight-recorder event naming tensor + rank,
// bumps hvdtpu_nonfinite_grads_total, and under HVDTPU_NANCHECK=abort
// fail-fasts the job with the tensor named in the post-mortem verdict.
//
// Surfaces: hvdtpu_gradstats_snapshot C API -> hvd.grad_report() / the
// /gradz endpoint (decoded by horovod_tpu/gradstats.py), per-rank
// grad_profile.<rank>.json at shutdown for scripts/grad_diff.py, NAN/DIV
// flags + worst-SNR readout in `hvdrun --top`.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "thread_roles.h"

namespace hvdtpu {

enum class WireCompression : int32_t;  // compressed.h

// HVDTPU_NANCHECK policy. Mirrored in horovod_tpu/gradstats.py
// NAN_POLICIES (scripts/check_invariants.py ENUM-MIRROR).
enum class NanPolicy : int32_t {
  OFF = 0,    // moments still stream; non-finite values are not flagged
  WARN = 1,   // flight event + counter + WARN, op proceeds (default)
  ABORT = 2,  // fail-fast: the op errors, the world breaks, forensics dump
};

// Numerical-health event kinds (the /gradz event log's `kind` codes and
// the grad-profile event records). Mirrored in horovod_tpu/gradstats.py
// GRAD_EVENTS (scripts/check_invariants.py ENUM-MIRROR).
enum class GradEvent : int32_t {
  NONFINITE = 0,       // NaN/Inf gradient elements seen at fusion copy-in
  DIVERGENCE = 1,      // cross-rank fingerprint mismatch (SDC sentinel)
  RESIDUAL_RESET = 2,  // error-feedback residual dropped (reshape/overflow)
};

const char* NanPolicyName(NanPolicy p);

// CRC32C (Castagnoli), the fingerprint the divergence probe compares
// across ranks: hardware SSE4.2 CRC32 instruction when the CPU has it
// (~20 GB/s), software slice-by-8 otherwise. seed lets callers chain.
uint32_t Crc32c(const void* data, size_t len, uint32_t seed = 0);

// One-pass moments of an fp32 gradient buffer. sumsq/absmax accumulate
// FINITE lanes only (one NaN must not erase the norm of the other 16M
// elements); NaN and Inf lanes are counted instead.
struct GradMoments {
  double sumsq = 0;
  double absmax = 0;
  int64_t nonfinite = 0;  // NaN + Inf elements
  int64_t count = 0;

  HVDTPU_CALLED_ON(background)
  void Merge(const GradMoments& o) {
    sumsq += o.sumsq;
    if (o.absmax > absmax) absmax = o.absmax;
    nonfinite += o.nonfinite;
    count += o.count;
  }
};

// Scan `count` floats into *m (AVX2 when available; += semantics so callers
// can accumulate across blocks).
void MomentsF32(const float* src, int64_t count, GradMoments* m);
// Fused copy + scan: dst[i] = src[i] while accumulating moments — the
// scan rides the load the copy already does, with REGULAR stores at
// every size (a streaming-store variant was rejected by the paired A/B:
// the collective re-reads this buffer right after the copy-in, and NT
// stores cost 13-25% of the op in post-copy misses; BENCH_r10.json).
void CopyMomentsF32(float* dst, const float* src, int64_t count,
                    GradMoments* m);

// Quantization-quality accumulator one compressed op carries through its
// WireCompress calls (compressed.cpp): err2 = sum (x - dequantized)^2 over
// every quantized element (x = gradient + error-feedback residual), sig2 =
// sum x^2. MSE = err2/count, SNR = 10*log10(sig2/err2). Because error
// feedback stores exactly x - dequantized back into the residual, err2 IS
// the post-op ResidualStore content for these elements: sqrt(err2) is the
// residual norm the blowup sentinel watches.
struct GradQuality {
  double err2 = 0;
  double sig2 = 0;
  int64_t count = 0;

  HVDTPU_CALLED_ON(background)
  void Reset() {
    err2 = 0;
    sig2 = 0;
    count = 0;
  }
};

// Streaming keyed-statistics sizing, same rationale as perfstats.h: keys
// past the cap share the overflow slot 0 so the hot path never allocates.
constexpr int kGradMaxKeys = 256;

// One key's numerical-health state. Same concurrency contract as PerfSlot
// (perfstats.h): writer fields behind a per-slot spinlock, published fields
// relaxed atomics any thread may read mid-update (torn SETS, never torn
// values).
struct GradSlot {
  // Writer-owned (guarded by lock).
  double ewma_norm = 0;
  double ewma_snr_db = 0;
  std::atomic_flag lock = ATOMIC_FLAG_INIT;

  // Published, lock-free readable.
  std::atomic<int64_t> count{0};  // atomic: relaxed-counter
  std::atomic<double> pub_norm{0};       // last L2 norm  // atomic: relaxed-counter
  std::atomic<double> pub_ewma_norm{0};  // EWMA of the norm  // atomic: relaxed-counter
  std::atomic<double> pub_absmax{0};     // last absmax  // atomic: relaxed-counter
  std::atomic<int64_t> nonfinite{0};     // cumulative NaN/Inf elements  // atomic: relaxed-counter
  // Quantization quality (zero q_count = never compressed: dense layer or
  // skip-regex match — the /gradz report omits SNR for these).
  std::atomic<int64_t> q_count{0};  // atomic: relaxed-counter
  std::atomic<double> pub_mse{0};  // atomic: relaxed-counter
  std::atomic<double> pub_snr_db{0};  // atomic: relaxed-counter
  std::atomic<double> pub_ewma_snr_db{0};  // atomic: relaxed-counter
  std::atomic<double> pub_res_norm{0};  // post-op EF residual norm  // atomic: relaxed-counter
  std::atomic<int32_t> comp{0};         // last WireCompression code  // atomic: relaxed-counter
  // NONFINITE WARN/flight-event throttle stamp (steady us; 0 = never).
  // Same per-key CAS window as PerfSlot::last_warn_us: a tensor that went
  // NaN floods hundreds of ops per second, and an unthrottled event per
  // op would evict the op/hop records a post-mortem needs from the
  // flight ring. The counters stay exact; only the log + ring ride this.
  std::atomic<int64_t> last_warn_us{0};  // atomic: relaxed-counter

  std::string key;  // immutable once the slot is published
};

class GradStats {
 public:
  // enabled=false turns every Record* into one branch. sample_n is the
  // divergence probe's every-Nth-op rate (0 disables the probe; moments
  // and quality still stream). Call before the background loop starts.
  HVDTPU_CALLED_ON(background)
  void Configure(bool enabled, NanPolicy policy, int64_t sample_n);
  HVDTPU_CALLED_ON(any)
  bool enabled() const { return enabled_; }
  HVDTPU_CALLED_ON(any)
  NanPolicy nan_policy() const { return policy_; }
  HVDTPU_CALLED_ON(any)
  int64_t gradcheck_sample() const { return sample_n_; }

  // Intern `key` -> slot id (>= 1; 0 = the shared overflow slot once the
  // table fills). Background (collective-driving) thread only, like
  // PerfStats::KeySlot.
  HVDTPU_CALLED_ON(background)
  int KeySlot(const std::string& key);

  // Record one tensor's copy-in moments against `slot`. Thread-safe
  // (per-slot spinlock); no allocation.
  HVDTPU_CALLED_ON(background)
  void RecordMoments(int slot, const GradMoments& m);

  // Record one compressed op's quantization quality against `slot`.
  HVDTPU_CALLED_ON(background)
  void RecordQuality(int slot, WireCompression c, const GradQuality& q);

  // Per-key throttle for the NONFINITE WARN + flight record: true at most
  // once per min_gap_us per slot (the first event of a key always
  // passes). CAS on the slot's stamp — thread-safe, one winner.
  HVDTPU_CALLED_ON(background)
  bool ShouldWarnNonfinite(int slot, int64_t now_us,
                           int64_t min_gap_us = 1000000);

  // Cumulative event counters (the snapshot's totals; the matching
  // Prometheus counters live in the core's registry).
  HVDTPU_CALLED_ON(background)
  void NoteNonfinite(int64_t elements) {
    nonfinite_total_.fetch_add(elements, std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(background)
  void NoteProbe() { probes_total_.fetch_add(1, std::memory_order_relaxed); }
  HVDTPU_CALLED_ON(background)
  void NoteDivergence() {
    divergence_total_.fetch_add(1, std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(background)
  void NoteResidualReset() {
    residual_resets_total_.fetch_add(1, std::memory_order_relaxed);
  }

  HVDTPU_CALLED_ON(any)
  int64_t nonfinite_total() const {
    return nonfinite_total_.load(std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(any)
  int64_t probes_total() const {
    return probes_total_.load(std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(any)
  int64_t divergence_total() const {
    return divergence_total_.load(std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(any)
  int64_t residual_resets_total() const {
    return residual_resets_total_.load(std::memory_order_relaxed);
  }

  // Keyed-health snapshot as JSON (the /gradz payload and the body of
  // grad_profile.<rank>.json). Readers touch atomics + immutable keys only
  // — callable from any thread while writers run.
  HVDTPU_CALLED_ON(any)
  std::string SnapshotJson() const;

  HVDTPU_CALLED_ON(any)
  int slot_count() const { return nslots_.load(std::memory_order_acquire); }
  HVDTPU_CALLED_ON(any)
  const GradSlot* slot(int i) const {  // tests/introspection
    return i >= 0 && i < slot_count() ? &slots_[i] : nullptr;
  }

 private:
  bool enabled_ = false;
  NanPolicy policy_ = NanPolicy::WARN;
  int64_t sample_n_ = 0;
  std::unique_ptr<GradSlot[]> slots_;
  std::atomic<int> nslots_{0};  // atomic: release-publish
  std::unordered_map<std::string, int> key_ids_;  // background thread only
  std::atomic<int64_t> nonfinite_total_{0};  // atomic: relaxed-counter
  std::atomic<int64_t> probes_total_{0};  // atomic: relaxed-counter
  std::atomic<int64_t> divergence_total_{0};  // atomic: relaxed-counter
  std::atomic<int64_t> residual_resets_total_{0};  // atomic: relaxed-counter
};

}  // namespace hvdtpu
