#include "compressed.h"

#include <cmath>
#include <cstring>

#include "data_plane.h"  // HalfToFloatPublic / FloatToHalfPublic (PR-1 RNE)

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

namespace hvdtpu {

namespace {

// Quality accumulation helper (docs/numerics.md): err2 += (x - deq)^2,
// sig2 += x^2, finite terms only — one NaN element (or an fp16 overflow's
// inf diff) must not erase the whole op's SNR; the non-finite elements
// themselves are the NaN sentinel's business, not the quality metric's.
inline void AccumQuality(GradQuality* q, float x, float deq) {
  const float d = x - deq;
  if (d - d == 0.0f && x - x == 0.0f) {  // both finite
    q->err2 += static_cast<double>(d) * static_cast<double>(d);
    q->sig2 += static_cast<double>(x) * static_cast<double>(x);
  }
  ++q->count;
}

#if defined(__x86_64__)
// Drain an 8-lane float product vector into a double accumulator.
__attribute__((target("avx2")))
inline void AccumPd(__m256d* acc, __m256 v) {
  *acc = _mm256_add_pd(*acc, _mm256_cvtps_pd(_mm256_castps256_ps128(v)));
  *acc = _mm256_add_pd(*acc, _mm256_cvtps_pd(_mm256_extractf128_ps(v, 1)));
}

__attribute__((target("avx2")))
inline double HorizontalSumPd(__m256d v) {
  __m128d lo = _mm256_castpd256_pd128(v);
  __m128d hi = _mm256_extractf128_pd(v, 1);
  lo = _mm_add_pd(lo, hi);
  return _mm_cvtsd_f64(lo) + _mm_cvtsd_f64(_mm_unpackhi_pd(lo, lo));
}

bool HaveF16C() {
  // gcc 10's __builtin_cpu_supports has no "f16c"; read CPUID leaf 1 ECX
  // bit 29 directly (same probe as data_plane.cpp).
  static const bool ok = [] {
    unsigned eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
    return (ecx & (1u << 29)) != 0 && __builtin_cpu_supports("avx2") != 0;
  }();
  return ok;
}

// 8-lane fp32 -> fp16 -> fp32 cast with optional residual/self-decode, on
// the F16C hardware converters (full IEEE round-to-nearest-even, identical
// to the scalar FloatToHalf path for numeric values).
__attribute__((target("avx2,f16c")))
void Fp16CompressF16C(const float* __restrict__ src, int64_t count,
                      uint16_t* __restrict__ dst, float* __restrict__ residual,
                      float* __restrict__ self_decode,
                      GradQuality* __restrict__ quality) {
  const bool want_back =
      residual != nullptr || self_decode != nullptr || quality != nullptr;
  __m256d qerr = _mm256_setzero_pd();
  __m256d qsig = _mm256_setzero_pd();
  int64_t i = 0;
  for (; i + 8 <= count; i += 8) {
    __m256 x = _mm256_loadu_ps(src + i);
    if (residual != nullptr) {
      x = _mm256_add_ps(x, _mm256_loadu_ps(residual + i));
    }
    __m128i h = _mm256_cvtps_ph(x, _MM_FROUND_TO_NEAREST_INT);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i), h);
    if (want_back) {
      __m256 back = _mm256_cvtph_ps(h);
      // Finite mask over x - back: zero where half-range overflow
      // saturated to inf or a NaN input poisons the diff.
      __m256 r = _mm256_sub_ps(x, back);
      __m256 finite = _mm256_cmp_ps(_mm256_sub_ps(r, r),
                                    _mm256_setzero_ps(), _CMP_EQ_OQ);
      __m256 rf = _mm256_and_ps(r, finite);
      if (residual != nullptr) {
        // Carrying ±inf would poison the element's error feedback
        // permanently — store the filtered diff.
        _mm256_storeu_ps(residual + i, rf);
      }
      if (self_decode != nullptr) _mm256_storeu_ps(self_decode + i, back);
      if (quality != nullptr) {
        // r finite implies x finite (back is never NaN for finite x), so
        // one mask filters both quality terms.
        __m256 xf = _mm256_and_ps(x, finite);
        AccumPd(&qerr, _mm256_mul_ps(rf, rf));
        AccumPd(&qsig, _mm256_mul_ps(xf, xf));
      }
    }
  }
  if (quality != nullptr) {
    quality->err2 += HorizontalSumPd(qerr);
    quality->sig2 += HorizontalSumPd(qsig);
    quality->count += i;
  }
  for (; i < count; ++i) {
    float x = src[i] + (residual != nullptr ? residual[i] : 0.0f);
    uint16_t h = FloatToHalfPublic(x);
    dst[i] = h;
    if (want_back) {
      float back = HalfToFloatPublic(h);
      if (residual != nullptr) {
        float r = x - back;
        residual[i] = std::isfinite(r) ? r : 0.0f;
      }
      if (self_decode != nullptr) self_decode[i] = back;
      if (quality != nullptr) AccumQuality(quality, x, back);
    }
  }
}

__attribute__((target("avx2,f16c")))
void Fp16DecompressF16C(const uint16_t* __restrict__ src, int64_t count,
                        float* __restrict__ dst, bool add) {
  int64_t i = 0;
  for (; i + 8 <= count; i += 8) {
    __m256 v = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i)));
    if (add) v = _mm256_add_ps(v, _mm256_loadu_ps(dst + i));
    _mm256_storeu_ps(dst + i, v);
  }
  for (; i < count; ++i) {
    float v = HalfToFloatPublic(src[i]);
    dst[i] = add ? dst[i] + v : v;
  }
}
#endif  // __x86_64__

void Fp16Compress(const float* src, int64_t count, uint8_t* dst,
                  float* residual, float* self_decode, GradQuality* quality) {
  uint16_t* h = reinterpret_cast<uint16_t*>(dst);
#if defined(__x86_64__)
  if (HaveF16C()) {
    Fp16CompressF16C(src, count, h, residual, self_decode, quality);
    return;
  }
#endif
  const bool want_back =
      residual != nullptr || self_decode != nullptr || quality != nullptr;
  for (int64_t i = 0; i < count; ++i) {
    float x = src[i] + (residual != nullptr ? residual[i] : 0.0f);
    h[i] = FloatToHalfPublic(x);
    if (want_back) {
      float back = HalfToFloatPublic(h[i]);
      if (residual != nullptr) {
        // Half-range overflow saturates to inf; a ±inf residual would
        // poison the element forever — drop the feedback instead.
        float r = x - back;
        residual[i] = std::isfinite(r) ? r : 0.0f;
      }
      if (self_decode != nullptr) self_decode[i] = back;
      if (quality != nullptr) AccumQuality(quality, x, back);
    }
  }
}

void Fp16Decompress(const uint8_t* src, int64_t count, float* dst, bool add) {
  const uint16_t* h = reinterpret_cast<const uint16_t*>(src);
#if defined(__x86_64__)
  if (HaveF16C()) {
    Fp16DecompressF16C(h, count, dst, add);
    return;
  }
#endif
  for (int64_t i = 0; i < count; ++i) {
    float v = HalfToFloatPublic(h[i]);
    dst[i] = add ? dst[i] + v : v;
  }
}

// --- bucket-wise max-min quantization ---------------------------------------
// Bit-compatible with compression/quantize.py MaxMinQuantizer: same bucket
// size (512), same unit = (max - min) / (2^bits - 1), same
// round-to-nearest-EVEN codes (nearbyintf under the default rounding mode,
// matching jnp.round), and the same zero-padded-tail min/max semantics
// (_bucketize pads the last bucket with zeros BEFORE the min/max scan, so a
// short tail bucket's range always includes 0).
//
// The int8 hot path has an AVX2 variant (8 lanes per step, bit-identical to
// the scalar loop: same subtract/divide/RNE-round/clamp element ops, no FMA
// contraction) — without it the quantize+dequantize passes cost more than
// the bytes they save on fast links.

inline int64_t NumBuckets(int64_t count) {
  return (count + kWireBucketSize - 1) / kWireBucketSize;
}

#if defined(__x86_64__)
bool HaveAvx2() {
  static const bool ok = __builtin_cpu_supports("avx2") != 0;
  return ok;
}

__attribute__((target("avx2")))
inline float HorizontalMin(__m256 v) {
  __m128 m = _mm_min_ps(_mm256_castps256_ps128(v),
                        _mm256_extractf128_ps(v, 1));
  m = _mm_min_ps(m, _mm_movehl_ps(m, m));
  m = _mm_min_ss(m, _mm_shuffle_ps(m, m, 1));
  return _mm_cvtss_f32(m);
}

__attribute__((target("avx2")))
inline float HorizontalMax(__m256 v) {
  __m128 m = _mm_max_ps(_mm256_castps256_ps128(v),
                        _mm256_extractf128_ps(v, 1));
  m = _mm_max_ps(m, _mm_movehl_ps(m, m));
  m = _mm_max_ss(m, _mm_shuffle_ps(m, m, 1));
  return _mm_cvtss_f32(m);
}

__attribute__((target("avx2")))
void MaxMinCompress8Avx2(const float* src, int64_t count, uint8_t* dst,
                         float* residual, float* self_decode,
                         GradQuality* quality) {
  __m256d qerr = _mm256_setzero_pd();
  __m256d qsig = _mm256_setzero_pd();
  int64_t qvec = 0;  // lanes the vector loop accumulated (tails self-count)
  const int64_t nb = NumBuckets(count);
  float* header = reinterpret_cast<float*>(dst);
  uint8_t* codes = dst + nb * 8;
  alignas(32) float xbuf[kWireBucketSize];
  for (int64_t b = 0; b < nb; ++b) {
    const int64_t lo = b * kWireBucketSize;
    const int64_t n = std::min<int64_t>(kWireBucketSize, count - lo);
    // Adjusted values (error feedback applied) staged through xbuf so the
    // stores below may alias src via self_decode.
    int64_t i = 0;
    if (residual != nullptr) {
      for (; i + 8 <= n; i += 8) {
        _mm256_store_ps(xbuf + i,
                        _mm256_add_ps(_mm256_loadu_ps(src + lo + i),
                                      _mm256_loadu_ps(residual + lo + i)));
      }
      for (; i < n; ++i) xbuf[i] = src[lo + i] + residual[lo + i];
    } else {
      for (; i + 8 <= n; i += 8) {
        _mm256_store_ps(xbuf + i, _mm256_loadu_ps(src + lo + i));
      }
      for (; i < n; ++i) xbuf[i] = src[lo + i];
    }
    float mn = xbuf[0], mx = xbuf[0];
    if (n >= 8) {
      __m256 vmn = _mm256_load_ps(xbuf), vmx = vmn;
      for (i = 8; i + 8 <= n; i += 8) {
        __m256 x = _mm256_load_ps(xbuf + i);
        vmn = _mm256_min_ps(vmn, x);
        vmx = _mm256_max_ps(vmx, x);
      }
      mn = HorizontalMin(vmn);
      mx = HorizontalMax(vmx);
    } else {
      i = 1;
    }
    for (; i < n; ++i) {
      mn = std::min(mn, xbuf[i]);
      mx = std::max(mx, xbuf[i]);
    }
    if (n < kWireBucketSize) {  // zero-padded tail (quantize.py parity)
      mn = std::min(mn, 0.0f);
      mx = std::max(mx, 0.0f);
    }
    const float unit = (mx - mn) / 255.0f;
    const float safe_unit = unit == 0.0f ? 1.0f : unit;
    header[b * 2] = mn;
    header[b * 2 + 1] = unit;
    const __m256 vmn = _mm256_set1_ps(mn);
    const __m256 vunit = _mm256_set1_ps(unit);
    const __m256 vsafe = _mm256_set1_ps(safe_unit);
    const __m256 vzero = _mm256_setzero_ps();
    const __m256 vlev = _mm256_set1_ps(255.0f);
    for (i = 0; i + 8 <= n; i += 8) {
      __m256 x = _mm256_load_ps(xbuf + i);
      __m256 q = _mm256_round_ps(
          _mm256_div_ps(_mm256_sub_ps(x, vmn), vsafe),
          _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC);
      q = _mm256_min_ps(_mm256_max_ps(q, vzero), vlev);
      __m256i i32 = _mm256_cvtps_epi32(q);
      __m128i u16 = _mm_packus_epi32(_mm256_castsi256_si128(i32),
                                     _mm256_extracti128_si256(i32, 1));
      _mm_storel_epi64(reinterpret_cast<__m128i*>(codes + lo + i),
                       _mm_packus_epi16(u16, u16));
      if (residual != nullptr || self_decode != nullptr ||
          quality != nullptr) {
        __m256 deq = _mm256_add_ps(vmn, _mm256_mul_ps(q, vunit));
        __m256 diff = _mm256_sub_ps(x, deq);
        if (residual != nullptr) {
          _mm256_storeu_ps(residual + lo + i, diff);
        }
        if (self_decode != nullptr) {
          _mm256_storeu_ps(self_decode + lo + i, deq);
        }
        if (quality != nullptr) {
          // Finite lanes only (a NaN input makes the bucket's min/unit —
          // and so diff — NaN; the sentinel owns non-finite values).
          __m256 finite = _mm256_cmp_ps(_mm256_sub_ps(diff, diff),
                                        _mm256_setzero_ps(), _CMP_EQ_OQ);
          __m256 df = _mm256_and_ps(diff, finite);
          __m256 xf = _mm256_and_ps(x, finite);
          AccumPd(&qerr, _mm256_mul_ps(df, df));
          AccumPd(&qsig, _mm256_mul_ps(xf, xf));
          qvec += 8;
        }
      }
    }
    for (; i < n; ++i) {
      float scaled = (xbuf[i] - mn) / safe_unit;
      float q = nearbyintf(scaled);
      if (q < 0.0f) q = 0.0f;
      if (q > 255.0f) q = 255.0f;
      codes[lo + i] = static_cast<uint8_t>(q);
      if (residual != nullptr || self_decode != nullptr ||
          quality != nullptr) {
        const float deq = mn + q * unit;
        if (residual != nullptr) residual[lo + i] = xbuf[i] - deq;
        if (self_decode != nullptr) self_decode[lo + i] = deq;
        if (quality != nullptr) AccumQuality(quality, xbuf[i], deq);
      }
    }
  }
  if (quality != nullptr) {
    quality->err2 += HorizontalSumPd(qerr);
    quality->sig2 += HorizontalSumPd(qsig);
    quality->count += qvec;  // scalar tails self-counted via AccumQuality
  }
}

template <bool kAdd>
__attribute__((target("avx2")))
void MaxMinDecompress8Avx2(const uint8_t* src, int64_t count, float* dst) {
  const int64_t nb = NumBuckets(count);
  const float* header = reinterpret_cast<const float*>(src);
  const uint8_t* codes = src + nb * 8;
  for (int64_t b = 0; b < nb; ++b) {
    const int64_t lo = b * kWireBucketSize;
    const int64_t n = std::min<int64_t>(kWireBucketSize, count - lo);
    const float mn = header[b * 2];
    const float unit = header[b * 2 + 1];
    const __m256 vmn = _mm256_set1_ps(mn);
    const __m256 vunit = _mm256_set1_ps(unit);
    int64_t i = 0;
    for (; i + 8 <= n; i += 8) {
      __m256i i32 = _mm256_cvtepu8_epi32(_mm_loadl_epi64(
          reinterpret_cast<const __m128i*>(codes + lo + i)));
      __m256 v =
          _mm256_add_ps(vmn, _mm256_mul_ps(_mm256_cvtepi32_ps(i32), vunit));
      if (kAdd) v = _mm256_add_ps(v, _mm256_loadu_ps(dst + lo + i));
      _mm256_storeu_ps(dst + lo + i, v);
    }
    for (; i < n; ++i) {
      const float v = mn + static_cast<float>(codes[lo + i]) * unit;
      dst[lo + i] = kAdd ? dst[lo + i] + v : v;
    }
  }
}
#endif  // __x86_64__

template <int kBits>
void MaxMinCompress(const float* src, int64_t count, uint8_t* dst,
                    float* residual, float* self_decode,
                    GradQuality* quality) {
  constexpr float kLevels = static_cast<float>((1 << kBits) - 1);
  const int64_t nb = NumBuckets(count);
  float* header = reinterpret_cast<float*>(dst);
  uint8_t* codes = dst + nb * 8;
  float xbuf[kWireBucketSize];  // adjusted values (src may alias self_decode)
  for (int64_t b = 0; b < nb; ++b) {
    const int64_t lo = b * kWireBucketSize;
    const int64_t n = std::min<int64_t>(kWireBucketSize, count - lo);
    float mn = src[lo] + (residual != nullptr ? residual[lo] : 0.0f);
    float mx = mn;
    for (int64_t i = 0; i < n; ++i) {
      float x = src[lo + i] + (residual != nullptr ? residual[lo + i] : 0.0f);
      xbuf[i] = x;
      mn = std::min(mn, x);
      mx = std::max(mx, x);
    }
    if (n < kWireBucketSize) {  // zero-padded tail (quantize.py parity)
      mn = std::min(mn, 0.0f);
      mx = std::max(mx, 0.0f);
    }
    const float unit = (mx - mn) / kLevels;
    const float safe_unit = unit == 0.0f ? 1.0f : unit;
    header[b * 2] = mn;
    header[b * 2 + 1] = unit;
    for (int64_t i = 0; i < n; ++i) {
      float scaled = (xbuf[i] - mn) / safe_unit;
      float q = nearbyintf(scaled);
      if (q < 0.0f) q = 0.0f;
      if (q > kLevels) q = kLevels;
      const uint8_t code = static_cast<uint8_t>(q);
      if (kBits == 8) {
        codes[lo + i] = code;
      } else {
        // Two codes per byte, low nibble first (quantize.py pack_bits).
        uint8_t& cell = codes[(lo + i) >> 1];
        if (((lo + i) & 1) == 0) {
          cell = code;
        } else {
          cell = static_cast<uint8_t>(cell | (code << 4));
        }
      }
      if (residual != nullptr || self_decode != nullptr ||
          quality != nullptr) {
        const float deq = mn + q * unit;
        if (residual != nullptr) residual[lo + i] = xbuf[i] - deq;
        if (self_decode != nullptr) self_decode[lo + i] = deq;
        if (quality != nullptr) AccumQuality(quality, xbuf[i], deq);
      }
    }
  }
}

template <int kBits, bool kAdd>
void MaxMinDecompress(const uint8_t* src, int64_t count, float* dst) {
  const int64_t nb = NumBuckets(count);
  const float* header = reinterpret_cast<const float*>(src);
  const uint8_t* codes = src + nb * 8;
  for (int64_t b = 0; b < nb; ++b) {
    const int64_t lo = b * kWireBucketSize;
    const int64_t n = std::min<int64_t>(kWireBucketSize, count - lo);
    const float mn = header[b * 2];
    const float unit = header[b * 2 + 1];
    for (int64_t i = 0; i < n; ++i) {
      uint8_t code;
      if (kBits == 8) {
        code = codes[lo + i];
      } else {
        const uint8_t cell = codes[(lo + i) >> 1];
        code = ((lo + i) & 1) == 0 ? (cell & 0x0f) : (cell >> 4);
      }
      const float v = mn + static_cast<float>(code) * unit;
      dst[lo + i] = kAdd ? dst[lo + i] + v : v;
    }
  }
}

}  // namespace

const char* WireCompressionName(WireCompression c) {
  switch (c) {
    case WireCompression::NONE: return "none";
    case WireCompression::FP16: return "fp16";
    case WireCompression::INT8: return "int8";
    case WireCompression::INT4: return "int4";
    case WireCompression::AUTO: return "auto";
  }
  return "unknown";
}

int64_t WireBytes(WireCompression c, int64_t count) {
  switch (c) {
    case WireCompression::FP16:
      return count * 2;
    case WireCompression::INT8:
      return NumBuckets(count) * 8 + count;
    case WireCompression::INT4:
      return NumBuckets(count) * 8 + (count + 1) / 2;
    case WireCompression::NONE:
    case WireCompression::AUTO:
      break;
  }
  return count * 4;
}

void WireCompress(WireCompression c, const float* src, int64_t count,
                  uint8_t* dst, float* residual, float* self_decode,
                  GradQuality* quality) {
  if (count <= 0) return;
  switch (c) {
    case WireCompression::FP16:
      Fp16Compress(src, count, dst, residual, self_decode, quality);
      return;
    case WireCompression::INT8:
#if defined(__x86_64__)
      if (HaveAvx2()) {
        MaxMinCompress8Avx2(src, count, dst, residual, self_decode, quality);
        return;
      }
#endif
      MaxMinCompress<8>(src, count, dst, residual, self_decode, quality);
      return;
    case WireCompression::INT4:
      MaxMinCompress<4>(src, count, dst, residual, self_decode, quality);
      return;
    case WireCompression::NONE:
    case WireCompression::AUTO:
      break;
  }
  memcpy(dst, src, static_cast<size_t>(count) * 4);
  if (self_decode != nullptr && self_decode != src) {
    memcpy(self_decode, src, static_cast<size_t>(count) * 4);
  }
}

void WireDecompress(WireCompression c, const uint8_t* src, int64_t count,
                    float* dst) {
  if (count <= 0) return;
  switch (c) {
    case WireCompression::FP16:
      Fp16Decompress(src, count, dst, /*add=*/false);
      return;
    case WireCompression::INT8:
#if defined(__x86_64__)
      if (HaveAvx2()) {
        MaxMinDecompress8Avx2<false>(src, count, dst);
        return;
      }
#endif
      MaxMinDecompress<8, false>(src, count, dst);
      return;
    case WireCompression::INT4:
      MaxMinDecompress<4, false>(src, count, dst);
      return;
    case WireCompression::NONE:
    case WireCompression::AUTO:
      break;
  }
  memcpy(dst, src, static_cast<size_t>(count) * 4);
}

void WireDecompressAdd(WireCompression c, const uint8_t* src, int64_t count,
                       float* dst) {
  if (count <= 0) return;
  switch (c) {
    case WireCompression::FP16:
      Fp16Decompress(src, count, dst, /*add=*/true);
      return;
    case WireCompression::INT8:
#if defined(__x86_64__)
      if (HaveAvx2()) {
        MaxMinDecompress8Avx2<true>(src, count, dst);
        return;
      }
#endif
      MaxMinDecompress<8, true>(src, count, dst);
      return;
    case WireCompression::INT4:
      MaxMinDecompress<4, true>(src, count, dst);
      return;
    case WireCompression::NONE:
    case WireCompression::AUTO: {
      const float* v = reinterpret_cast<const float*>(src);
      for (int64_t i = 0; i < count; ++i) dst[i] += v[i];
      return;
    }
  }
}

float* ResidualStore::Get(const std::string& key, int64_t count,
                          bool* reset) {
  if (reset != nullptr) *reset = false;
  if (buf_.size() >= kMaxEntries && buf_.find(key) == buf_.end()) {
    // Cap reached by a NEW signature: every live key's feedback is
    // dropped — that is a reset of real state, not a first use.
    if (reset != nullptr && !buf_.empty()) *reset = true;
    buf_.clear();
  }
  auto it = buf_.find(key);
  const bool existed = it != buf_.end();
  std::vector<float>& buf = existed ? it->second : buf_[key];
  if (buf.size() != static_cast<size_t>(count)) {
    // Element count changed on a LIVE key (refused fusion / reshape):
    // accumulated error feedback restarts from zero — the caller counts
    // and WARNs (hvdtpu_residual_resets_total) so a mid-run reshape is
    // visible instead of silently degrading quality.
    if (reset != nullptr && existed && !buf.empty()) *reset = true;
    buf.assign(static_cast<size_t>(count), 0.0f);
  }
  return buf.data();
}

}  // namespace hvdtpu
