// Host data plane: per-pair transport lanes (TCP mesh / shared memory) +
// collective algorithms.
//
// Fills the role of the reference's Gloo/MPI CPU data plane
// (horovod/common/ops/gloo_operations.cc, mpi_operations.cc): ring allreduce
// (reduce-scatter + allgather, like MPI/NCCL ring), rotation-based allgatherv,
// direct-send broadcast, and pairwise alltoallv — over pluggable transports
// (transport.h): plain TCP between hosts, POSIX shared-memory rings
// (shm_transport.h) between ranks sharing one. fp16/bf16 are accumulated in
// float (reference: half.{h,cc}).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "compressed.h"
#include "flightrec.h"
#include "metrics.h"
#include "shm_transport.h"
#include "tracing.h"
#include "transport.h"

namespace hvdtpu {
class Timeline;
}

namespace hvdtpu {

struct PeerAddr {
  std::string host;
  int port = 0;
};

// Allreduce algorithm menu (reference fork: the IST-DASLab layer's
// ring / scatter-allgather / parameter-server / tree reduction selection).
// AUTO picks the latency algorithm (recursive doubling) at or below the
// crossover size and a bandwidth algorithm above it — the pipelined ring, or
// scatter-allgather once the group reaches sa_min_group ranks (where the
// ring's 2(n-1) serialized hops lose to SA's one round-trip of depth); the
// crossover and the SA choice are owned by the autotune machinery
// (autotune.h ParameterManager). PARAMETER_SERVER is explicit-selection
// only: workers ship the whole vector to a root that reduces and
// broadcasts — the reference PS baseline, never a win AUTO should pick.
enum class AllreduceAlgo : int32_t {
  AUTO = 0,
  RING = 1,
  RECURSIVE_DOUBLING = 2,
  TREE = 3,
  SCATTER_ALLGATHER = 4,
  PARAMETER_SERVER = 5,
};

// Default ring/latency-algorithm crossover: messages at or below this ride
// recursive doubling (log2(n) full-size exchanges beat 2(n-1) chunk rounds
// when per-round latency dominates). Conservative — measured on loopback,
// recursive doubling loses to the ring well before 256 KB at larger worlds;
// the autotuner owns the workload-specific value (4 KB .. 4 MB range).
constexpr int64_t kDefaultAlgoCrossoverBytes = 32 * 1024;
// Default ring pipeline segment: each ring chunk is streamed in segments of
// this size so reduction of segment k overlaps the transfer of segment k+1.
constexpr int64_t kDefaultSegmentBytes = 1 << 20;
// Default group size at which AUTO prefers scatter-allgather over the ring
// above the crossover: SA's direct exchange finishes in ~2 rounds of depth
// vs the ring's 2(n-1) serialized hops, but posts n-1 concurrent lanes —
// oversubscribed small worlds do better on the ring's two-lane schedule.
// Override with HVDTPU_ALLREDUCE_SA_GROUP (0 = never auto-pick SA).
constexpr int kDefaultSaMinGroup = 16;
// Broadcast latency floor: payloads at or below this ride the flat schedule
// (root sends to every peer directly — one hop of depth per peer, no
// store-and-forward handoff), larger ones the binomial tree (⌈log2 n⌉
// serialized rounds, but each rank forwards at most ⌈log2 n⌉ copies instead
// of the root shipping n-1). Override with HVDTPU_BCAST_FLAT_MAX.
constexpr int64_t kDefaultBcastFlatMaxBytes = 4096;

// Hierarchical two-level allreduce (HVDTPU_ALLREDUCE_HIER / hvdrun --hier):
// intra-host ring reduce-scatter/allgather over the (shm) local lanes, one
// leader per host running the flat ring/recursive-doubling over TCP.
// AUTO leaves the on/off choice to the Bayesian autotuner.
enum class HierMode : int32_t {
  OFF = 0,
  ON = 1,
  AUTO = 2,
};

// Fault injection (chaos harness; docs/fault-tolerance.md): at most one
// action armed per process via HVDTPU_CHAOS -> hvdtpu_set_chaos. Fires once,
// at the op_index-th collective this rank starts (allreduce, adasum,
// reduce-scatter and allgather all count) or the hop_index-th pairwise
// exchange it runs (1-based; exchanges count across every phase — segmented
// ring hops, recursive-doubling rounds, tree edges, hier leader phases and
// compressed hops alike, so a randomized hop lands anywhere in the
// schedule). Python owns the spec grammar (horovod_tpu/chaos.py); the native
// side only sees resolved integers.
struct ChaosSpec {
  enum class Action : int32_t {
    NONE = 0,
    KILL = 1,     // raise(SIGKILL): abrupt rank death mid-schedule
    HANG = 2,     // wedge the collective thread forever (live but silent)
    DELAY = 3,    // one-shot sleep of delay_ms (must NOT trip detection)
    DROP = 4,     // blackhole one peer lane (partition: silent, no EOF)
    CORRUPT = 5,  // flip one byte of the op's reduced/gathered output —
                  // seeded silent data corruption the divergence probe
                  // (docs/numerics.md) must catch. op trigger only.
  };
  Action action = Action::NONE;
  int64_t op_index = 0;   // 0 = not op-gated
  int64_t hop_index = 0;  // 0 = not hop-gated
  int64_t delay_ms = 0;
  int peer = -1;  // DROP: lane to blackhole (-1 = the triggering hop's peer)
};

// Concurrency contract (checked indirectly by `make analyze`: this type
// holds no mutex on purpose): a DataPlane is driven by exactly ONE thread —
// the core's background loop (plus the Python host thread during
// Listen/Connect, strictly before that loop starts). Collectives, setters
// and per-op counters are therefore unsynchronized by design. The only
// cross-thread members are the metrics-registry counters
// (total_raw_bytes/total_wire_bytes, relaxed atomics readable from any
// thread) and the worker threads SendRecvSegmented spawns internally, which
// are joined before the collective returns. Adding a second driving thread
// requires adding a Mutex + GUARDED_BY annotations here first.
class DataPlane {
 public:
  DataPlane(int rank, int size);
  ~DataPlane();

  // Start listening; returns the bound (ephemeral) port to advertise.
  HVDTPU_CALLED_ON(background)
  Status Listen();
  HVDTPU_CALLED_ON(any)
  int port() const { return port_; }

  // Establish the mesh: connect to lower ranks, accept from higher ranks.
  HVDTPU_CALLED_ON(background)
  Status Connect(const std::vector<PeerAddr>& peers);

  HVDTPU_CALLED_ON(background)
  void Shutdown();

  // Break every lane NOW: flips the shared IoControl abort flag (sliced
  // reads observe it within one detect slice), aborts the shm segments
  // (futex waiters wake), and half-closes every TCP lane so blocked peers
  // see EOF — which is how failure detection cascades rank-to-rank across
  // the world within ~one detect slice per hop. Idempotent. Must run on the
  // collective-driving thread (same single-driver rule as the collectives;
  // cross-thread callers have the IoControl flags).
  HVDTPU_CALLED_ON(background)
  void Abort();
  HVDTPU_CALLED_ON(any)
  bool aborted() const { return io_ctl_.is_aborted(); }
  // First peer a lane failure was pinned on (-1 when none): names the
  // suspect in logs and the coordinator's dead-ranks accounting.
  HVDTPU_CALLED_ON(background)
  int failed_peer() const { return failed_peer_; }

  // Fault-detection knobs (docs/fault-tolerance.md), set before Start's
  // Connect. detect_ms bounds abort-propagation latency (poll slice =
  // detect_ms/5, clamped to [5, 100] ms); read_deadline_secs > 0 declares a
  // silent-but-open lane dead after that long with zero progress (0 = off);
  // formup_timeout_ms bounds Connect's accept phase.
  HVDTPU_CALLED_ON(background)
  void set_failure_detect_ms(int64_t ms) {
    if (ms <= 0) return;
    int64_t slice = ms / 5;
    io_ctl_.detect_slice_ms = slice < 5 ? 5 : (slice > 100 ? 100 : slice);
  }
  HVDTPU_CALLED_ON(background)
  void set_read_deadline_secs(double s) {
    io_ctl_.read_deadline_secs = s > 0 ? s : 0;
  }
  HVDTPU_CALLED_ON(background)
  void set_formup_timeout_ms(int64_t ms) {
    if (ms > 0) formup_timeout_ms_ = ms;
  }
  HVDTPU_CALLED_ON(background)
  void set_chaos(const ChaosSpec& spec) { chaos_ = spec; }

  // In-place allreduce over `count` elements (SUM/MIN/MAX/PRODUCT; AVERAGE
  // is SUM + caller-side postscale, reference operations.cc:928). Dispatches
  // by the configured algorithm: pipelined ring (reduce-scatter + allgather
  // with segment-level reduce/transfer overlap), recursive doubling, or
  // binomial tree; AUTO selects by message size vs the crossover.
  HVDTPU_CALLED_ON(background)
  Status Allreduce(void* data, int64_t count, DataType dtype, ReduceOp op);

  // Algorithm-selection knobs (hvdtpu_allreduce_algo surface + autotuned
  // crossover). Call from the thread that runs the collectives (the core's
  // background loop) or before it starts; values <= 0 are ignored.
  HVDTPU_CALLED_ON(background)
  void set_allreduce_algo(AllreduceAlgo algo) { algo_ = algo; }
  HVDTPU_CALLED_ON(background)
  void set_crossover_bytes(int64_t b) { if (b > 0) crossover_bytes_ = b; }
  HVDTPU_CALLED_ON(background)
  void set_segment_bytes(int64_t b) { if (b > 0) segment_bytes_ = b; }
  // AUTO's scatter-allgather gate: groups of at least this many ranks take
  // SA above the crossover (0 = never). set_sa_auto is the autotuner's
  // per-cycle choice on top of the static gate, mirroring set_hier_auto.
  HVDTPU_CALLED_ON(background)
  void set_sa_min_group(int64_t n) { if (n >= 0) sa_min_group_ = static_cast<int>(n); }
  HVDTPU_CALLED_ON(background)
  void set_sa_auto(bool on) { sa_auto_ = on; }
  // Broadcast flat/tree crossover (HVDTPU_BCAST_FLAT_MAX; 0 = always tree).
  HVDTPU_CALLED_ON(background)
  void set_bcast_flat_max(int64_t b) { if (b >= 0) bcast_flat_max_ = b; }
  HVDTPU_CALLED_ON(background)
  int64_t bcast_flat_max() const { return bcast_flat_max_; }
  HVDTPU_CALLED_ON(background)
  AllreduceAlgo allreduce_algo() const { return algo_; }
  HVDTPU_CALLED_ON(background)
  int64_t crossover_bytes() const { return crossover_bytes_; }
  HVDTPU_CALLED_ON(background)
  int64_t segment_bytes() const { return segment_bytes_; }
  HVDTPU_CALLED_ON(background)
  int sa_min_group() const { return sa_min_group_; }
  HVDTPU_CALLED_ON(background)
  bool sa_auto() const { return sa_auto_; }

  // Transport / topology knobs. set_shm_enabled and set_shm_ring_bytes must
  // be called before Connect (the lanes are negotiated there); hier mode may
  // change any time from the collective-driving thread, and set_hier_auto is
  // the autotuner's choice under HierMode::AUTO.
  HVDTPU_CALLED_ON(background)
  void set_shm_enabled(bool on) { shm_enabled_ = on; }
  HVDTPU_CALLED_ON(background)
  void set_shm_ring_bytes(int64_t b) { if (b > 0) shm_ring_bytes_ = b; }
  HVDTPU_CALLED_ON(background)
  void set_hier_mode(HierMode m) { hier_mode_ = m; }
  HVDTPU_CALLED_ON(background)
  void set_hier_auto(bool on) { hier_auto_ = on; }
  // Zero-copy lane knobs (PR 9; docs/collectives.md "Zero-copy TCP lane").
  // Must be set before Connect: the TCP lanes probe at construction, the
  // shm lanes take their doorbell/NUMA policy at negotiation.
  HVDTPU_CALLED_ON(background)
  void set_tcp_zerocopy(ZeroCopyMode m) { tcp_zerocopy_ = m; }
  HVDTPU_CALLED_ON(background)
  void set_shm_numa(ShmNumaMode m) { shm_numa_ = m; }
  HVDTPU_CALLED_ON(background)
  void set_doorbell_batch(int64_t b) { if (b > 0) doorbell_batch_ = b; }
  HVDTPU_CALLED_ON(background)
  ZeroCopyMode tcp_zerocopy() const { return tcp_zerocopy_; }
  HVDTPU_CALLED_ON(background)
  HierMode hier_mode() const { return hier_mode_; }
  // True when Allreduce will take the two-level path: hier requested (or
  // autotuned on) and at least one host holds 2+ ranks. The predicate must
  // be identical on EVERY rank (it's a world-level property — leaders_ and
  // size_ agree everywhere), or ranks would split between the flat and
  // hierarchical schedules and deadlock.
  HVDTPU_CALLED_ON(background)
  bool hier_active() const {
    if (size_ <= 1 || leaders_.size() >= static_cast<size_t>(size_)) {
      return false;  // every host single-rank: hier degenerates to flat
    }
    return hier_mode_ == HierMode::ON ||
           (hier_mode_ == HierMode::AUTO && hier_auto_);
  }
  // Per-peer shm-ring occupancy (peer rank, buffered bytes) for the
  // memory-occupancy telemetry gauges (docs/profiling.md). Background
  // thread only, like the other lane walks.
  HVDTPU_CALLED_ON(background)
  void ShmOccupancy(std::vector<std::pair<int, int64_t>>* out) const;
  // Lane summary for the timeline / introspection: "tcp", "tcp-zc", "shm",
  // "shm+tcp", "shm+tcp-zc" ("local" before Connect / at size 1). Rebuilt
  // per call because the zero-copy tag is LIVE: an AUTO lane that detects
  // kernel-copied completions downgrades itself mid-run and the per-op
  // metric/timeline labels must follow. Collective-driving thread only.
  HVDTPU_CALLED_ON(background)
  const std::string& transport_label();
  HVDTPU_CALLED_ON(background)
  int shm_lane_count() const;  // peers reached over shared memory
  // Any TCP lane currently riding the zero-copy engine? (introspection +
  // tests; background thread only, like the label.)
  HVDTPU_CALLED_ON(background)
  bool zerocopy_active() const;
  HVDTPU_CALLED_ON(background)
  int num_hosts() const { return static_cast<int>(leaders_.size()); }

  // Per-op wire compression (compressed.h). The core calls
  // BeginCompressedOp before each allreduce with the effective mode for
  // that (fused) tensor — resolved from HVDTPU_COMPRESSION, the min-bytes
  // bypass and the skip-regex, identically on every rank — and the
  // tensor's error-feedback residual buffer (nullable). Collective-driving
  // (background) thread only. Compression applies to fp32 SUM/AVERAGE on
  // the ring and recursive-doubling paths (tree and the hierarchical
  // intra-host/gather stages stay raw; hier compresses the leader phase —
  // the slow cross-host link, the reference fork's premise).
  // quality (nullable): per-op quantization-quality accumulator
  // (gradstats.h) threaded into every WireCompress call this op makes —
  // the core reads MSE/SNR/residual-norm out of it at op completion
  // (docs/numerics.md).
  HVDTPU_CALLED_ON(background)
  void BeginCompressedOp(WireCompression c, float* residual,
                         GradQuality* quality = nullptr) {
    op_comp_ = c == WireCompression::AUTO ? WireCompression::NONE : c;
    op_residual_ = residual;
    op_quality_ = quality;
    if (quality != nullptr) quality->Reset();
  }
  HVDTPU_CALLED_ON(background)
  void EndCompressedOp() {
    op_comp_ = WireCompression::NONE;
    op_residual_ = nullptr;
    op_quality_ = nullptr;
  }

  // Payload accounting for the timeline's per-op raw_bytes/wire_bytes args
  // and the cumulative hvdtpu_wire_stats counters: raw = bytes this rank
  // would have sent uncompressed, wire = bytes actually sent. Reset by
  // Allreduce/AdasumAllreduce at entry; cumulative totals live in the
  // metrics registry (hvdtpu_allreduce_{raw,wire}_bytes_total) — the single
  // source of truth behind both hvdtpu_wire_stats and /metrics — whose
  // lock-free counters user threads may read while the background thread
  // runs ops.
  HVDTPU_CALLED_ON(background)
  int64_t op_raw_bytes() const { return op_raw_bytes_; }
  HVDTPU_CALLED_ON(background)
  int64_t op_wire_bytes() const { return op_wire_bytes_; }
  HVDTPU_CALLED_ON(any)
  int64_t total_raw_bytes() const { return raw_bytes_total_->Get(); }
  HVDTPU_CALLED_ON(any)
  int64_t total_wire_bytes() const { return wire_bytes_total_->Get(); }

  // Metrics registry to account into. The DataPlane constructor wires up a
  // private registry so standalone instances (unit tests, bench harness)
  // always have live counters; the core injects its own registry before
  // Listen() so data-plane series land in the worker's /metrics dump.
  HVDTPU_CALLED_ON(background)
  void set_metrics(Metrics* m);

  // Distributed tracing (docs/tracing.md): per-hop SEND/RECV/SENDRECV/
  // REDUCE/QUANTIZE child spans on the timeline's "hops" track, emitted for
  // every `sample_every_n`-th op (TraceSampler) so the un-sampled hot path
  // pays one branch per hop. The tracer outlives the plane (core owns
  // both); both setters are collective-driving-thread-only like the other
  // knobs (the core's ApplyTimelineRequest runs there).
  HVDTPU_CALLED_ON(background)
  void set_tracer(Timeline* t) { tracer_ = t; }
  HVDTPU_CALLED_ON(background)
  void set_trace_sample(int64_t n) { trace_sampler_.set_every_n(n); }
  HVDTPU_CALLED_ON(background)
  int64_t trace_sample() const { return trace_sampler_.every_n(); }
  // Always-on flight recorder (flightrec.h): every hop/reduce/quantize and
  // failure-detect event lands in the ring UNSAMPLED — five relaxed atomic
  // stores per event, no JSON — alongside whatever the sampled tracer
  // emits. Set before Connect (core owns the recorder; nullptr disables).
  HVDTPU_CALLED_ON(background)
  void set_flightrec(FlightRecorder* fr) {
    flight_ = fr != nullptr && fr->enabled() ? fr : nullptr;
  }
  // True while the CURRENT op is being sampled (core gates its own
  // tensor-level FUSION-WAIT spans on the same decision).
  HVDTPU_CALLED_ON(background)
  bool trace_sampling_op() const { return trace_op_; }
  // Always-on perf attribution (perfstats.h): when enabled, TraceHop also
  // accumulates this op's wait/wire/reduce/codec phase buckets (and the
  // slowest hop peer) unsampled — the core feeds them into PerfStats at op
  // completion. Same timestamping gate the flight recorder already pays.
  HVDTPU_CALLED_ON(background)
  void set_perf_enabled(bool on) { perf_on_ = on; }
  HVDTPU_CALLED_ON(background)
  int64_t op_wait_us() const { return op_wait_us_; }
  HVDTPU_CALLED_ON(background)
  int64_t op_wire_us() const { return op_wire_us_; }
  HVDTPU_CALLED_ON(background)
  int64_t op_reduce_us() const { return op_reduce_us_; }
  HVDTPU_CALLED_ON(background)
  int64_t op_codec_us() const { return op_codec_us_; }
  // Hop peer this op spent the most wait time on (-1 none): the wire-slow
  // anomaly's named suspect. Background thread only, like the accumulators.
  HVDTPU_CALLED_ON(background)
  int op_slow_peer() const { return op_slow_peer_; }
  // Label of the algorithm the LAST Allreduce actually ran ("ring",
  // "recursive_doubling", "tree", "scatter_allgather", "parameter_server",
  // with AUTO resolved by size; "hier" phases report the top-level
  // "hierarchical"). Background thread only — set by Allreduce, read by the
  // core's per-op metric labels.
  HVDTPU_CALLED_ON(background)
  const char* last_algo_label() const { return last_algo_label_; }

  // First-class allgather (PR 18): gather variable-length byte blocks from
  // every rank; out = concatenated in rank order. block_bytes[r] gives each
  // rank's contribution size (negotiated per-rank dim-0 in the RESPONSES
  // frame). Dispatches like Allreduce: at or below the crossover the direct
  // pairwise rotation ("direct", n-1 full-duplex lanes), above it the ring
  // store-and-forward rotation ("ring", neighbor lanes only — the
  // allreduce's allgather phase generalized to ragged blocks). When the
  // core armed wire compression for the op (BeginCompressedOp; fp32 blocks
  // only), the ring variant ships quantize-once owner codes: every rank —
  // the owner included, via self-decode — decodes identical codes, so the
  // gathered vectors are bitwise identical world-wide. Full op lifecycle
  // (chaos trigger, cumulative byte counters, perf phases) like Allreduce.
  HVDTPU_CALLED_ON(background)
  Status Allgatherv(const void* in, int64_t in_bytes,
                    const std::vector<int64_t>& block_bytes,
                    ByteBuf* out);

  // First-class broadcast (PR 19): binomial tree from the root (MPICH
  // schedule — depth ⌈log2 n⌉ vs the flat root-fanout's n-1 serialized
  // sends) with a flat fallback at or below bcast_flat_max_ bytes. When the
  // core armed wire compression (BeginCompressedOp; fp32 payloads only), the
  // ROOT quantizes once with self-decode and every hop forwards the codes
  // verbatim — all ranks decode identical codes, so the broadcast values are
  // bitwise identical world-wide even under int4 (the PR-18 quantize-once
  // pattern; no error-feedback residual — a broadcast payload is a value,
  // not a gradient stream). Full op lifecycle like Allreduce: chaos trigger,
  // cumulative byte counters, perf phases, algo label ("bcast_tree" /
  // "bcast_flat").
  HVDTPU_CALLED_ON(background)
  Status Broadcast(void* data, int64_t bytes, int root);

  // First-class pairwise alltoallv (PR 19): send_bytes[r] from my buffer to
  // rank r (contiguous, in rank order); recv_bytes[r] received from rank r
  // into out (rank order). Step k exchanges with ranks (rank±k) — n-1
  // full-duplex hops, every block travels exactly one hop, so uneven (MoE
  // capacity-skew) splits cost only the bytes actually routed. Under wire
  // compression each fp32 block is quantized once at its sender (the self
  // block self-decodes through the same codec) and decoded at its one
  // receiver — single-hop determinism needs no forwarding discipline. Full
  // op lifecycle like Allreduce; algo label "pairwise".
  HVDTPU_CALLED_ON(background)
  Status Alltoallv(const void* in, const std::vector<int64_t>& send_bytes,
                   const std::vector<int64_t>& recv_bytes,
                   ByteBuf* out);

  // First-class reduce-scatter (PR 18): reduce `count` elements across the
  // world and keep this rank's contiguous dim-0 chunk — the ring allreduce's
  // reduce-scatter phase promoted to a public op, at half an allreduce's
  // wire bytes ((n-1)/n of the payload per rank). Runs the existing ring
  // machinery over the rotated group [1, 2, ..., n-1, 0]: rank r sits at
  // group index (r-1+n)%n, so the phase's owner rule (member gi owns chunk
  // (gi+1)%gs) lands chunk r on rank r while the physical ring neighbors —
  // and therefore the segmented/zero-copy lane schedule — are unchanged.
  // Compressed mode (BeginCompressedOp, fp32 SUM/AVERAGE) rides the same
  // quantized hops as the compressed ring allreduce's first half. The
  // public op requires count % size == 0 (validated by the coordinator);
  // standalone callers may pass ragged counts and get the ragged chunk.
  HVDTPU_CALLED_ON(background)
  Status ReduceScatter(const void* in, int64_t count, DataType dtype,
                       ReduceOp op, ByteBuf* out);

  // In-place Adasum reduction (float32/float64): hypercube pairwise exchange
  // with the adaptive combine a*(1 - dot/2|a|^2) + b*(1 - dot/2|b|^2)
  // (reference: horovod/common/ops/adasum/adasum.h:38). Non-power-of-two
  // worlds fold extra ranks in by addition first, like the Python/XLA path.
  HVDTPU_CALLED_ON(background)
  Status AdasumAllreduce(void* data, int64_t count, DataType dtype);

 private:
  // Send to one peer while receiving from another (possibly the same), with
  // optional segment callbacks on the receive side. The building block every
  // algorithm rides; routes through the per-peer transports.
  // view_align: element size the receive-side callback views are aligned
  // to (the shm lane consumes segments IN PLACE from its ring — see
  // SegmentFn in transport.h — and must never hand the reducer a torn
  // element).
  Status Exchange(int send_peer, const void* send_buf, int64_t send_bytes,
                  int recv_peer, void* recv_buf, int64_t recv_bytes,
                  int64_t segment_bytes = 0,
                  const SegmentFn& on_segment = nullptr,
                  size_t view_align = 1);

  // Record a lane failure against `peer`, abort the plane, and return the
  // coherent "peer failure" status every subsequent op also gets.
  Status FailLane(int peer, const char* what);
  // Tracing helpers (no-ops unless the current op is sampled). BeginOpTrace
  // rolls the sampler at op entry; TraceHop emits one child span on the
  // "hops" track carrying {send/recv peer, bytes, lane, algo, hier,
  // compression, seg index, wait_us split}. wait0_us is the IoControl
  // wait counter snapshot from the hop's start.
  void BeginOpTrace();
  void TraceHop(const char* name, int send_peer, int recv_peer,
                int64_t bytes, int64_t t0_us, int64_t wait0_us);
  // One-directional hops with the same fault machinery as Exchange (chaos
  // hop counting, abort fast-fail, blackhole, FailLane attribution): the
  // tree edges, recursive-doubling fold/unfold links, hier leader
  // gather/scatter and broadcast fan-out all ride these, so the abort path
  // threads through EVERY schedule shape, not just the ring.
  Status SendTo(int peer, const void* buf, int64_t bytes, const char* what);
  Status RecvFrom(int peer, void* buf, int64_t bytes, const char* what);
  // Chaos triggers: counted at allreduce entry / every Exchange. MaybeChaos*
  // fire the armed action when its index is reached (FireChaos may not
  // return: KILL/HANG). BlackholeWait parks an exchange against a dropped
  // lane until the plane aborts or the read deadline declares it dead.
  void MaybeChaosOp();
  void MaybeChaosHop(int send_peer, int recv_peer);
  void FireChaos(int peer_hint);
  Status BlackholeWait(int peer);

  // Negotiate the per-pair lane (shm for same-host peers when both sides
  // set it up, TCP otherwise) over the freshly established socket mesh.
  Status SetupTransports(const std::vector<PeerAddr>& peers);

  // All algorithms run over an arbitrary ordered rank group so the flat
  // path (group = the whole world) and the hierarchical leader/local phases
  // share one implementation.
  Status AllreduceGroup(void* data, int64_t count, DataType dtype,
                        ReduceOp op, const std::vector<int>& group);
  // Bandwidth path: ring reduce-scatter + allgather; each reduce-scatter
  // step streams the incoming chunk in segments so ReduceBuffer of segment
  // k overlaps the transfer of segment k+1.
  Status RingAllreduceGroup(void* data, int64_t count, DataType dtype,
                            ReduceOp op, const std::vector<int>& group);
  // Latency path: log2(p) full-message pairwise exchanges; non-power-of-two
  // groups fold the extra ranks in by reduction first (like Adasum).
  Status RecursiveDoublingGroup(void* data, int64_t count, DataType dtype,
                                ReduceOp op, const std::vector<int>& group);
  // Binomial reduce-to-0 + binomial broadcast (reference fork's tree menu
  // entry; half the exchange volume of recursive doubling, twice the depth).
  Status TreeAllreduceGroup(void* data, int64_t count, DataType dtype,
                            ReduceOp op, const std::vector<int>& group);
  // Direct-exchange two-phase reduce (reference fork's scatter-allgather
  // menu entry): phase 1 rotates gs-1 pairwise exchanges so each rank
  // receives its owned chunk's slice from EVERY peer and reduces locally in
  // ascending source-rank order — the same accumulation order the ring's
  // reduce-scatter produces, so the result is bitwise identical to the
  // ring's; phase 2 is the rotation allgather. Same chunk ownership as the
  // ring: member gi owns chunk (gi+1) % gs.
  Status ScatterAllgatherGroup(void* data, int64_t count, DataType dtype,
                               ReduceOp op, const std::vector<int>& group);
  // Parameter-server baseline (reference PS mode): every worker ships the
  // whole vector to group[0], which reduces in rank order and broadcasts
  // the result — 2 hops of depth, n x the root's wire volume. The single
  // reduced buffer makes cross-rank bitwise equality trivial.
  Status ParameterServerGroup(void* data, int64_t count, DataType dtype,
                              ReduceOp op, const std::vector<int>& group);

  // Compressed-hop variants of the ring phases (fp32 SUM only; gated by
  // CompressionActive). Reduce-scatter: each hop quantizes the outgoing
  // chunk (error-feedback residual applied at the compressing rank),
  // ships the wire form, and the receiver dequantizes + reduces in fp32.
  // Allgather: the chunk owner quantizes its fully reduced chunk ONCE,
  // replaces its own copy with the dequantized values, and every hop
  // forwards the owner's wire bytes verbatim — all ranks decode identical
  // codes, so the final vectors are bitwise identical everywhere.
  Status CompressedRingReduceScatter(float* buf,
                                     const std::vector<int64_t>& starts,
                                     const std::vector<int>& group, int gi);
  Status CompressedRingAllgather(float* buf,
                                 const std::vector<int64_t>& starts,
                                 const std::vector<int>& group, int gi);
  // Recursive doubling with compressed exchanges: each round both peers
  // quantize their partial sum (self-decoding their own copy), so both
  // compute deQ(a) + deQ(b) and stay bitwise identical. Non-power-of-two
  // folds compress the uplink; the unfold ships the final vector raw so
  // folded ranks match the main group exactly.
  Status CompressedRecursiveDoubling(float* data, int64_t count,
                                     const std::vector<int>& group);
  // Compressed scatter-allgather: phase 1 quantizes every outgoing slice
  // (error feedback applied at its buffer region — each region is
  // compressed exactly once per op: gs-1 peer slices here, the owned chunk
  // in phase 2) and the receiver dequantize-adds into its owned chunk;
  // phase 2 is the quantize-once-at-owner rotation the ring allgather uses,
  // so every rank decodes identical codes and the vectors stay bitwise
  // identical world-wide.
  Status CompressedScatterAllgather(float* buf,
                                    const std::vector<int64_t>& starts,
                                    const std::vector<int>& group, int gi);
  // Compressed parameter-server: workers quantize the uplink (error
  // feedback at the worker), the root dequantize-adds in rank order, then
  // quantizes the result ONCE (self-decoding its own copy) and ships the
  // same wire bytes to every worker — bitwise identity by construction.
  Status CompressedParameterServer(float* buf, int64_t count,
                                   const std::vector<int>& group, int gi);

  bool CompressionActive(DataType dtype, ReduceOp op) const {
    return op_comp_ != WireCompression::NONE &&
           dtype == DataType::FLOAT32 &&
           (op == ReduceOp::SUM || op == ReduceOp::AVERAGE);
  }
  void AddOpBytes(int64_t raw, int64_t wire) {
    op_raw_bytes_ += raw;
    op_wire_bytes_ += wire;
  }

  // Ring phases over a group (shared by RingAllreduceGroup and the
  // hierarchical intra-host stages). After the reduce-scatter, group member
  // gi owns chunk (gi+1) % group_size fully reduced.
  Status RingReduceScatterPhase(uint8_t* buf, const std::vector<int64_t>& starts,
                                size_t elem, DataType dtype, ReduceOp op,
                                const std::vector<int>& group, int gi);
  Status RingAllgatherPhase(uint8_t* buf, const std::vector<int64_t>& starts,
                            size_t elem, const std::vector<int>& group,
                            int gi);

  // First-class allgather internals (PR 18), both over the natural world
  // ring (rank r owns block r; offsets[r] = byte start of block r in out).
  // RingAllgathervPhase: store-and-forward rotation of ragged blocks — at
  // step s ship block (rank-s), receive block (rank-s-1) from the left
  // neighbor, n-1 hops over neighbor lanes only. CompressedRingAllgatherv:
  // same rotation, but each block travels as its owner's quantize-once wire
  // codes (fp32 blocks; the owner self-decodes), forwarded verbatim so all
  // ranks decode identical codes and the result is bitwise identical
  // world-wide.
  Status RingAllgathervPhase(const std::vector<int64_t>& offsets,
                             const std::vector<int64_t>& block_bytes,
                             uint8_t* out);
  Status CompressedRingAllgatherv(const std::vector<int64_t>& offsets,
                                  const std::vector<int64_t>& block_bytes,
                                  uint8_t* out);

  // Broadcast internals (PR 19). Both schedules ship `wire_bytes` of `buf`
  // from `root` to every rank; raw_per_send is the uncompressed-equivalent
  // byte count each send accounts (== wire_bytes for raw payloads, the fp32
  // size for forwarded codes). Binomial: MPICH vrank schedule — receive from
  // parent (vrank minus its lowest set bit), forward to children on
  // descending masks. Flat: root sends to each peer directly.
  Status BinomialBroadcastSchedule(void* buf, int64_t wire_bytes,
                                   int64_t raw_per_send, int root);
  Status FlatBroadcastSchedule(void* buf, int64_t wire_bytes,
                               int64_t raw_per_send, int root);
  // Quantize-once-at-root broadcast: the root compresses `count` fp32
  // elements ONCE (self-decoding its own copy), the chosen schedule forwards
  // the codes verbatim, and every non-root rank decodes after its forwards
  // complete — bitwise identity world-wide by construction.
  Status CompressedBroadcast(float* data, int64_t count, int root, bool flat);
  // Compressed pairwise alltoallv: each outgoing fp32 block is quantized
  // once for its single receiver (the self block through the same
  // quantize/self-decode roundtrip so every block in `out` is uniformly
  // lossy), shipped as codes, and decoded on arrival.
  Status CompressedAlltoallv(const float* in,
                             const std::vector<int64_t>& send_off,
                             const std::vector<int64_t>& recv_off,
                             uint8_t* out);

  // Two-level path: intra-host ring reduce-scatter -> chunks gathered to the
  // host leader -> leaders run the flat algorithm over TCP -> chunks
  // scattered back -> intra-host ring allgather.
  Status HierarchicalAllreduce(void* data, int64_t count, DataType dtype,
                               ReduceOp op);

  int rank_;
  int size_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::vector<int> fds_;  // per-peer socket; -1 for self (owned here)
  std::vector<std::unique_ptr<Transport>> transports_;  // per-peer lane

  // Host topology, derived from the peer table in Connect().
  std::vector<int> world_group_;  // 0..size-1
  std::vector<int> local_group_;  // ranks sharing my host (sorted)
  std::vector<int> leaders_;      // lowest rank per host (sorted)

  AllreduceAlgo algo_ = AllreduceAlgo::AUTO;
  int64_t crossover_bytes_ = kDefaultAlgoCrossoverBytes;
  int64_t segment_bytes_ = kDefaultSegmentBytes;
  int sa_min_group_ = kDefaultSaMinGroup;
  bool sa_auto_ = true;  // autotuner's SA-vs-ring pick under AUTO
  int64_t bcast_flat_max_ = kDefaultBcastFlatMaxBytes;
  bool shm_enabled_ = true;
  int64_t shm_ring_bytes_ = 0;  // 0 = shm_transport.h kDefaultShmRingBytes
  std::string transport_label_ = "local";
  HierMode hier_mode_ = HierMode::AUTO;
  bool hier_auto_ = false;
  // Zero-copy lane configuration (PR 9): TCP MSG_ZEROCOPY/io_uring mode,
  // shm NUMA placement, futex-doorbell coalescing window (0 = lane
  // default). Applied at Connect/SetupTransports.
  ZeroCopyMode tcp_zerocopy_ = ZeroCopyMode::AUTO;
  ShmNumaMode shm_numa_ = ShmNumaMode::AUTO;
  int64_t doorbell_batch_ = 0;
  // TCP lanes (downcast cache) for zero-copy counter publication.
  std::vector<TcpTransport*> tcp_lanes_;
  int64_t zc_sends_published_ = 0;
  int64_t zc_fallbacks_published_ = 0;
  // Largest payload a TCP lane may send inline (blocking send, then recv)
  // without a deadlock risk; measured against the mesh's socket buffer
  // sizes in Connect(). 0 (pre-Connect) = always use the concurrent path.
  int64_t inline_max_bytes_ = 0;

  // Fault detection + injection state. io_ctl_ is shared with every lane
  // (its atomics are the only cross-thread members here); the rest is
  // driven by the collective thread only, like the members above.
  IoControl io_ctl_;
  int64_t formup_timeout_ms_ = 60000;
  int failed_peer_ = -1;
  ChaosSpec chaos_;
  int64_t chaos_ops_ = 0;
  int64_t chaos_hops_ = 0;
  int blackholed_peer_ = -1;
  // CORRUPT fired at this op's entry: flip one output byte AFTER the
  // reduction completes (the corruption must be in the post-allreduce
  // buffer the divergence probe fingerprints, not in an input a correct
  // reduction would overwrite).
  bool corrupt_pending_ = false;

  // Distributed-tracing state (background thread only, like the chaos
  // counters): the core's timeline as span sink, the every-Nth-op sampler,
  // and the current op's sampled flag + hop sequence. rec_hops_ is the
  // combined "timestamp this hop at all" gate: sampled-trace JSON OR the
  // always-on flight ring (flight_) wants it.
  Timeline* tracer_ = nullptr;
  TraceSampler trace_sampler_;
  bool trace_op_ = false;
  bool rec_hops_ = false;
  int64_t trace_hop_seq_ = 0;
  FlightRecorder* flight_ = nullptr;
  // Zero the per-op phase accumulators. Called by BeginOpTrace and by the
  // early returns that skip it (size_==1 / empty ops still reach
  // ObserveOp, which reads the accumulators unconditionally).
  void ResetOpPhaseAccum();
  // Per-op phase accumulation for the perf-attribution subsystem
  // (perfstats.h): reset by BeginOpTrace, fed by TraceHop and the
  // segmented-ring reduce callback, read by the core after each op.
  bool perf_on_ = false;
  int64_t op_wait_us_ = 0;
  int64_t op_wire_us_ = 0;
  int64_t op_reduce_us_ = 0;
  int64_t op_codec_us_ = 0;
  int op_slow_peer_ = -1;
  int64_t op_slow_peer_wait_us_ = 0;

  // Per-op wire compression state (background thread only) + payload
  // accounting (cumulative totals live in the metrics registry, readable
  // cross-thread).
  WireCompression op_comp_ = WireCompression::NONE;
  float* op_residual_ = nullptr;
  GradQuality* op_quality_ = nullptr;
  int64_t op_raw_bytes_ = 0;
  int64_t op_wire_bytes_ = 0;
  const char* last_algo_label_ = "none";
  // Registry state last and behind a pointer: embedding the fallback
  // registry inline shifted the hot members across cache lines and cost a
  // measurable ~3% on the 64 MB shm ring bench (layout, not work — the
  // counter adds themselves are two relaxed atomics per op).
  std::unique_ptr<Metrics> own_metrics_;  // fallback for standalone use
  Metrics* metrics_ = nullptr;
  Counter* raw_bytes_total_ = nullptr;
  Counter* wire_bytes_total_ = nullptr;
  Counter* zc_sends_total_ = nullptr;
  Counter* zc_fallbacks_total_ = nullptr;

  // Publish the TCP lanes' zero-copy send/fallback totals into the metrics
  // registry (delta-based; called at op boundaries on the driving thread).
  void PublishZeroCopyCounters();
};

// dst[i] = dst[i] OP src[i], accumulating fp16/bf16 in float.
void ReduceBuffer(void* dst, const void* src, int64_t count, DataType dtype,
                  ReduceOp op);

// Half-precision conversions (reference: horovod/common/half.{h,cc}).
float HalfToFloatPublic(uint16_t h);
uint16_t FloatToHalfPublic(float f);
float Bf16ToFloatPublic(uint16_t h);
uint16_t FloatToBf16Public(float f);

}  // namespace hvdtpu
