// Host data plane: TCP mesh between ranks + collective algorithms.
//
// Fills the role of the reference's Gloo/MPI CPU data plane
// (horovod/common/ops/gloo_operations.cc, mpi_operations.cc): ring allreduce
// (reduce-scatter + allgather, like MPI/NCCL ring), rotation-based allgatherv,
// direct-send broadcast, and pairwise alltoallv — over plain TCP, no MPI.
// fp16/bf16 are accumulated in float (reference: half.{h,cc}).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

struct PeerAddr {
  std::string host;
  int port = 0;
};

class DataPlane {
 public:
  DataPlane(int rank, int size);
  ~DataPlane();

  // Start listening; returns the bound (ephemeral) port to advertise.
  Status Listen();
  int port() const { return port_; }

  // Establish the mesh: connect to lower ranks, accept from higher ranks.
  Status Connect(const std::vector<PeerAddr>& peers);

  void Shutdown();

  // In-place ring allreduce over `count` elements (SUM/MIN/MAX/PRODUCT;
  // AVERAGE is SUM + caller-side postscale, reference operations.cc:928).
  Status Allreduce(void* data, int64_t count, DataType dtype, ReduceOp op);

  // Gather variable-length byte blocks from every rank; out = concatenated in
  // rank order. block_bytes[r] gives each rank's contribution size.
  Status Allgatherv(const void* in, int64_t in_bytes,
                    const std::vector<int64_t>& block_bytes,
                    std::vector<uint8_t>* out);

  Status Broadcast(void* data, int64_t bytes, int root);

  // Pairwise alltoallv: send_bytes[r] from my buffer to rank r (contiguous,
  // in rank order); recv_bytes[r] received from rank r into out (rank order).
  Status Alltoallv(const void* in, const std::vector<int64_t>& send_bytes,
                   const std::vector<int64_t>& recv_bytes,
                   std::vector<uint8_t>* out);

  // Reduce then keep this rank's contiguous chunk (count must divide evenly;
  // validated by the coordinator before dispatch).
  Status ReduceScatter(const void* in, int64_t count, DataType dtype,
                       ReduceOp op, std::vector<uint8_t>* out);

  // In-place Adasum reduction (float32/float64): hypercube pairwise exchange
  // with the adaptive combine a*(1 - dot/2|a|^2) + b*(1 - dot/2|b|^2)
  // (reference: horovod/common/ops/adasum/adasum.h:38). Non-power-of-two
  // worlds fold extra ranks in by addition first, like the Python/XLA path.
  Status AdasumAllreduce(void* data, int64_t count, DataType dtype);

 private:
  Status SendRecv(int send_fd, const void* send_buf, int64_t send_bytes,
                  int recv_fd, void* recv_buf, int64_t recv_bytes);

  int rank_;
  int size_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::vector<int> fds_;  // per-peer connection; -1 for self
};

// dst[i] = dst[i] OP src[i], accumulating fp16/bf16 in float.
void ReduceBuffer(void* dst, const void* src, int64_t count, DataType dtype,
                  ReduceOp op);

// Half-precision conversions (reference: horovod/common/half.{h,cc}).
float HalfToFloatPublic(uint16_t h);
uint16_t FloatToHalfPublic(float f);
float Bf16ToFloatPublic(uint16_t h);
uint16_t FloatToBf16Public(float f);

}  // namespace hvdtpu
