// Host data plane: TCP mesh between ranks + collective algorithms.
//
// Fills the role of the reference's Gloo/MPI CPU data plane
// (horovod/common/ops/gloo_operations.cc, mpi_operations.cc): ring allreduce
// (reduce-scatter + allgather, like MPI/NCCL ring), rotation-based allgatherv,
// direct-send broadcast, and pairwise alltoallv — over plain TCP, no MPI.
// fp16/bf16 are accumulated in float (reference: half.{h,cc}).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

struct PeerAddr {
  std::string host;
  int port = 0;
};

// Allreduce algorithm menu (reference fork: the IST-DASLab layer's
// ring / scatter-allgather / tree reduction selection). AUTO picks the
// latency algorithm (recursive doubling) at or below the crossover size and
// the pipelined ring above it; the crossover is owned by the autotune
// machinery (autotune.h ParameterManager).
enum class AllreduceAlgo : int32_t {
  AUTO = 0,
  RING = 1,
  RECURSIVE_DOUBLING = 2,
  TREE = 3,
};

// Default ring/latency-algorithm crossover: messages at or below this ride
// recursive doubling (log2(n) full-size exchanges beat 2(n-1) chunk rounds
// when per-round latency dominates). Conservative — measured on loopback,
// recursive doubling loses to the ring well before 256 KB at larger worlds;
// the autotuner owns the workload-specific value (4 KB .. 4 MB range).
constexpr int64_t kDefaultAlgoCrossoverBytes = 32 * 1024;
// Default ring pipeline segment: each ring chunk is streamed in segments of
// this size so reduction of segment k overlaps the transfer of segment k+1.
constexpr int64_t kDefaultSegmentBytes = 1 << 20;

class DataPlane {
 public:
  DataPlane(int rank, int size);
  ~DataPlane();

  // Start listening; returns the bound (ephemeral) port to advertise.
  Status Listen();
  int port() const { return port_; }

  // Establish the mesh: connect to lower ranks, accept from higher ranks.
  Status Connect(const std::vector<PeerAddr>& peers);

  void Shutdown();

  // In-place allreduce over `count` elements (SUM/MIN/MAX/PRODUCT; AVERAGE
  // is SUM + caller-side postscale, reference operations.cc:928). Dispatches
  // by the configured algorithm: pipelined ring (reduce-scatter + allgather
  // with segment-level reduce/transfer overlap), recursive doubling, or
  // binomial tree; AUTO selects by message size vs the crossover.
  Status Allreduce(void* data, int64_t count, DataType dtype, ReduceOp op);

  // Algorithm-selection knobs (hvdtpu_allreduce_algo surface + autotuned
  // crossover). Call from the thread that runs the collectives (the core's
  // background loop) or before it starts; values <= 0 are ignored.
  void set_allreduce_algo(AllreduceAlgo algo) { algo_ = algo; }
  void set_crossover_bytes(int64_t b) { if (b > 0) crossover_bytes_ = b; }
  void set_segment_bytes(int64_t b) { if (b > 0) segment_bytes_ = b; }
  AllreduceAlgo allreduce_algo() const { return algo_; }
  int64_t crossover_bytes() const { return crossover_bytes_; }
  int64_t segment_bytes() const { return segment_bytes_; }

  // Gather variable-length byte blocks from every rank; out = concatenated in
  // rank order. block_bytes[r] gives each rank's contribution size.
  Status Allgatherv(const void* in, int64_t in_bytes,
                    const std::vector<int64_t>& block_bytes,
                    std::vector<uint8_t>* out);

  Status Broadcast(void* data, int64_t bytes, int root);

  // Pairwise alltoallv: send_bytes[r] from my buffer to rank r (contiguous,
  // in rank order); recv_bytes[r] received from rank r into out (rank order).
  Status Alltoallv(const void* in, const std::vector<int64_t>& send_bytes,
                   const std::vector<int64_t>& recv_bytes,
                   std::vector<uint8_t>* out);

  // Reduce then keep this rank's contiguous chunk (count must divide evenly;
  // validated by the coordinator before dispatch).
  Status ReduceScatter(const void* in, int64_t count, DataType dtype,
                       ReduceOp op, std::vector<uint8_t>* out);

  // In-place Adasum reduction (float32/float64): hypercube pairwise exchange
  // with the adaptive combine a*(1 - dot/2|a|^2) + b*(1 - dot/2|b|^2)
  // (reference: horovod/common/ops/adasum/adasum.h:38). Non-power-of-two
  // worlds fold extra ranks in by addition first, like the Python/XLA path.
  Status AdasumAllreduce(void* data, int64_t count, DataType dtype);

 private:
  Status SendRecv(int send_fd, const void* send_buf, int64_t send_bytes,
                  int recv_fd, void* recv_buf, int64_t recv_bytes);

  // Bandwidth path: ring reduce-scatter + allgather; each reduce-scatter
  // step streams the incoming chunk in segments so ReduceBuffer of segment
  // k overlaps the socket transfer of segment k+1 (socket_util
  // SendRecvSegmented).
  Status RingAllreduce(void* data, int64_t count, DataType dtype,
                       ReduceOp op);
  // Latency path: log2(p) full-message pairwise exchanges; non-power-of-two
  // worlds fold the extra ranks in by reduction first (like Adasum).
  Status RecursiveDoublingAllreduce(void* data, int64_t count, DataType dtype,
                                    ReduceOp op);
  // Binomial reduce-to-0 + binomial broadcast (reference fork's tree menu
  // entry; half the exchange volume of recursive doubling, twice the depth).
  Status TreeAllreduce(void* data, int64_t count, DataType dtype,
                       ReduceOp op);

  int rank_;
  int size_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::vector<int> fds_;  // per-peer connection; -1 for self

  AllreduceAlgo algo_ = AllreduceAlgo::AUTO;
  int64_t crossover_bytes_ = kDefaultAlgoCrossoverBytes;
  int64_t segment_bytes_ = kDefaultSegmentBytes;
  // Largest payload SendRecv may exchange inline (blocking send, then recv)
  // without a deadlock risk; measured against the mesh's socket buffer
  // sizes in Connect(). 0 (pre-Connect) = always use the concurrent path.
  int64_t inline_max_bytes_ = 0;
};

// dst[i] = dst[i] OP src[i], accumulating fp16/bf16 in float.
void ReduceBuffer(void* dst, const void* src, int64_t count, DataType dtype,
                  ReduceOp op);

// Half-precision conversions (reference: horovod/common/half.{h,cc}).
float HalfToFloatPublic(uint16_t h);
uint16_t FloatToHalfPublic(float f);
float Bf16ToFloatPublic(uint16_t h);
uint16_t FloatToBf16Public(float f);

}  // namespace hvdtpu
