#include "message.h"

namespace hvdtpu {

void SerializeRequest(const Request& r, Writer* w) {
  w->I32(r.rank);
  w->I32(static_cast<int32_t>(r.op_type));
  w->I32(static_cast<int32_t>(r.reduce_op));
  w->I32(static_cast<int32_t>(r.dtype));
  w->Str(r.name);
  w->VecI64(r.shape);
  w->F64(r.prescale);
  w->F64(r.postscale);
  w->I32(r.root_rank);
  w->VecI32(r.splits);
}

Request DeserializeRequest(Reader* r) {
  Request q;
  q.rank = r->I32();
  q.op_type = static_cast<OpType>(r->I32());
  q.reduce_op = static_cast<ReduceOp>(r->I32());
  q.dtype = static_cast<DataType>(r->I32());
  q.name = r->Str();
  q.shape = r->VecI64();
  q.prescale = r->F64();
  q.postscale = r->F64();
  q.root_rank = r->I32();
  q.splits = r->VecI32();
  return q;
}

void SerializeResponse(const Response& r, Writer* w) {
  w->I32(static_cast<int32_t>(r.type));
  w->I32(static_cast<int32_t>(r.op_type));
  w->I32(static_cast<int32_t>(r.reduce_op));
  w->I32(static_cast<int32_t>(r.dtype));
  w->Str(r.error_message);
  w->I64(static_cast<int64_t>(r.names.size()));
  for (size_t i = 0; i < r.names.size(); ++i) {
    w->Str(r.names[i]);
    w->VecI64(r.shapes[i]);
    w->F64(r.prescales[i]);
    w->F64(r.postscales[i]);
  }
  w->I32(r.root_rank);
  w->VecI32(r.all_splits);
  w->VecI64(r.first_dims);
  w->I32(r.last_joined_rank);
}

Response DeserializeResponse(Reader* r) {
  Response p;
  p.type = static_cast<ResponseType>(r->I32());
  p.op_type = static_cast<OpType>(r->I32());
  p.reduce_op = static_cast<ReduceOp>(r->I32());
  p.dtype = static_cast<DataType>(r->I32());
  p.error_message = r->Str();
  int64_t n = r->I64();
  for (int64_t i = 0; i < n; ++i) {
    p.names.push_back(r->Str());
    p.shapes.push_back(r->VecI64());
    p.prescales.push_back(r->F64());
    p.postscales.push_back(r->F64());
  }
  p.root_rank = r->I32();
  p.all_splits = r->VecI32();
  p.first_dims = r->VecI64();
  p.last_joined_rank = r->I32();
  return p;
}

}  // namespace hvdtpu
