// Native-layer unit tests (SURVEY.md §4: "There is no C++ unit test in the
// reference — the native core is tested only through the Python surface.
// Implication for the rebuild: add the missing native-layer unit tests.")
//
// Covers the pure components directly at the C++ boundary: wire
// serialization roundtrips + truncation safety, half-precision conversion,
// buffer reduction ops, and the Gaussian-process/Bayesian-optimizer math.
// Built and run by `make check` (tests/test_sanitizers.py-style integration
// lives in tests/test_native_features.py; this binary needs no Python).

#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <limits>
#include <string>
#include <vector>

#include "autotune.h"
#include "data_plane.h"
#include "message.h"

namespace hvdtpu {
namespace {

int failures = 0;

#define CHECK_TRUE(cond)                                                  \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++failures;                                                         \
    }                                                                     \
  } while (0)

void TestRequestRoundtrip() {
  Request q;
  q.rank = 3;
  q.op_type = OpType::ALLTOALL;
  q.reduce_op = ReduceOp::ADASUM;
  q.dtype = DataType::BFLOAT16;
  q.name = "layer/kernel";
  q.shape = {4, 0, 7};
  q.prescale = 0.25;
  q.postscale = 4.0;
  q.root_rank = 2;
  q.splits = {1, 0, 3};

  Writer w;
  SerializeRequest(q, &w);
  std::vector<uint8_t> buf = w.Take();
  Reader r(buf);
  Request out = DeserializeRequest(&r);
  CHECK_TRUE(r.ok());
  CHECK_TRUE(out.rank == q.rank);
  CHECK_TRUE(out.op_type == q.op_type);
  CHECK_TRUE(out.reduce_op == q.reduce_op);
  CHECK_TRUE(out.dtype == q.dtype);
  CHECK_TRUE(out.name == q.name);
  CHECK_TRUE(out.shape == q.shape);
  CHECK_TRUE(out.prescale == q.prescale);
  CHECK_TRUE(out.postscale == q.postscale);
  CHECK_TRUE(out.root_rank == q.root_rank);
  CHECK_TRUE(out.splits == q.splits);
}

void TestResponseRoundtrip() {
  Response p;
  p.type = ResponseType::ERROR;
  p.op_type = OpType::ALLGATHER;
  p.dtype = DataType::INT64;
  p.error_message = "shape mismatch";
  p.names = {"a", "b"};
  p.shapes = {{2, 3}, {5}};
  p.prescales = {1.0, 0.5};
  p.postscales = {2.0, 1.0};
  p.all_splits = {0, 1, 1, 0};
  p.first_dims = {2, 5};
  p.last_joined_rank = 1;

  Writer w;
  SerializeResponse(p, &w);
  std::vector<uint8_t> buf = w.Take();
  Reader r(buf);
  Response out = DeserializeResponse(&r);
  CHECK_TRUE(r.ok());
  CHECK_TRUE(out.type == p.type);
  CHECK_TRUE(out.error_message == p.error_message);
  CHECK_TRUE(out.names == p.names);
  CHECK_TRUE(out.shapes == p.shapes);
  CHECK_TRUE(out.all_splits == p.all_splits);
  CHECK_TRUE(out.first_dims == p.first_dims);
  CHECK_TRUE(out.last_joined_rank == p.last_joined_rank);
}

void TestReaderTruncationIsSafe() {
  // A frame cut mid-field must flip ok() without reading out of bounds or
  // throwing length_error on a garbage allocation size (message.h Len()).
  Request q;
  q.name = "tensor";
  q.shape = {1024, 1024};
  Writer w;
  SerializeRequest(q, &w);
  std::vector<uint8_t> buf = w.Take();
  for (size_t cut = 0; cut < buf.size(); cut += 3) {
    std::vector<uint8_t> truncated(buf.begin(), buf.begin() + cut);
    Reader r(truncated);
    (void)DeserializeRequest(&r);
    CHECK_TRUE(!r.ok());
  }
}

void TestHalfConversionRoundtrip() {
  const float cases[] = {0.0f, 1.0f, -1.0f, 0.5f, 65504.0f, 1e-4f, -3.25f};
  for (float f : cases) {
    float h = HalfToFloatPublic(FloatToHalfPublic(f));
    CHECK_TRUE(std::fabs(h - f) <= std::fabs(f) * 1e-3f + 1e-6f);
    float b = Bf16ToFloatPublic(FloatToBf16Public(f));
    CHECK_TRUE(std::fabs(b - f) <= std::fabs(f) * 8e-3f + 1e-6f);
  }
}

void TestHalfConversionSpecialValues() {
  const float inf = std::numeric_limits<float>::infinity();
  const float nan = std::numeric_limits<float>::quiet_NaN();
  // Infinities survive both formats with sign.
  CHECK_TRUE(std::isinf(HalfToFloatPublic(FloatToHalfPublic(inf))));
  float nh = HalfToFloatPublic(FloatToHalfPublic(-inf));
  CHECK_TRUE(std::isinf(nh) && nh < 0);
  CHECK_TRUE(std::isinf(Bf16ToFloatPublic(FloatToBf16Public(inf))));
  float nb = Bf16ToFloatPublic(FloatToBf16Public(-inf));
  CHECK_TRUE(std::isinf(nb) && nb < 0);
  // NaN stays NaN (pre-fix: fp16 silently produced inf; bf16's rounding
  // add carried 0x7fffffff into the sign bit, producing -0.0).
  CHECK_TRUE(std::isnan(HalfToFloatPublic(FloatToHalfPublic(nan))));
  CHECK_TRUE(std::isnan(Bf16ToFloatPublic(FloatToBf16Public(nan))));
  float all_ones_nan;
  uint32_t all_ones_bits = 0x7fffffffu;
  std::memcpy(&all_ones_nan, &all_ones_bits, sizeof(all_ones_nan));
  CHECK_TRUE(std::isnan(Bf16ToFloatPublic(FloatToBf16Public(all_ones_nan))));
  // Overflow saturates to inf (fp16 max normal is 65504).
  CHECK_TRUE(std::isinf(HalfToFloatPublic(FloatToHalfPublic(1e6f))));
  // Negative zero keeps its sign bit.
  CHECK_TRUE(std::signbit(HalfToFloatPublic(FloatToHalfPublic(-0.0f))));
  CHECK_TRUE(std::signbit(Bf16ToFloatPublic(FloatToBf16Public(-0.0f))));
  // fp16 subnormal range (min normal 6.1e-5) roundtrips approximately.
  float sub = 1e-5f;
  float back = HalfToFloatPublic(FloatToHalfPublic(sub));
  CHECK_TRUE(std::fabs(back - sub) < 1e-6f);
}

void TestReduceBufferOps() {
  float dst[4] = {1, 2, 3, 4};
  float src[4] = {4, 3, 2, 1};
  ReduceBuffer(dst, src, 4, DataType::FLOAT32, ReduceOp::SUM);
  CHECK_TRUE(dst[0] == 5 && dst[3] == 5);
  float dmin[2] = {1, 9};
  float smin[2] = {3, 2};
  ReduceBuffer(dmin, smin, 2, DataType::FLOAT32, ReduceOp::MIN);
  CHECK_TRUE(dmin[0] == 1 && dmin[1] == 2);
  int64_t dprod[2] = {2, -3};
  int64_t sprod[2] = {5, 7};
  ReduceBuffer(dprod, sprod, 2, DataType::INT64, ReduceOp::PRODUCT);
  CHECK_TRUE(dprod[0] == 10 && dprod[1] == -21);
  // bf16 accumulates through float (reference: half.cc custom MPI sum).
  uint16_t dbf[2] = {FloatToBf16Public(1.5f), FloatToBf16Public(-2.0f)};
  uint16_t sbf[2] = {FloatToBf16Public(0.5f), FloatToBf16Public(1.0f)};
  ReduceBuffer(dbf, sbf, 2, DataType::BFLOAT16, ReduceOp::SUM);
  CHECK_TRUE(std::fabs(Bf16ToFloatPublic(dbf[0]) - 2.0f) < 0.05f);
  CHECK_TRUE(std::fabs(Bf16ToFloatPublic(dbf[1]) - (-1.0f)) < 0.05f);
}

void TestGaussianProcessInterpolates() {
  GaussianProcess gp(/*noise=*/1e-6);
  std::vector<std::vector<double>> x = {{0.0}, {0.5}, {1.0}};
  std::vector<double> y = {1.0, 3.0, 2.0};
  gp.Fit(x, y);
  double mu, sigma;
  for (size_t i = 0; i < x.size(); ++i) {
    gp.Predict(x[i], &mu, &sigma);
    CHECK_TRUE(std::fabs(mu - y[i]) < 0.05);   // near-interpolation
    CHECK_TRUE(sigma < 0.2);                   // confident at data points
  }
  gp.Predict({0.25}, &mu, &sigma);
  CHECK_TRUE(mu > 1.0 && mu < 3.2);            // between neighbors
}

void TestBayesianOptimizerPicksBestSample() {
  BayesianOptimizer opt(/*dim=*/2, /*noise=*/1e-4);
  opt.AddSample({0.1, 0.1}, 1.0);
  opt.AddSample({0.9, 0.2}, 5.0);
  opt.AddSample({0.4, 0.8}, 3.0);
  std::vector<double> best = opt.BestSample();
  CHECK_TRUE(best[0] == 0.9 && best[1] == 0.2);
  std::vector<double> next = opt.NextSample();
  CHECK_TRUE(next.size() == 2);
  for (double v : next) CHECK_TRUE(v >= 0.0 && v <= 1.0);
}

void TestParameterManagerFreezesAtBest() {
  ParameterManager pm;
  pm.Initialize(/*cycle=*/1.0, /*fusion=*/64 << 20, /*cache=*/true,
                /*log=*/"", /*warmup=*/1, /*cycles_per_sample=*/1,
                /*max_samples=*/4, /*gp_noise=*/0.1);
  CHECK_TRUE(pm.active());
  double t = 0.0;
  // Drive synthetic traffic until tuning freezes (warmup 1 sample +
  // 4 scored samples x 3 median scores each).
  bool changed_at_least_once = false;
  for (int i = 0; i < 64; ++i) {
    t += 0.01;
    changed_at_least_once |= pm.Update(/*bytes=*/1 << 20, t);
  }
  CHECK_TRUE(changed_at_least_once);
  ParameterManager::Params p = pm.Current();
  CHECK_TRUE(p.cycle_time_ms >= 0.5 && p.cycle_time_ms <= 50.0);
  CHECK_TRUE(p.fusion_threshold >= (1 << 20));
}

}  // namespace
}  // namespace hvdtpu

int main() {
  using namespace hvdtpu;
  TestRequestRoundtrip();
  TestResponseRoundtrip();
  TestReaderTruncationIsSafe();
  TestHalfConversionRoundtrip();
  TestHalfConversionSpecialValues();
  TestReduceBufferOps();
  TestGaussianProcessInterpolates();
  TestBayesianOptimizerPicksBestSample();
  TestParameterManagerFreezesAtBest();
  if (failures == 0) {
    std::printf("native unit tests: ALL OK\n");
    return 0;
  }
  std::fprintf(stderr, "native unit tests: %d failure(s)\n", failures);
  return 1;
}
