// Native-layer unit tests (SURVEY.md §4: "There is no C++ unit test in the
// reference — the native core is tested only through the Python surface.
// Implication for the rebuild: add the missing native-layer unit tests.")
//
// Covers the pure components directly at the C++ boundary: wire
// serialization roundtrips + truncation safety, half-precision conversion,
// buffer reduction ops, and the Gaussian-process/Bayesian-optimizer math.
// Built and run by `make check` (tests/test_sanitizers.py-style integration
// lives in tests/test_native_features.py; this binary needs no Python).

#include <signal.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cassert>
#include <cerrno>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <limits>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>

#include <cstdlib>

#include "autotune.h"
#include "compressed.h"
#include "data_plane.h"
#include "flightrec.h"
#include "message.h"
#include "metrics.h"
#include "perfstats.h"
#include "profiler.h"
#include "shm_transport.h"
#include "socket_util.h"
#include "timeline.h"
#include "tracing.h"

namespace hvdtpu {
namespace {

int failures = 0;

#define CHECK_TRUE(cond)                                                  \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++failures;                                                         \
    }                                                                     \
  } while (0)

void TestRequestRoundtrip() {
  Request q;
  q.rank = 3;
  q.op_type = OpType::ALLTOALL;
  q.reduce_op = ReduceOp::ADASUM;
  q.dtype = DataType::BFLOAT16;
  q.name = "layer/kernel";
  q.shape = {4, 0, 7};
  q.prescale = 0.25;
  q.postscale = 4.0;
  q.root_rank = 2;
  q.splits = {1, 0, 3};

  Writer w;
  SerializeRequest(q, &w);
  std::vector<uint8_t> buf = w.Take();
  Reader r(buf);
  Request out = DeserializeRequest(&r);
  CHECK_TRUE(r.ok());
  CHECK_TRUE(out.rank == q.rank);
  CHECK_TRUE(out.op_type == q.op_type);
  CHECK_TRUE(out.reduce_op == q.reduce_op);
  CHECK_TRUE(out.dtype == q.dtype);
  CHECK_TRUE(out.name == q.name);
  CHECK_TRUE(out.shape == q.shape);
  CHECK_TRUE(out.prescale == q.prescale);
  CHECK_TRUE(out.postscale == q.postscale);
  CHECK_TRUE(out.root_rank == q.root_rank);
  CHECK_TRUE(out.splits == q.splits);
}

void TestResponseRoundtrip() {
  Response p;
  p.type = ResponseType::ERROR;
  p.op_type = OpType::ALLGATHER;
  p.dtype = DataType::INT64;
  p.error_message = "shape mismatch";
  p.names = {"a", "b"};
  p.shapes = {{2, 3}, {5}};
  p.prescales = {1.0, 0.5};
  p.postscales = {2.0, 1.0};
  p.all_splits = {0, 1, 1, 0};
  p.first_dims = {2, 5};
  p.last_joined_rank = 1;

  Writer w;
  SerializeResponse(p, &w);
  std::vector<uint8_t> buf = w.Take();
  Reader r(buf);
  Response out = DeserializeResponse(&r);
  CHECK_TRUE(r.ok());
  CHECK_TRUE(out.type == p.type);
  CHECK_TRUE(out.error_message == p.error_message);
  CHECK_TRUE(out.names == p.names);
  CHECK_TRUE(out.shapes == p.shapes);
  CHECK_TRUE(out.all_splits == p.all_splits);
  CHECK_TRUE(out.first_dims == p.first_dims);
  CHECK_TRUE(out.last_joined_rank == p.last_joined_rank);
}

void TestReaderTruncationIsSafe() {
  // A frame cut mid-field must flip ok() without reading out of bounds or
  // throwing length_error on a garbage allocation size (message.h Len()).
  Request q;
  q.name = "tensor";
  q.shape = {1024, 1024};
  Writer w;
  SerializeRequest(q, &w);
  std::vector<uint8_t> buf = w.Take();
  for (size_t cut = 0; cut < buf.size(); cut += 3) {
    std::vector<uint8_t> truncated(buf.begin(), buf.begin() + cut);
    Reader r(truncated);
    (void)DeserializeRequest(&r);
    CHECK_TRUE(!r.ok());
  }
}

void TestHalfConversionRoundtrip() {
  const float cases[] = {0.0f, 1.0f, -1.0f, 0.5f, 65504.0f, 1e-4f, -3.25f};
  for (float f : cases) {
    float h = HalfToFloatPublic(FloatToHalfPublic(f));
    CHECK_TRUE(std::fabs(h - f) <= std::fabs(f) * 1e-3f + 1e-6f);
    float b = Bf16ToFloatPublic(FloatToBf16Public(f));
    CHECK_TRUE(std::fabs(b - f) <= std::fabs(f) * 8e-3f + 1e-6f);
  }
}

void TestHalfConversionSpecialValues() {
  const float inf = std::numeric_limits<float>::infinity();
  const float nan = std::numeric_limits<float>::quiet_NaN();
  // Infinities survive both formats with sign.
  CHECK_TRUE(std::isinf(HalfToFloatPublic(FloatToHalfPublic(inf))));
  float nh = HalfToFloatPublic(FloatToHalfPublic(-inf));
  CHECK_TRUE(std::isinf(nh) && nh < 0);
  CHECK_TRUE(std::isinf(Bf16ToFloatPublic(FloatToBf16Public(inf))));
  float nb = Bf16ToFloatPublic(FloatToBf16Public(-inf));
  CHECK_TRUE(std::isinf(nb) && nb < 0);
  // NaN stays NaN (pre-fix: fp16 silently produced inf; bf16's rounding
  // add carried 0x7fffffff into the sign bit, producing -0.0).
  CHECK_TRUE(std::isnan(HalfToFloatPublic(FloatToHalfPublic(nan))));
  CHECK_TRUE(std::isnan(Bf16ToFloatPublic(FloatToBf16Public(nan))));
  float all_ones_nan;
  uint32_t all_ones_bits = 0x7fffffffu;
  std::memcpy(&all_ones_nan, &all_ones_bits, sizeof(all_ones_nan));
  CHECK_TRUE(std::isnan(Bf16ToFloatPublic(FloatToBf16Public(all_ones_nan))));
  // Overflow saturates to inf (fp16 max normal is 65504).
  CHECK_TRUE(std::isinf(HalfToFloatPublic(FloatToHalfPublic(1e6f))));
  // Negative zero keeps its sign bit.
  CHECK_TRUE(std::signbit(HalfToFloatPublic(FloatToHalfPublic(-0.0f))));
  CHECK_TRUE(std::signbit(Bf16ToFloatPublic(FloatToBf16Public(-0.0f))));
  // fp16 subnormal range (min normal 6.1e-5) roundtrips approximately.
  float sub = 1e-5f;
  float back = HalfToFloatPublic(FloatToHalfPublic(sub));
  CHECK_TRUE(std::fabs(back - sub) < 1e-6f);
}

void TestHalfConversionExhaustive() {
  // Every one of the 65536 fp16 bit patterns must survive a float round
  // trip bit-exactly (NaNs must stay NaN; payloads may be canonicalized).
  for (uint32_t u = 0; u < 0x10000u; ++u) {
    uint16_t h = static_cast<uint16_t>(u);
    bool is_nan = (h & 0x7c00u) == 0x7c00u && (h & 0x3ffu) != 0;
    float f = HalfToFloatPublic(h);
    uint16_t back = FloatToHalfPublic(f);
    if (is_nan) {
      if (!((back & 0x7c00u) == 0x7c00u && (back & 0x3ffu) != 0)) {
        std::fprintf(stderr, "FAIL fp16 NaN roundtrip: %04x -> %04x\n", h,
                     back);
        ++failures;
      }
    } else if (back != h) {
      std::fprintf(stderr, "FAIL fp16 roundtrip: %04x -> %g -> %04x\n", h, f,
                   back);
      ++failures;
      return;  // don't spam 65k lines
    }
  }
  // Same for bfloat16 (every pattern is an exact float truncation).
  for (uint32_t u = 0; u < 0x10000u; ++u) {
    uint16_t h = static_cast<uint16_t>(u);
    bool is_nan = (h & 0x7f80u) == 0x7f80u && (h & 0x7fu) != 0;
    float f = Bf16ToFloatPublic(h);
    uint16_t back = FloatToBf16Public(f);
    if (is_nan) {
      if (!((back & 0x7f80u) == 0x7f80u && (back & 0x7fu) != 0)) {
        std::fprintf(stderr, "FAIL bf16 NaN roundtrip: %04x -> %04x\n", h,
                     back);
        ++failures;
      }
    } else if (back != h) {
      std::fprintf(stderr, "FAIL bf16 roundtrip: %04x -> %g -> %04x\n", h, f,
                   back);
      ++failures;
      return;
    }
  }
}

void TestHalfRoundToNearestEven() {
  // Subnormal ties round to even, not up (the seed's round-half-up bug):
  // 2^-25 is exactly halfway between 0 and the smallest subnormal 2^-24.
  CHECK_TRUE(FloatToHalfPublic(std::ldexp(1.0f, -25)) == 0x0000);
  CHECK_TRUE(FloatToHalfPublic(-std::ldexp(1.0f, -25)) == 0x8000);
  // Just above the tie rounds away from zero.
  CHECK_TRUE(FloatToHalfPublic(std::nextafterf(std::ldexp(1.0f, -25), 1.0f)) ==
             0x0001);
  // 3 * 2^-25 (halfway between subnormals 1 and 2) rounds to even (2).
  CHECK_TRUE(FloatToHalfPublic(3.0f * std::ldexp(1.0f, -25)) == 0x0002);
  // Normal-path tie: 1 + 2^-11 is halfway between 1.0 and 1 + 2^-10;
  // round-to-even keeps 1.0 (mantissa 0 is even).
  CHECK_TRUE(FloatToHalfPublic(1.0f + std::ldexp(1.0f, -11)) == 0x3c00);
  // 1 + 3*2^-11 is halfway between 1+2^-10 and 1+2^-9 -> even (1+2^-9).
  CHECK_TRUE(FloatToHalfPublic(1.0f + 3.0f * std::ldexp(1.0f, -11)) == 0x3c02);
  // Overflow rounding: 65520 (tie between 65504 and out-of-range 65536)
  // rounds up to infinity; just below stays at the max normal.
  CHECK_TRUE(FloatToHalfPublic(65520.0f) == 0x7c00);
  CHECK_TRUE(FloatToHalfPublic(std::nextafterf(65520.0f, 0.0f)) == 0x7bff);
}

void TestReduceBufferHalfMatchesScalar() {
  // The fp16/bf16 SUM kernels take a SIMD path when the CPU supports it;
  // verify bit-exact agreement with the scalar convert-combine-convert
  // reference over every finite fp16 value (paired with a fixed addend) and
  // that NaN inputs still propagate.
  const int64_t n = 0x10000;
  std::vector<uint16_t> dst(n), src(n), expect(n);
  for (int64_t i = 0; i < n; ++i) {
    uint16_t h = static_cast<uint16_t>(i);
    bool is_nan = (h & 0x7c00u) == 0x7c00u && (h & 0x3ffu) != 0;
    dst[i] = h;
    src[i] = FloatToHalfPublic(0.37109375f);  // exact in fp16
    expect[i] = is_nan ? 0xffffu  // placeholder: checked via isnan below
                       : FloatToHalfPublic(HalfToFloatPublic(dst[i]) +
                                           HalfToFloatPublic(src[i]));
  }
  ReduceBuffer(dst.data(), src.data(), n, DataType::FLOAT16, ReduceOp::SUM);
  for (int64_t i = 0; i < n; ++i) {
    uint16_t h = static_cast<uint16_t>(i);
    bool is_nan = (h & 0x7c00u) == 0x7c00u && (h & 0x3ffu) != 0;
    if (is_nan) {
      if (!((dst[i] & 0x7c00u) == 0x7c00u && (dst[i] & 0x3ffu) != 0)) {
        std::fprintf(stderr, "FAIL fp16 sum NaN propagation at %04x -> %04x\n",
                     h, dst[i]);
        ++failures;
      }
    } else if (dst[i] != expect[i]) {
      std::fprintf(stderr, "FAIL fp16 sum kernel mismatch at %04x: %04x vs "
                   "scalar %04x\n", h, dst[i], expect[i]);
      ++failures;
      return;
    }
  }
  // bf16: same sweep.
  for (int64_t i = 0; i < n; ++i) {
    uint16_t h = static_cast<uint16_t>(i);
    bool is_nan = (h & 0x7f80u) == 0x7f80u && (h & 0x7fu) != 0;
    dst[i] = h;
    src[i] = FloatToBf16Public(1.5f);
    expect[i] = is_nan ? 0xffffu
                       : FloatToBf16Public(Bf16ToFloatPublic(dst[i]) +
                                           Bf16ToFloatPublic(src[i]));
  }
  ReduceBuffer(dst.data(), src.data(), n, DataType::BFLOAT16, ReduceOp::SUM);
  for (int64_t i = 0; i < n; ++i) {
    uint16_t h = static_cast<uint16_t>(i);
    bool is_nan = (h & 0x7f80u) == 0x7f80u && (h & 0x7fu) != 0;
    if (is_nan) {
      if (!((dst[i] & 0x7f80u) == 0x7f80u && (dst[i] & 0x7fu) != 0)) {
        std::fprintf(stderr, "FAIL bf16 sum NaN propagation at %04x -> %04x\n",
                     h, dst[i]);
        ++failures;
      }
    } else if (dst[i] != expect[i]) {
      std::fprintf(stderr, "FAIL bf16 sum kernel mismatch at %04x: %04x vs "
                   "scalar %04x\n", h, dst[i], expect[i]);
      ++failures;
      return;
    }
  }
}

void TestSendRecvSegmented() {
  // Full-duplex segmented transfer over a socketpair: side A uses the
  // segmented path with an on-the-fly reduction callback, side B a plain
  // concurrent send+recv. Odd segment size exercises the short tail.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  const size_t kBytes = 1 << 20;
  std::vector<uint8_t> a_send(kBytes), a_recv(kBytes), b_send(kBytes),
      b_recv(kBytes);
  for (size_t i = 0; i < kBytes; ++i) {
    a_send[i] = static_cast<uint8_t>(i * 7);
    b_send[i] = static_cast<uint8_t>(i * 13 + 1);
  }
  std::atomic<int> b_rc{-1};
  std::thread side_b([&] {
    int send_rc = 0;
    std::thread sender([&] {
      send_rc = SendAll(sv[1], b_send.data(), kBytes);
    });
    int recv_rc = RecvAll(sv[1], b_recv.data(), kBytes);
    sender.join();
    b_rc = (send_rc == 0 && recv_rc == 0) ? 0 : 1;
  });
  size_t callback_bytes = 0;
  size_t calls = 0;
  int rc = SendRecvSegmented(
      sv[0], a_send.data(), kBytes, sv[0], a_recv.data(), kBytes,
      /*segment_bytes=*/100000,
      [&](const uint8_t* data, size_t off, size_t len) {
        // Segments arrive in order, disjoint, and fully landed; the view
        // pointer is buf-backed on the socket path.
        CHECK_TRUE(off == callback_bytes);
        CHECK_TRUE(data == a_recv.data() + off);
        for (size_t i = 0; i < len; i += 9973) {
          CHECK_TRUE(data[i] == static_cast<uint8_t>((off + i) * 13 + 1));
        }
        callback_bytes += len;
        ++calls;
      });
  side_b.join();
  CHECK_TRUE(rc == 0);
  CHECK_TRUE(b_rc == 0);
  CHECK_TRUE(callback_bytes == kBytes);
  // calls is scheduling-dependent (1 if the receiver outran the consumer,
  // up to 11 with no coalescing) — only its lower bound is guaranteed.
  CHECK_TRUE(calls >= 1);
  CHECK_TRUE(b_recv == a_send);
  close(sv[0]);
  close(sv[1]);
}

// --- shm transport unit tests ----------------------------------------------
// The rings are plain MAP_SHARED memory, so two transports in one process
// (threads) exercise exactly the cross-process protocol — and TSan/ASan see
// every access (make check-tsan / check-asan).

void TestShmRingWraparoundWithBatch(int64_t doorbell_batch) {
  // Push far more than the ring capacity through in odd-sized pieces so the
  // cursors wrap the ring many times mid-message; verify every byte. Runs
  // under both doorbell protocols: 1 = legacy wake-per-advance, other =
  // coalesced batching (the default).
  const std::string name = "/hvdtpu_test_wrap_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, /*ring_bytes=*/4096);
  CHECK_TRUE(a != nullptr);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  a->set_doorbell_batch(doorbell_batch);
  b->set_doorbell_batch(doorbell_batch);
  CHECK_TRUE(a->ring_bytes() == 4096 && b->ring_bytes() == 4096);
  const size_t kBytes = 1 << 20;  // 256 ring-fulls
  std::vector<uint8_t> sent(kBytes), got(kBytes, 0);
  for (size_t i = 0; i < kBytes; ++i) {
    sent[i] = static_cast<uint8_t>(i * 31 + 7);
  }
  std::atomic<int> send_rc{-1};
  std::thread producer([&] {
    // Odd chunk size: pieces straddle the ring boundary continually.
    size_t off = 0;
    int rc = 0;
    while (off < kBytes && rc == 0) {
      size_t n = std::min<size_t>(4097, kBytes - off);
      rc = a->Send(sent.data() + off, n);
      off += n;
    }
    send_rc = rc;
  });
  size_t calls = 0, cb_bytes = 0;
  // Zero-copy views: the payload is delivered THROUGH the callback (in-ring
  // pointers; `got` stays scratch) — copy it out here to verify every byte.
  int rc = b->RecvSegmented(got.data(), kBytes, 100000, /*view_align=*/1,
                            [&](const uint8_t* data, size_t off, size_t len) {
                              CHECK_TRUE(off == cb_bytes);
                              memcpy(got.data() + off, data, len);
                              cb_bytes += len;
                              ++calls;
                            });
  producer.join();
  CHECK_TRUE(rc == 0 && send_rc == 0);
  CHECK_TRUE(cb_bytes == kBytes && calls >= 11);
  CHECK_TRUE(got == sent);
  // Full-duplex interleaved pump: both sides exchange > ring capacity.
  std::vector<uint8_t> b2a(64 * 1024), a_got(64 * 1024);
  for (size_t i = 0; i < b2a.size(); ++i) b2a[i] = static_cast<uint8_t>(i * 13);
  std::atomic<int> b_rc{-1};
  std::thread side_b([&] {
    b_rc = b->SendRecv(b2a.data(), b2a.size(), got.data(), kBytes, 0, 1,
                       nullptr);
  });
  rc = a->SendRecv(sent.data(), kBytes, a_got.data(), a_got.size(), 0, 1,
                   nullptr);
  side_b.join();
  CHECK_TRUE(rc == 0 && b_rc == 0);
  CHECK_TRUE(got == sent && a_got == b2a);
}

void TestShmRingWraparound() {
  TestShmRingWraparoundWithBatch(1);  // legacy: doorbell per cursor advance
  TestShmRingWraparoundWithBatch(0);  // 0 -> default coalescing window
}

void TestShmDoorbellBatchingCoalesces() {
  // Deterministic single-threaded fill/drain: with no waiter registered and
  // the batch window larger than the traffic, NO futex syscalls may happen
  // — the whole point of coalescing is that a running peer costs zero
  // doorbells. (The edge-wake latency contract is covered by
  // TestShmDoorbellWakeup, which runs under the default batching.)
  const std::string name = "/hvdtpu_test_batch_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, 4096);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(a != nullptr && b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  // Window 2048 < op 4096: the adaptive gate engages coalescing (an op
  // smaller than the window would keep the legacy per-advance protocol).
  a->set_doorbell_batch(2048);
  b->set_doorbell_batch(2048);
  std::vector<uint8_t> buf(4096), sink(4096);
  for (size_t i = 0; i < buf.size(); ++i) buf[i] = static_cast<uint8_t>(i);
  CHECK_TRUE(a->Send(buf.data(), buf.size()) == 0);   // exactly one ring-full
  CHECK_TRUE(b->Recv(sink.data(), sink.size()) == 0);
  CHECK_TRUE(sink == buf);
  CHECK_TRUE(a->futex_wakes() == 0);
  CHECK_TRUE(b->futex_wakes() == 0);
  // Same traffic under the legacy protocol still works (and may wake).
  a->set_doorbell_batch(1);
  b->set_doorbell_batch(1);
  CHECK_TRUE(a->Send(buf.data(), buf.size()) == 0);
  CHECK_TRUE(b->Recv(sink.data(), sink.size()) == 0);
  CHECK_TRUE(sink == buf);
}

void TestShmInPlaceViewsAlignedAcrossWrap() {
  // The zero-copy view consumer: payloads are handed out as in-ring views,
  // elem-aligned in op space even when the ring's wrap point slices an
  // element (the 3-byte prologue skews every later wrap mid-element, so the
  // staging path runs on every lap of the 64-byte ring).
  const std::string name = "/hvdtpu_test_views_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, 64);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(a != nullptr && b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  uint8_t skew[3] = {9, 9, 9}, skew_got[3] = {0, 0, 0};
  std::thread pre([&] { CHECK_TRUE(a->Send(skew, 3) == 0); });
  CHECK_TRUE(b->Recv(skew_got, 3) == 0);
  pre.join();
  const size_t kWords = 1024;
  std::vector<uint32_t> sent(kWords), got(kWords, 0);
  for (size_t i = 0; i < kWords; ++i) {
    sent[i] = static_cast<uint32_t>(i * 2654435761u);
  }
  std::atomic<int> send_rc{-1};
  std::thread producer([&] {
    // Odd-size pieces so element bytes trickle in across view attempts.
    const uint8_t* p = reinterpret_cast<const uint8_t*>(sent.data());
    size_t off = 0, total = kWords * 4;
    int rc = 0;
    while (off < total && rc == 0) {
      size_t n = std::min<size_t>(37, total - off);
      rc = a->Send(p + off, n);
      off += n;
    }
    send_rc = rc;
  });
  size_t cb_bytes = 0;
  bool aligned_ok = true;
  std::vector<uint8_t> scratch(kWords * 4);  // untouched by the views
  int rc = b->RecvSegmented(
      scratch.data(), kWords * 4, 0, /*view_align=*/4,
      [&](const uint8_t* data, size_t off, size_t len) {
        aligned_ok = aligned_ok && off % 4 == 0 && len % 4 == 0;
        memcpy(reinterpret_cast<uint8_t*>(got.data()) + off, data, len);
        cb_bytes += len;
      });
  producer.join();
  CHECK_TRUE(rc == 0 && send_rc == 0);
  CHECK_TRUE(aligned_ok);
  CHECK_TRUE(cb_bytes == kWords * 4);
  CHECK_TRUE(got == sent);
}

void TestShmViewsNeverMisaligned() {
  // An odd-sized prologue knocks the ring cursor off the 8-byte grid; the
  // consumer must then hand out ALIGNED view pointers anyway (the bounce
  // path) — a typed fp64 reducer reading a misaligned view is UB that the
  // UBSan gate aborts on (caught there first, pinned here).
  const std::string name = "/hvdtpu_test_align_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, 4096);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(a != nullptr && b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  uint8_t skew = 0x5a, skew_got = 0;
  std::thread pre([&] { CHECK_TRUE(a->Send(&skew, 1) == 0); });
  CHECK_TRUE(b->Recv(&skew_got, 1) == 0);
  pre.join();
  const size_t kDoubles = 4096;  // 32 KB: many ring laps, all misaligned
  std::vector<double> sent(kDoubles), got(kDoubles, 0);
  for (size_t i = 0; i < kDoubles; ++i) sent[i] = 0.5 * (i + 1);
  std::atomic<int> send_rc{-1};
  std::thread producer(
      [&] { send_rc = a->Send(sent.data(), kDoubles * 8); });
  bool aligned_ok = true;
  size_t cb = 0;
  std::vector<uint8_t> scratch(kDoubles * 8);
  int rc = b->RecvSegmented(
      scratch.data(), kDoubles * 8, 0, /*view_align=*/8,
      [&](const uint8_t* data, size_t off, size_t len) {
        aligned_ok = aligned_ok &&
                     reinterpret_cast<uintptr_t>(data) % 8 == 0 &&
                     off % 8 == 0 && len % 8 == 0;
        // Read THROUGH the typed lens the reducers use.
        const double* d = reinterpret_cast<const double*>(data);
        for (size_t i = 0; i < len / 8; ++i) {
          reinterpret_cast<double*>(got.data())[(off / 8) + i] = d[i];
        }
        cb += len;
      });
  producer.join();
  CHECK_TRUE(rc == 0 && send_rc == 0);
  CHECK_TRUE(aligned_ok);
  CHECK_TRUE(cb == kDoubles * 8);
  CHECK_TRUE(got == sent);
}

void TestNumaProbeAndPolicy() {
  // Sysfs probe fixtures: node<digits> entries count as nodes; an absent
  // directory reads as single-node (probed no-op everywhere downstream).
  char tmpl[] = "/tmp/hvdtpu_numa_XXXXXX";
  char* dir = mkdtemp(tmpl);
  CHECK_TRUE(dir != nullptr);
  if (dir == nullptr) return;
  std::string d(dir);
  CHECK_TRUE(NumaNodeCount(d + "/missing") == 1);
  CHECK_TRUE(mkdir((d + "/node0").c_str(), 0700) == 0);
  CHECK_TRUE(NumaNodeCount(d) == 1);
  CHECK_TRUE(mkdir((d + "/node1").c_str(), 0700) == 0);
  CHECK_TRUE(mkdir((d + "/nodeX").c_str(), 0700) == 0);  // not a node
  CHECK_TRUE(NumaNodeCount(d) == 2);
  rmdir((d + "/node0").c_str());
  rmdir((d + "/node1").c_str());
  rmdir((d + "/nodeX").c_str());
  rmdir(d.c_str());
  // Policy application on a live segment: OFF is always a no-op; AUTO/ON
  // must degrade cleanly (single-node host, missing syscall) and never
  // break the rings — traffic still flows after.
  const std::string name = "/hvdtpu_test_numa_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, 4096);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(a != nullptr && b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  CHECK_TRUE(!a->ApplyNumaPolicy(ShmNumaMode::OFF));
  a->ApplyNumaPolicy(ShmNumaMode::AUTO);  // no-crash; result is host-shaped
  b->ApplyNumaPolicy(ShmNumaMode::ON);
  uint64_t v = 0xfeedface, got = 0;
  std::thread s([&] { CHECK_TRUE(a->Send(&v, sizeof(v)) == 0); });
  CHECK_TRUE(b->Recv(&got, sizeof(got)) == 0);
  s.join();
  CHECK_TRUE(got == v);
}

void TestShmDoorbellWakeup() {
  // Consumer blocks on an empty ring (past the spin phase, into the futex
  // wait); a producer that shows up much later must still get through.
  const std::string name = "/hvdtpu_test_bell_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, 4096);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(a != nullptr && b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  uint32_t got = 0;
  std::atomic<int> recv_rc{-1};
  std::thread consumer([&] { recv_rc = b->Recv(&got, sizeof(got)); });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  uint32_t val = 0xdeadbeef;
  CHECK_TRUE(a->Send(&val, sizeof(val)) == 0);
  consumer.join();
  CHECK_TRUE(recv_rc == 0 && got == 0xdeadbeef);
  // And the reverse doorbell: a producer blocked on a FULL ring wakes when
  // the consumer drains.
  std::vector<uint8_t> big(8192, 0x5a), sink(8192, 0);
  std::atomic<int> send_rc{-1};
  std::thread producer([&] { send_rc = a->Send(big.data(), big.size()); });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  CHECK_TRUE(b->Recv(sink.data(), sink.size()) == 0);
  producer.join();
  CHECK_TRUE(send_rc == 0);
  CHECK_TRUE(sink == big);
}

void TestShmAbortCleanup() {
  // Abort wakes a blocked peer with an error instead of hanging it, and
  // teardown removes the name from the shm namespace (nothing leaks).
  const std::string name = "/hvdtpu_test_abort_" + std::to_string(getpid());
  {
    auto a = ShmTransport::Create(name, 4096);
    auto b = ShmTransport::Open(name, 2000);
    CHECK_TRUE(a != nullptr && b != nullptr);
    if (a == nullptr || b == nullptr) return;
    uint8_t byte;
    std::atomic<int> recv_rc{0};
    std::thread consumer([&] { recv_rc = b->Recv(&byte, 1); });
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    a->Abort();
    consumer.join();
    CHECK_TRUE(recv_rc == -1);          // blocked op fails over
    CHECK_TRUE(a->Send(&byte, 1) == -1);  // post-abort ops fail fast
    // Destructors: opener unmaps, creator unlinks.
  }
  int fd = shm_open(name.c_str(), O_RDWR, 0600);
  CHECK_TRUE(fd < 0 && errno == ENOENT);
  if (fd >= 0) {
    close(fd);
    shm_unlink(name.c_str());
  }
}

void TestShmKilledPeerWakesWaiter() {
  // The killed-peer fixture (docs/fault-tolerance.md): a SIGKILLed peer can
  // never flip the shared abort flag, so a blocked ring op must be woken by
  // the liveness probe — the (otherwise idle) pair socket EOFs when the
  // peer process dies, checked every wait slice. Fork a REAL peer process,
  // kill it -9 mid-wait, and require the waiter to fail over within a few
  // slices instead of hanging until teardown.
  const std::string name = "/hvdtpu_test_kill_" + std::to_string(getpid());
  int live[2];  // stands in for the pair's TCP socket (liveness probe)
  int sync[2];  // child -> parent "attached" signal, NOT the liveness lane
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, live) == 0);
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sync) == 0);
  auto a = ShmTransport::Create(name, 4096);
  CHECK_TRUE(a != nullptr);
  if (a == nullptr) return;
  pid_t child = fork();
  if (child == 0) {
    // Child: attach as the peer, confirm, then wedge until SIGKILLed. No
    // CHECKs here — the parent judges us by waitpid status. Keep live[1]
    // OPEN: its kernel-side close at SIGKILL is the death signal.
    close(live[0]);
    close(sync[0]);
    auto b = ShmTransport::Open(name, 2000);
    if (b == nullptr) _exit(1);
    char ok = 'k';
    if (write(sync[1], &ok, 1) != 1) _exit(2);
    for (;;) pause();
  }
  close(live[1]);
  close(sync[1]);
  CHECK_TRUE(child > 0);
  char attached = 0;
  CHECK_TRUE(read(sync[0], &attached, 1) == 1 && attached == 'k');
  close(sync[0]);
  // Small detect slice via the shared control block, like the data plane.
  IoControl ctl;
  ctl.detect_slice_ms = 50;
  a->set_liveness_fd(live[0]);
  a->set_control(&ctl);
  uint8_t byte;
  std::atomic<int> recv_rc{0};
  std::thread consumer([&] { recv_rc = a->Recv(&byte, 1); });
  // Let the waiter pass the spin phase into the sliced futex wait.
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  auto t0 = std::chrono::steady_clock::now();
  CHECK_TRUE(kill(child, SIGKILL) == 0);
  consumer.join();
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  CHECK_TRUE(recv_rc == -1);  // woke with an error, did not hang
  // "Within one timeout slice" + generous CI scheduling slack.
  CHECK_TRUE(waited < 2.0);
  // Peer death must break the WHOLE plane, not just this lane.
  CHECK_TRUE(ctl.peer_failed.load() != 0 && ctl.is_aborted());
  int status = 0;
  CHECK_TRUE(waitpid(child, &status, 0) == child);
  CHECK_TRUE(WIFSIGNALED(status) && WTERMSIG(status) == SIGKILL);
  close(live[0]);
}

// --- interruptible socket I/O (IoControl) -----------------------------------

void TestIoControlRecvFailsFastOnPeerClose() {
  // A controlled RecvAll against a peer that closes mid-wait fails within a
  // poll slice (EOF/POLLHUP), marking the whole plane failed.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  std::atomic<int> rc{0};
  std::thread reader([&] {
    uint8_t buf[16];
    rc = RecvAll(sv[0], buf, sizeof(buf), &ctl);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  auto t0 = std::chrono::steady_clock::now();
  close(sv[1]);
  reader.join();
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  CHECK_TRUE(rc == -1);
  CHECK_TRUE(waited < 2.0);
  CHECK_TRUE(ctl.peer_failed.load() != 0 && ctl.is_aborted());
  close(sv[0]);
}

void TestIoControlAbortBreaksBlockedRecv() {
  // A plane-wide abort (flag flip by ANY thread) breaks a blocked read
  // within one slice — this is how one lane's failure cascades to ops
  // blocked on perfectly healthy lanes.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  std::atomic<int> rc{0};
  std::thread reader([&] {
    uint8_t buf[4];
    rc = RecvAll(sv[0], buf, sizeof(buf), &ctl);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  ctl.aborted.store(1);
  reader.join();
  CHECK_TRUE(rc == -1);
  CHECK_TRUE(ctl.peer_failed.load() == 0);  // abort, not a peer verdict
  close(sv[0]);
  close(sv[1]);
}

void TestIoControlReadDeadlineTripsOnSilentPeer() {
  // An open-but-silent lane (hung peer / blackholed route: no bytes, no
  // EOF) trips the no-progress deadline instead of blocking forever.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  ctl.read_deadline_secs = 0.15;
  uint8_t buf[4];
  auto t0 = std::chrono::steady_clock::now();
  int rc = RecvAll(sv[0], buf, sizeof(buf), &ctl);
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  CHECK_TRUE(rc == -1);
  CHECK_TRUE(waited >= 0.15 && waited < 2.0);
  CHECK_TRUE(ctl.peer_failed.load() != 0);  // declared dead, plane broken
  close(sv[0]);
  close(sv[1]);
}

void TestShmReadDeadlineTripsOnSilentPeer() {
  // Same contract on the shm lane: a live segment whose ring never moves
  // past the deadline fails over (the peer is attached but wedged — only a
  // deadline can catch it; there is no EOF).
  const std::string name = "/hvdtpu_test_dl_" + std::to_string(getpid());
  auto a = ShmTransport::Create(name, 4096);
  auto b = ShmTransport::Open(name, 2000);
  CHECK_TRUE(a != nullptr && b != nullptr);
  if (a == nullptr || b == nullptr) return;
  a->Unlink();
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  ctl.read_deadline_secs = 0.15;
  a->set_control(&ctl);
  uint8_t byte;
  auto t0 = std::chrono::steady_clock::now();
  int rc = a->Recv(&byte, 1);  // b never sends
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  CHECK_TRUE(rc == -1);
  CHECK_TRUE(waited >= 0.15 && waited < 2.0);
  CHECK_TRUE(ctl.peer_failed.load() != 0 && ctl.is_aborted());
}

// --- zero-copy TCP lane -----------------------------------------------------

// Connected loopback TCP pair (real AF_INET sockets: the SO_ZEROCOPY probe
// needs a TCP socket — AF_UNIX pairs refuse it, which is itself a fixture).
bool MakeTcpPair(int* a, int* b) {
  int port = 0;
  int lfd = TcpListen(0, 1, &port);
  if (lfd < 0) return false;
  *a = TcpConnectRetry("127.0.0.1", port, 2000);
  *b = TcpAccept(lfd);
  CloseFd(lfd);
  if (*a < 0 || *b < 0) {
    CloseFd(*a);
    CloseFd(*b);
    return false;
  }
  return true;
}

void TestSendAllVecExactConcatenation() {
  // Vectored scatter-gather send: three iovecs (frame-header-sized + two
  // payload planes) must arrive as one exact byte stream, including under
  // an IoControl and partial-transfer advancing.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  uint64_t hdr = 0x1122334455667788ull;
  std::vector<uint8_t> p1(300000), p2(70001);
  for (size_t i = 0; i < p1.size(); ++i) p1[i] = static_cast<uint8_t>(i * 3);
  for (size_t i = 0; i < p2.size(); ++i) p2[i] = static_cast<uint8_t>(i * 5 + 1);
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  std::atomic<int> send_rc{-1};
  std::thread sender([&] {
    iovec iov[3] = {{&hdr, sizeof(hdr)},
                    {p1.data(), p1.size()},
                    {p2.data(), p2.size()}};
    send_rc = SendAllVec(sv[0], iov, 3, &ctl);
  });
  std::vector<uint8_t> got(sizeof(hdr) + p1.size() + p2.size());
  CHECK_TRUE(RecvAll(sv[1], got.data(), got.size()) == 0);
  sender.join();
  CHECK_TRUE(send_rc == 0);
  CHECK_TRUE(memcmp(got.data(), &hdr, sizeof(hdr)) == 0);
  CHECK_TRUE(memcmp(got.data() + sizeof(hdr), p1.data(), p1.size()) == 0);
  CHECK_TRUE(memcmp(got.data() + sizeof(hdr) + p1.size(), p2.data(),
                    p2.size()) == 0);
  close(sv[0]);
  close(sv[1]);
}

void TestZeroCopyProbeFallbackBitwise(ZeroCopyMode mode) {
  // Forced-EOPNOTSUPP fixture: AF_UNIX sockets refuse SO_ZEROCOPY, so the
  // probe must leave the engine disabled, the send must take the copy path
  // bit-for-bit, and the fallback counter must record the decline.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  TcpTransport t(sv[0], 32 * 1024, &ctl, mode);
  CHECK_TRUE(!t.zerocopy_enabled());
  CHECK_TRUE(std::strcmp(t.kind(), "tcp") == 0);
  const size_t kBytes = 512 * 1024;  // >= ZeroCopySender::kMinBytes
  std::vector<uint8_t> sent(kBytes), got(kBytes, 0);
  for (size_t i = 0; i < kBytes; ++i) sent[i] = static_cast<uint8_t>(i * 11);
  std::atomic<int> rc{-1};
  std::thread sender([&] { rc = t.Send(sent.data(), kBytes); });
  CHECK_TRUE(RecvAll(sv[1], got.data(), kBytes) == 0);
  sender.join();
  CHECK_TRUE(rc == 0);
  CHECK_TRUE(got == sent);  // copy path bitwise-matches the payload
  CHECK_TRUE(t.zerocopy_sends() == 0);
  CHECK_TRUE(t.zerocopy_fallbacks() >= 1);
  close(sv[0]);
  close(sv[1]);
}

void TestZeroCopyTcpSendBitwise(ZeroCopyMode mode) {
  // The armed lane (whatever the probe lands on — MSG_ZEROCOPY, io_uring,
  // or the copy fallback in a restricted sandbox) must deliver large
  // payloads bit-for-bit and keep the fallback/sends accounting coherent.
  int a = -1, b = -1;
  CHECK_TRUE(MakeTcpPair(&a, &b));
  if (a < 0 || b < 0) return;
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  TcpTransport t(a, 32 * 1024, &ctl, mode);
  const size_t kBytes = 1 << 20;
  std::vector<uint8_t> sent(kBytes), got(kBytes, 0);
  for (size_t i = 0; i < kBytes; ++i) {
    sent[i] = static_cast<uint8_t>(i * 7 + 3);
  }
  std::atomic<int> rc{-1};
  std::thread sender([&] { rc = t.Send(sent.data(), kBytes); });
  CHECK_TRUE(RecvAll(b, got.data(), kBytes) == 0);
  sender.join();
  CHECK_TRUE(rc == 0);
  CHECK_TRUE(got == sent);
  // Exactly one large send: it either completed zero-copy or was counted
  // as a fallback — never silently neither.
  CHECK_TRUE(t.zerocopy_sends() + t.zerocopy_fallbacks() >= 1);
  // Buffer-reuse safety: mutate and resend — the drain-before-return
  // contract means the peer must see the NEW bytes.
  for (size_t i = 0; i < kBytes; ++i) sent[i] = static_cast<uint8_t>(i ^ 0x5a);
  std::thread sender2([&] { rc = t.Send(sent.data(), kBytes); });
  CHECK_TRUE(RecvAll(b, got.data(), kBytes) == 0);
  sender2.join();
  CHECK_TRUE(rc == 0);
  CHECK_TRUE(got == sent);
  CloseFd(a);
  CloseFd(b);
}

void TestZeroCopyKilledPeerFailsFast() {
  // Peer death mid-large-send through the zero-copy lane: the sliced
  // completion/backpressure waits must fail the plane within a couple of
  // slices, exactly like the copy path (docs/fault-tolerance.md).
  int a = -1, b = -1;
  CHECK_TRUE(MakeTcpPair(&a, &b));
  if (a < 0 || b < 0) return;
  IoControl ctl;
  ctl.detect_slice_ms = 20;
  TcpTransport t(a, 32 * 1024, &ctl, ZeroCopyMode::AUTO);
  // Shrink the send buffer so a multi-MB send MUST block on peer drain.
  int small = 16 * 1024;
  setsockopt(a, SOL_SOCKET, SO_SNDBUF, &small, sizeof(small));
  const size_t kBytes = 32 << 20;
  std::vector<uint8_t> payload(kBytes, 0x77);
  std::atomic<int> rc{0};
  std::thread sender([&] { rc = t.Send(payload.data(), kBytes); });
  // Let the send wedge against the tiny buffer, then kill the peer end.
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  auto t0 = std::chrono::steady_clock::now();
  CloseFd(b);
  sender.join();
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  CHECK_TRUE(rc == -1);
  CHECK_TRUE(waited < 2.0);
  CHECK_TRUE(ctl.peer_failed.load() != 0 && ctl.is_aborted());
  CloseFd(a);
}

// --- data-plane worlds ------------------------------------------------------

// One DataPlane per thread; host strings decide the lanes (same string ->
// shm negotiation; the sockets stay as fallback + liveness probes).
struct TestWorld {
  std::vector<std::unique_ptr<DataPlane>> planes;
  std::vector<PeerAddr> peers;
};

TestWorld MakeWorld(const std::vector<std::string>& hosts) {
  TestWorld w;
  const int n = static_cast<int>(hosts.size());
  w.peers.resize(n);
  for (int r = 0; r < n; ++r) {
    w.planes.emplace_back(new DataPlane(r, n));
    CHECK_TRUE(w.planes[r]->Listen().ok());
    w.peers[r] = {hosts[r], w.planes[r]->port()};
  }
  return w;
}

// Exhaustive dtype/op sweep on one rank's plane; returns false on any
// mismatch. Covers every wire dtype and every reduce op, flat or
// hierarchical depending on the plane's configuration.
bool RunDtypeOpSweep(DataPlane* plane, int r, int world) {
  // float32 SUM, count straddling several 512 B segments per chunk
  // (and an odd count so ring chunks are uneven).
  {
    const int64_t n = 4099;
    std::vector<float> v(n);
    for (int64_t i = 0; i < n; ++i) {
      v[i] = static_cast<float>(r + 1) * (i % 11);
    }
    if (!plane->Allreduce(v.data(), n, DataType::FLOAT32, ReduceOp::SUM)
             .ok()) {
      return false;
    }
    float scale = world * (world + 1) / 2.0f;
    for (int64_t i = 0; i < n; ++i) {
      if (v[i] != scale * (i % 11)) return false;
    }
  }
  // float64 PRODUCT (exact for small integers).
  {
    std::vector<double> v = {2.0, 1.0, static_cast<double>(r + 1)};
    if (!plane->Allreduce(v.data(), 3, DataType::FLOAT64, ReduceOp::PRODUCT)
             .ok()) {
      return false;
    }
    double fact = 1.0;
    for (int q = 1; q <= world; ++q) fact *= q;
    if (v[0] != std::pow(2.0, world) || v[1] != 1.0 || v[2] != fact) {
      return false;
    }
  }
  // int32 MAX, small (latency path under AUTO).
  {
    std::vector<int32_t> v = {r, 100 - r, 7};
    if (!plane->Allreduce(v.data(), 3, DataType::INT32, ReduceOp::MAX).ok()) {
      return false;
    }
    if (v[0] != world - 1 || v[1] != 100 || v[2] != 7) return false;
  }
  // int64 MIN.
  {
    std::vector<int64_t> v = {static_cast<int64_t>(r) - 5, 1000 + r};
    if (!plane->Allreduce(v.data(), 2, DataType::INT64, ReduceOp::MIN).ok()) {
      return false;
    }
    if (v[0] != -5 || v[1] != 1000) return false;
  }
  // uint8 / int8 SUM.
  {
    std::vector<uint8_t> u(5, 3);
    if (!plane->Allreduce(u.data(), 5, DataType::UINT8, ReduceOp::SUM).ok()) {
      return false;
    }
    for (uint8_t x : u) {
      if (x != 3 * world) return false;
    }
    std::vector<int8_t> s(5, -2);
    if (!plane->Allreduce(s.data(), 5, DataType::INT8, ReduceOp::SUM).ok()) {
      return false;
    }
    for (int8_t x : s) {
      if (x != -2 * world) return false;
    }
  }
  // bool: SUM == OR, PRODUCT == AND.
  {
    std::vector<uint8_t> v = {static_cast<uint8_t>(r == 0 ? 1 : 0), 1, 0};
    if (!plane->Allreduce(v.data(), 3, DataType::BOOL, ReduceOp::SUM).ok()) {
      return false;
    }
    if (v[0] != 1 || v[1] != 1 || v[2] != 0) return false;
    std::vector<uint8_t> w = {static_cast<uint8_t>(r == 0 ? 0 : 1), 1, 1};
    if (!plane->Allreduce(w.data(), 3, DataType::BOOL, ReduceOp::PRODUCT)
             .ok()) {
      return false;
    }
    if (w[0] != 0 || w[1] != 1 || w[2] != 1) return false;
  }
  // fp16 SUM through the fused kernel.
  {
    const int64_t n = 1024;
    std::vector<uint16_t> v(n, FloatToHalfPublic(0.25f));
    if (!plane->Allreduce(v.data(), n, DataType::FLOAT16, ReduceOp::SUM)
             .ok()) {
      return false;
    }
    for (int64_t i = 0; i < n; ++i) {
      if (HalfToFloatPublic(v[i]) != 0.25f * world) return false;
    }
  }
  // bf16 MAX.
  {
    std::vector<uint16_t> v = {FloatToBf16Public(static_cast<float>(r)),
                               FloatToBf16Public(-1.0f)};
    if (!plane->Allreduce(v.data(), 2, DataType::BFLOAT16, ReduceOp::MAX)
             .ok()) {
      return false;
    }
    if (Bf16ToFloatPublic(v[0]) != static_cast<float>(world - 1) ||
        Bf16ToFloatPublic(v[1]) != -1.0f) {
      return false;
    }
  }
  return true;
}

// In-process world: one DataPlane per thread over localhost TCP (all ranks
// "share a host", so the lanes come up as shm unless disabled), exercising
// every allreduce algorithm (incl. the pipelined ring with a tiny segment
// size) on even/odd world sizes across the exhaustive dtype/op sweep.
void TestDataPlaneAllreduceAlgos() {
  for (bool shm : {false, true}) {
    for (int world : {2, 3, 4}) {
      for (AllreduceAlgo algo :
           {AllreduceAlgo::AUTO, AllreduceAlgo::RING,
            AllreduceAlgo::RECURSIVE_DOUBLING, AllreduceAlgo::TREE,
            AllreduceAlgo::SCATTER_ALLGATHER,
            AllreduceAlgo::PARAMETER_SERVER}) {
        TestWorld w = MakeWorld(
            std::vector<std::string>(world, "127.0.0.1"));
        for (int r = 0; r < world; ++r) {
          w.planes[r]->set_allreduce_algo(algo);
          w.planes[r]->set_segment_bytes(512);  // force ring pipelining
          w.planes[r]->set_crossover_bytes(4096);
          w.planes[r]->set_shm_enabled(shm);
          w.planes[r]->set_shm_ring_bytes(8192);  // force ring wraparound
          w.planes[r]->set_hier_mode(HierMode::OFF);
        }
        std::atomic<int> bad{0};
        std::vector<std::thread> threads;
        for (int r = 0; r < world; ++r) {
          threads.emplace_back([&, r] {
            if (!w.planes[r]->Connect(w.peers).ok()) {
              ++bad;
              return;
            }
            if (w.planes[r]->shm_lane_count() != (shm ? world - 1 : 0)) {
              ++bad;
              return;
            }
            if (!RunDtypeOpSweep(w.planes[r].get(), r, world)) ++bad;
          });
        }
        for (auto& t : threads) t.join();
        if (bad != 0) {
          std::fprintf(
              stderr,
              "FAIL DataPlane allreduce world=%d algo=%d shm=%d (%d bad)\n",
              world, static_cast<int>(algo), shm ? 1 : 0, bad.load());
          ++failures;
        }
        for (auto& p : w.planes) p->Shutdown();
      }
    }
  }
}

// The probe-fallback acceptance fixture at the world level: identical
// inputs through TCP worlds with every zero-copy mode (OFF = pure copy
// path, ON/AUTO = armed MSG_ZEROCOPY where the kernel allows, URING = the
// io_uring ladder, each degrading to copy under seccomp) must produce
// BITWISE identical results — the lane may never change payload bytes.
void TestDataPlaneZeroCopyMatchesCopyPathBitwise() {
  const int world = 2;
  const int64_t n = 400000;  // ~1.6 MB: hops clear ZeroCopySender::kMinBytes
  std::vector<std::vector<float>> outputs;
  const ZeroCopyMode modes[] = {ZeroCopyMode::OFF, ZeroCopyMode::ON,
                                ZeroCopyMode::AUTO, ZeroCopyMode::URING};
  for (ZeroCopyMode mode : modes) {
    TestWorld w = MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
    for (int r = 0; r < world; ++r) {
      w.planes[r]->set_allreduce_algo(AllreduceAlgo::RING);
      w.planes[r]->set_segment_bytes(64 * 1024);
      w.planes[r]->set_shm_enabled(false);  // pure TCP lanes
      w.planes[r]->set_hier_mode(HierMode::OFF);
      w.planes[r]->set_tcp_zerocopy(mode);
    }
    std::vector<std::vector<float>> bufs(world);
    std::atomic<int> bad{0};
    std::vector<std::thread> threads;
    for (int r = 0; r < world; ++r) {
      threads.emplace_back([&, r] {
        if (!w.planes[r]->Connect(w.peers).ok()) {
          ++bad;
          return;
        }
        bufs[r].resize(n);
        for (int64_t i = 0; i < n; ++i) {
          // Values whose sums are not exactly representable — bitwise
          // agreement must come from identical arithmetic, not luck.
          bufs[r][i] = 0.1f * static_cast<float>((i % 97) + r) + 1e-3f;
        }
        Status st = w.planes[r]->Allreduce(bufs[r].data(), n,
                                           DataType::FLOAT32, ReduceOp::SUM);
        if (!st.ok()) {
          std::fprintf(stderr, "zc world rank %d allreduce: %s\n", r,
                       st.reason.c_str());
          ++bad;
        }
      });
    }
    for (auto& t : threads) t.join();
    if (bad != 0) {
      std::fprintf(stderr, "FAIL zero-copy bitwise world mode=%d (%d bad)\n",
                   static_cast<int>(mode), bad.load());
      ++failures;
    }
    if (bad == 0) {
      CHECK_TRUE(bufs[0] == bufs[1]);  // ranks agree within the world
      if (mode == ZeroCopyMode::ON) {
        // With the lane armed on real TCP, the transport label must say so
        // (it may have legitimately downgraded only under AUTO).
        const std::string& label = w.planes[0]->transport_label();
        CHECK_TRUE(label == (w.planes[0]->zerocopy_active() ? "tcp-zc"
                                                            : "tcp"));
      }
      outputs.push_back(bufs[0]);
    }
    for (auto& p : w.planes) p->Shutdown();
  }
  for (size_t i = 1; i < outputs.size(); ++i) {
    CHECK_TRUE(outputs[i] == outputs[0]);  // every lane bitwise-matches OFF
  }
}

// Chaos `drop` (silent partition) through the zero-copy send path: the
// blackholed exchange must trip the read deadline and abort the plane, not
// wedge inside the completion drain.
void TestDataPlaneZeroCopyDropAborts() {
  const int world = 2;
  TestWorld w = MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
  for (int r = 0; r < world; ++r) {
    w.planes[r]->set_allreduce_algo(AllreduceAlgo::RING);
    w.planes[r]->set_shm_enabled(false);
    w.planes[r]->set_hier_mode(HierMode::OFF);
    w.planes[r]->set_tcp_zerocopy(ZeroCopyMode::ON);
    w.planes[r]->set_failure_detect_ms(100);
    w.planes[r]->set_read_deadline_secs(0.3);
  }
  ChaosSpec drop;
  drop.action = ChaosSpec::Action::DROP;
  drop.hop_index = 1;
  drop.peer = 0;
  w.planes[1]->set_chaos(drop);
  const int64_t n = 400000;
  std::atomic<int> failed{0};
  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> threads;
  for (int r = 0; r < world; ++r) {
    threads.emplace_back([&, r] {
      if (!w.planes[r]->Connect(w.peers).ok()) {
        ++failed;
        return;
      }
      std::vector<float> v(n, static_cast<float>(r + 1));
      Status st = w.planes[r]->Allreduce(v.data(), n, DataType::FLOAT32,
                                         ReduceOp::SUM);
      if (!st.ok()) ++failed;
    });
  }
  for (auto& t : threads) t.join();
  double waited = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
  // At least the dropped side fails (the healthy side's op breaks too once
  // the abort cascades); nobody may hang past the deadline + slack.
  CHECK_TRUE(failed >= 1);
  CHECK_TRUE(waited < 10.0);
  CHECK_TRUE(w.planes[1]->aborted());
  for (auto& p : w.planes) p->Shutdown();
}

// Scale-out algorithms (scatter-allgather, parameter-server) against the
// ring across worlds x transports x wire modes, on fp32 values whose sums
// are NOT exactly representable:
//   - every algorithm must agree BITWISE across ranks (compressed modes
//     included — quantize-once-at-owner makes every rank decode the same
//     codes);
//   - scatter-allgather under compression=NONE must match the ring BITWISE
//     (it replays the ring reduce-scatter's exact fold order);
//   - parameter-server is a LEFT fold (x_0 + x_1 + ...), a different IEEE
//     summation order than the ring's owner-rotated fold, so it is only
//     held to cross-rank identity plus a loose numeric tolerance.
void TestDataPlaneScaleAlgosBitwise() {
  const int64_t n = 3001;  // odd: ragged chunks, empty none at w<=5
  const AllreduceAlgo algos[] = {AllreduceAlgo::RING,
                                 AllreduceAlgo::SCATTER_ALLGATHER,
                                 AllreduceAlgo::PARAMETER_SERVER};
  for (bool shm : {false, true}) {
    for (int world : {2, 3, 5}) {
      for (WireCompression comp :
           {WireCompression::NONE, WireCompression::FP16,
            WireCompression::INT8, WireCompression::INT4}) {
        // outs[algo][rank] — filled per algorithm run below.
        std::vector<std::vector<std::vector<float>>> outs(
            3, std::vector<std::vector<float>>(world));
        std::vector<double> expect(n, 0.0);
        for (int r = 0; r < world; ++r) {
          for (int64_t i = 0; i < n; ++i) {
            expect[i] +=
                0.1 * static_cast<double>((i % 97) + r) + 1e-3;
          }
        }
        for (int a = 0; a < 3; ++a) {
          TestWorld w = MakeWorld(
              std::vector<std::string>(world, "127.0.0.1"));
          for (int r = 0; r < world; ++r) {
            w.planes[r]->set_allreduce_algo(algos[a]);
            w.planes[r]->set_segment_bytes(512);
            w.planes[r]->set_shm_enabled(shm);
            w.planes[r]->set_shm_ring_bytes(8192);
            w.planes[r]->set_hier_mode(HierMode::OFF);
          }
          std::atomic<int> bad{0};
          std::vector<std::thread> threads;
          for (int r = 0; r < world; ++r) {
            threads.emplace_back([&, r] {
              if (!w.planes[r]->Connect(w.peers).ok()) {
                ++bad;
                return;
              }
              outs[a][r].resize(n);
              for (int64_t i = 0; i < n; ++i) {
                outs[a][r][i] =
                    0.1f * static_cast<float>((i % 97) + r) + 1e-3f;
              }
              std::vector<float> residual;
              if (comp != WireCompression::NONE) {
                residual.assign(n, 0.0f);
                w.planes[r]->BeginCompressedOp(comp, residual.data());
              }
              Status st = w.planes[r]->Allreduce(
                  outs[a][r].data(), n, DataType::FLOAT32, ReduceOp::SUM);
              if (comp != WireCompression::NONE) {
                w.planes[r]->EndCompressedOp();
              }
              if (!st.ok()) {
                std::fprintf(stderr, "scale algo rank %d allreduce: %s\n",
                             r, st.reason.c_str());
                ++bad;
              }
            });
          }
          for (auto& t : threads) t.join();
          if (bad == 0) {
            for (int r = 1; r < world; ++r) {
              if (outs[a][r] != outs[a][0]) ++bad;  // cross-rank bitwise
            }
            // Loose numeric sanity (any fold order, any wire mode).
            const double tol =
                (comp == WireCompression::NONE   ? 1e-3
                 : comp == WireCompression::FP16 ? 2e-2
                 : comp == WireCompression::INT8 ? 0.2
                                                 : 2.0) *
                static_cast<double>(world);
            for (int64_t i = 0; i < n && bad == 0; ++i) {
              if (std::fabs(outs[a][0][i] - expect[i]) > tol) ++bad;
            }
          }
          if (bad != 0) {
            std::fprintf(stderr,
                         "FAIL scale algos world=%d algo=%d comp=%s shm=%d "
                         "(%d bad)\n",
                         world, static_cast<int>(algos[a]),
                         WireCompressionName(comp), shm ? 1 : 0, bad.load());
            ++failures;
          }
          for (auto& p : w.planes) p->Shutdown();
        }
        if (comp == WireCompression::NONE) {
          // scatter-allgather == ring, bitwise, on the raw wire.
          CHECK_TRUE(outs[1][0] == outs[0][0]);
        }
      }
    }
  }
}

// Chaos `drop` (silent partition) mid-collective on the scale-out
// algorithms: the blackholed hop must trip the read deadline, abort the
// plane, and cascade — never wedge. Covers both the scatter-allgather
// direct exchanges and the parameter-server star (worker <-> root lanes).
void TestDataPlaneScaleAlgosDropAborts() {
  for (AllreduceAlgo algo : {AllreduceAlgo::SCATTER_ALLGATHER,
                             AllreduceAlgo::PARAMETER_SERVER}) {
    const int world = 3;  // ragged chunks + a bystander rank for the cascade
    TestWorld w = MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
    for (int r = 0; r < world; ++r) {
      w.planes[r]->set_allreduce_algo(algo);
      w.planes[r]->set_shm_enabled(false);
      w.planes[r]->set_hier_mode(HierMode::OFF);
      w.planes[r]->set_failure_detect_ms(100);
      w.planes[r]->set_read_deadline_secs(0.3);
    }
    ChaosSpec drop;
    drop.action = ChaosSpec::Action::DROP;
    drop.hop_index = 1;
    drop.peer = 0;
    w.planes[1]->set_chaos(drop);
    const int64_t n = 100001;
    std::atomic<int> failed{0};
    auto t0 = std::chrono::steady_clock::now();
    std::vector<std::thread> threads;
    for (int r = 0; r < world; ++r) {
      threads.emplace_back([&, r] {
        if (!w.planes[r]->Connect(w.peers).ok()) {
          ++failed;
          return;
        }
        std::vector<float> v(n, static_cast<float>(r + 1));
        Status st = w.planes[r]->Allreduce(v.data(), n, DataType::FLOAT32,
                                           ReduceOp::SUM);
        if (!st.ok()) ++failed;
      });
    }
    for (auto& t : threads) t.join();
    double waited = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - t0)
                        .count();
    if (failed < 1 || waited >= 10.0 || !w.planes[1]->aborted()) {
      std::fprintf(stderr,
                   "FAIL scale algo drop abort algo=%d failed=%d "
                   "waited=%.1f aborted=%d\n",
                   static_cast<int>(algo), failed.load(), waited,
                   w.planes[1]->aborted() ? 1 : 0);
      ++failures;
    }
    for (auto& p : w.planes) p->Shutdown();
  }
}

// Hierarchical two-level allreduce across synthetic host topologies: two
// host strings split the world into local (shm) groups with one TCP leader
// pair; uneven local sizes exercise the leader gather/scatter with ragged
// chunks. The flat path on the identical world double-checks the oracle.
void TestDataPlaneHierarchicalAllreduce() {
  struct Topo {
    std::vector<std::string> hosts;
  };
  const Topo topos[] = {
      {{"127.0.0.1", "127.0.0.1", "localhost", "localhost"}},  // 2x2
      {{"127.0.0.1", "127.0.0.1", "127.0.0.1", "localhost"}},  // 3+1
      {{"127.0.0.1", "127.0.0.1", "localhost"}},               // 2+1
      {{"127.0.0.1", "127.0.0.1", "127.0.0.1"}},               // single host
  };
  for (const Topo& topo : topos) {
    const int world = static_cast<int>(topo.hosts.size());
    TestWorld w = MakeWorld(topo.hosts);
    for (int r = 0; r < world; ++r) {
      w.planes[r]->set_segment_bytes(512);
      w.planes[r]->set_shm_ring_bytes(8192);
      w.planes[r]->set_hier_mode(HierMode::ON);
    }
    std::atomic<int> bad{0};
    std::vector<std::thread> threads;
    for (int r = 0; r < world; ++r) {
      threads.emplace_back([&, r] {
        if (!w.planes[r]->Connect(w.peers).ok()) {
          ++bad;
          return;
        }
        if (!w.planes[r]->hier_active()) {
          ++bad;
          return;
        }
        if (!RunDtypeOpSweep(w.planes[r].get(), r, world)) ++bad;
        // Tiny tensor: count < local group size leaves empty chunks on the
        // gather/scatter path.
        std::vector<float> tiny = {static_cast<float>(r + 1)};
        if (!w.planes[r]
                 ->Allreduce(tiny.data(), 1, DataType::FLOAT32, ReduceOp::SUM)
                 .ok() ||
            tiny[0] != world * (world + 1) / 2.0f) {
          ++bad;
        }
      });
    }
    for (auto& t : threads) t.join();
    if (bad != 0) {
      std::fprintf(stderr, "FAIL hierarchical allreduce world=%d (%d bad)\n",
                   world, bad.load());
      ++failures;
    }
    for (auto& p : w.planes) p->Shutdown();
  }
}

// --- wire compression (compressed.{h,cpp}) ----------------------------------

// Per-bucket quantization range for the error bound, replicating the
// quantizer's zero-padded-tail semantics.
float BucketRange(const float* x, int64_t count, int64_t bucket) {
  const int64_t lo = bucket * kWireBucketSize;
  const int64_t n = std::min<int64_t>(kWireBucketSize, count - lo);
  float mn = x[lo], mx = x[lo];
  for (int64_t i = 0; i < n; ++i) {
    mn = std::min(mn, x[lo + i]);
    mx = std::max(mx, x[lo + i]);
  }
  if (n < kWireBucketSize) {
    mn = std::min(mn, 0.0f);
    mx = std::max(mx, 0.0f);
  }
  return mx - mn;
}

void TestWireQuantizerRoundTrip() {
  // Counts exercise sub-bucket tensors, exact buckets, padded tails, and
  // odd int4 nibble counts.
  const int64_t counts[] = {1, 2, 511, 512, 513, 1000, 1025};
  const WireCompression modes[] = {WireCompression::FP16,
                                   WireCompression::INT8,
                                   WireCompression::INT4};
  for (WireCompression c : modes) {
    for (int64_t n : counts) {
      std::vector<float> x(n), back(n, -1e9f);
      for (int64_t i = 0; i < n; ++i) {
        x[i] = 0.25f * static_cast<float>((i * 7 + 3) % 23 - 11) +
               0.001f * static_cast<float>(i % 5);
      }
      std::vector<uint8_t> wire(static_cast<size_t>(WireBytes(c, n)), 0xa5);
      WireCompress(c, x.data(), n, wire.data(), nullptr, nullptr);
      WireDecompress(c, wire.data(), n, back.data());
      const float levels = c == WireCompression::INT8 ? 255.0f : 15.0f;
      for (int64_t i = 0; i < n; ++i) {
        float bound;
        if (c == WireCompression::FP16) {
          bound = std::fabs(x[i]) * 1e-3f + 1e-6f;
        } else {
          // Max-min quantization error is at most half a unit.
          bound = BucketRange(x.data(), n, i / kWireBucketSize) / levels *
                      0.5f + 1e-5f;
        }
        if (std::fabs(back[i] - x[i]) > bound) {
          std::fprintf(stderr,
                       "FAIL wire roundtrip %s n=%lld i=%lld: %g vs %g\n",
                       WireCompressionName(c), static_cast<long long>(n),
                       static_cast<long long>(i), back[i], x[i]);
          ++failures;
          return;
        }
      }
      // The fused decompress-add matches decompress + add exactly.
      std::vector<float> acc(n, 1.5f);
      WireDecompressAdd(c, wire.data(), n, acc.data());
      for (int64_t i = 0; i < n; ++i) {
        CHECK_TRUE(acc[i] == 1.5f + back[i]);
      }
      // Self-decode returns exactly what a peer would decode (and may
      // alias the source buffer).
      std::vector<float> self(x);
      WireCompress(c, self.data(), n, wire.data(), nullptr, self.data());
      for (int64_t i = 0; i < n; ++i) {
        CHECK_TRUE(self[i] == back[i]);
      }
    }
  }
}

void TestWireInt4PackingAndTail() {
  // Hand-checked 3-element int4 block: the tail bucket is zero-padded for
  // the min/max scan (mn 0, mx 2, unit 2/15), codes ride low-nibble-first
  // (quantize.py pack_bits order), and the scaled tie 7.5 rounds to EVEN 8.
  const float x[3] = {0.0f, 1.0f, 2.0f};
  std::vector<uint8_t> wire(
      static_cast<size_t>(WireBytes(WireCompression::INT4, 3)), 0xff);
  CHECK_TRUE(wire.size() == 8 + 2);  // one bucket header + 2 code bytes
  WireCompress(WireCompression::INT4, x, 3, wire.data(), nullptr, nullptr);
  float mn, unit;
  memcpy(&mn, wire.data(), 4);
  memcpy(&unit, wire.data() + 4, 4);
  CHECK_TRUE(mn == 0.0f);
  CHECK_TRUE(std::fabs(unit - 2.0f / 15.0f) < 1e-7f);
  // codes low-nibble-first: element 1 scales to 1.0/unit = 7.49999952 in
  // fp32 (not an exact tie — unit rounds up), so RNE gives 7.
  CHECK_TRUE(wire[8] == 0x70);
  CHECK_TRUE(wire[9] == 0x0f);  // code 15; odd tail's high nibble is zeroed
  float back[3];
  WireDecompress(WireCompression::INT4, wire.data(), 3, back);
  CHECK_TRUE(back[0] == 0.0f);
  CHECK_TRUE(std::fabs(back[1] - 7.0f * 2.0f / 15.0f) < 1e-6f);
  CHECK_TRUE(back[2] == 2.0f);
}

void TestWireErrorFeedbackConvergence() {
  // The EF telescoping identity: sum_t decode_t = T*x + r_0 - r_T, so the
  // running mean of the quantized outputs converges to the exact input at
  // rate |r_T| / T — repeated int4 quantization of a FIXED gradient
  // recovers it to far below one quantization unit.
  const int64_t n = 700;  // padded tail bucket included
  std::vector<float> x(n);
  for (int64_t i = 0; i < n; ++i) {
    x[i] = 0.125f * static_cast<float>((i * 11 + 5) % 31 - 15);
  }
  std::vector<float> residual(n, 0.0f), decode(n, 0.0f);
  std::vector<double> acc(n, 0.0);
  std::vector<uint8_t> wire(
      static_cast<size_t>(WireBytes(WireCompression::INT4, n)));
  const int kIters = 200;
  for (int t = 0; t < kIters; ++t) {
    WireCompress(WireCompression::INT4, x.data(), n, wire.data(),
                 residual.data(), decode.data());
    for (int64_t i = 0; i < n; ++i) acc[i] += decode[i];
  }
  float max_range = 0.0f;
  for (int64_t b = 0; b * kWireBucketSize < n; ++b) {
    max_range = std::max(max_range, BucketRange(x.data(), n, b));
  }
  // One-shot quantization error bound vs the EF mean bound (T x smaller).
  const double one_shot = max_range / 15.0 * 0.5;
  const double ef_bound = 2.0 * one_shot / kIters + 1e-5;
  for (int64_t i = 0; i < n; ++i) {
    double err = std::fabs(acc[i] / kIters - x[i]);
    if (err > ef_bound) {
      std::fprintf(stderr,
                   "FAIL wire EF convergence at %lld: err %g (bound %g, "
                   "one-shot %g)\n",
                   static_cast<long long>(i), err, ef_bound, one_shot);
      ++failures;
      return;
    }
  }
}

// Compressed allreduce worlds: fp16/int8/int4 x ring/recursive-doubling x
// TCP/shm lanes. Verifies the quantized sum against the exact fp32 oracle
// within the mode's error budget, bitwise cross-rank agreement (the
// self-decode/forwarding design), raw-vs-wire byte accounting, and that
// non-eligible ops (MIN) pass through the compressed op untouched.
void TestDataPlaneCompressedAllreduce() {
  const int64_t n = 3000;
  for (bool shm : {false, true}) {
    for (AllreduceAlgo algo :
         {AllreduceAlgo::RING, AllreduceAlgo::RECURSIVE_DOUBLING}) {
      for (WireCompression comp :
           {WireCompression::FP16, WireCompression::INT8,
            WireCompression::INT4}) {
        for (int world : {2, 3}) {  // 3: ragged ring chunks + the RD fold
          TestWorld w = MakeWorld(
              std::vector<std::string>(world, "127.0.0.1"));
          for (int r = 0; r < world; ++r) {
            w.planes[r]->set_allreduce_algo(algo);
            w.planes[r]->set_segment_bytes(512);
            w.planes[r]->set_shm_enabled(shm);
            w.planes[r]->set_shm_ring_bytes(8192);
            w.planes[r]->set_hier_mode(HierMode::OFF);
          }
          std::vector<std::vector<float>> outs(
              world, std::vector<float>(n));
          std::vector<double> expect(n, 0.0);
          for (int r = 0; r < world; ++r) {
            for (int64_t i = 0; i < n; ++i) {
              outs[r][i] = 0.25f *
                  static_cast<float>((i * 7 + r * 13) % 23 - 11);
              expect[i] += outs[r][i];
            }
          }
          double max_abs = 0.0;
          for (double v : expect) max_abs = std::max(max_abs, std::fabs(v));
          const double tol =
              (comp == WireCompression::FP16   ? 2e-3
               : comp == WireCompression::INT8 ? 0.03
                                               : 0.4) *
              std::max(max_abs, 1.0);
          std::atomic<int> bad{0};
          std::vector<std::thread> threads;
          for (int r = 0; r < world; ++r) {
            threads.emplace_back([&, r] {
              if (!w.planes[r]->Connect(w.peers).ok()) {
                ++bad;
                return;
              }
              std::vector<float> residual(n, 0.0f);
              w.planes[r]->BeginCompressedOp(comp, residual.data());
              Status st = w.planes[r]->Allreduce(
                  outs[r].data(), n, DataType::FLOAT32, ReduceOp::SUM);
              w.planes[r]->EndCompressedOp();
              if (!st.ok()) {
                ++bad;
                return;
              }
              // int8 on the pure-compressed ring must beat 3.5x on the
              // wire (headers cost 8/512 per bucket; fp32 -> int8 is 4x).
              if (comp == WireCompression::INT8 &&
                  algo == AllreduceAlgo::RING &&
                  w.planes[r]->op_wire_bytes() * 7 >
                      w.planes[r]->op_raw_bytes() * 2) {
                ++bad;
                return;
              }
              // MIN is not eligible: the compressed op leaves it exact.
              std::vector<int32_t> m = {r, 100 - r};
              w.planes[r]->BeginCompressedOp(comp, nullptr);
              st = w.planes[r]->Allreduce(m.data(), 2, DataType::INT32,
                                          ReduceOp::MIN);
              w.planes[r]->EndCompressedOp();
              if (!st.ok() || m[0] != 0 || m[1] != 100 - (world - 1)) ++bad;
            });
          }
          for (auto& t : threads) t.join();
          for (int r = 0; r < world && bad == 0; ++r) {
            for (int64_t i = 0; i < n; ++i) {
              if (std::fabs(outs[r][i] - expect[i]) > tol) {
                ++bad;
                break;
              }
            }
            // Bitwise cross-rank agreement.
            if (memcmp(outs[r].data(), outs[0].data(), n * 4) != 0) ++bad;
          }
          if (bad != 0) {
            std::fprintf(stderr,
                         "FAIL compressed allreduce world=%d algo=%d "
                         "comp=%s shm=%d (%d bad)\n",
                         world, static_cast<int>(algo),
                         WireCompressionName(comp), shm ? 1 : 0,
                         bad.load());
            ++failures;
          }
          for (auto& p : w.planes) p->Shutdown();
        }
      }
    }
  }
}

// First-class reduce-scatter (PR 18): worlds {2,3} x TCP/shm x
// {dense,fp16,int8,int4}. Every rank must land exactly its own contiguous
// chunk of the reduced vector — the rotated-group ring leaves chunk r on
// rank r — within the wire mode's error budget, including a ragged count
// (standalone DataPlane callers may pass count % world != 0).
void TestDataPlaneReduceScatter() {
  for (bool shm : {false, true}) {
    for (WireCompression comp :
         {WireCompression::NONE, WireCompression::FP16,
          WireCompression::INT8, WireCompression::INT4}) {
      for (int world : {2, 3}) {
        // Ragged only on the dense path: the compressed ring quantizes
        // whole chunks, and the coordinator enforces divisibility for the
        // public op anyway.
        const int64_t n = comp == WireCompression::NONE ? 3001 : 3000;
        TestWorld w =
            MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
        for (int r = 0; r < world; ++r) {
          w.planes[r]->set_segment_bytes(512);
          w.planes[r]->set_shm_enabled(shm);
          w.planes[r]->set_shm_ring_bytes(8192);
          w.planes[r]->set_hier_mode(HierMode::OFF);
        }
        std::vector<std::vector<float>> ins(world, std::vector<float>(n));
        std::vector<double> expect(n, 0.0);
        for (int r = 0; r < world; ++r) {
          for (int64_t i = 0; i < n; ++i) {
            ins[r][i] =
                0.25f * static_cast<float>((i * 5 + r * 17) % 19 - 9);
            expect[i] += ins[r][i];
          }
        }
        double max_abs = 0.0;
        for (double v : expect) max_abs = std::max(max_abs, std::fabs(v));
        const double tol =
            (comp == WireCompression::NONE   ? 1e-6
             : comp == WireCompression::FP16 ? 2e-3
             : comp == WireCompression::INT8 ? 0.03
                                             : 0.4) *
            std::max(max_abs, 1.0);
        std::vector<ByteBuf> outs(world);
        std::atomic<int> bad{0};
        std::vector<std::thread> threads;
        for (int r = 0; r < world; ++r) {
          threads.emplace_back([&, r] {
            if (!w.planes[r]->Connect(w.peers).ok()) {
              ++bad;
              return;
            }
            std::vector<float> residual(n, 0.0f);
            if (comp != WireCompression::NONE) {
              w.planes[r]->BeginCompressedOp(comp, residual.data());
            }
            Status st = w.planes[r]->ReduceScatter(
                ins[r].data(), n, DataType::FLOAT32, ReduceOp::SUM,
                &outs[r]);
            w.planes[r]->EndCompressedOp();
            if (!st.ok()) ++bad;
            // Half an allreduce: raw accounting is (n-1)/n of the payload
            // this rank forwarded; dense wire == raw.
            if (comp == WireCompression::NONE &&
                w.planes[r]->op_wire_bytes() !=
                    w.planes[r]->op_raw_bytes()) {
              ++bad;
            }
          });
        }
        for (auto& t : threads) t.join();
        // Chunk starts mirror ChunkStarts(): base + remainder spread.
        const int64_t base = n / world, rem = n % world;
        int64_t start = 0;
        for (int r = 0; r < world && bad == 0; ++r) {
          const int64_t len = base + (r < rem ? 1 : 0);
          if (static_cast<int64_t>(outs[r].size()) != len * 4) {
            ++bad;
            break;
          }
          const float* got = reinterpret_cast<const float*>(outs[r].data());
          for (int64_t i = 0; i < len; ++i) {
            if (std::fabs(got[i] - expect[start + i]) > tol) {
              ++bad;
              break;
            }
          }
          start += len;
        }
        if (bad != 0) {
          std::fprintf(stderr,
                       "FAIL reduce-scatter world=%d comp=%s shm=%d\n",
                       world, WireCompressionName(comp), shm ? 1 : 0);
          ++failures;
        }
        for (auto& p : w.planes) p->Shutdown();
      }
    }
  }
}

// First-class ragged allgather (PR 18): worlds {2,3} x TCP/shm x
// {dense-direct,dense-ring,fp16,int8,int4}. Dense results must be exact
// on both dispatch arms (pairwise rotation under the crossover, ring
// store-and-forward above it); compressed results ride quantize-once
// owner codes, so every rank's gathered vector must be BITWISE identical
// even though the codes are lossy vs the originals.
void TestDataPlaneAllgatherv() {
  for (bool shm : {false, true}) {
    struct Arm {
      WireCompression comp;
      int64_t crossover;  // 0 = keep the 32 KB default (direct arm)
    };
    const Arm arms[] = {
        {WireCompression::NONE, 0},     // direct pairwise rotation
        {WireCompression::NONE, 1024},  // forced ring store-and-forward
        {WireCompression::FP16, 0},     {WireCompression::INT8, 0},
        {WireCompression::INT4, 0},
    };
    for (const Arm& arm : arms) {
      for (int world : {2, 3}) {
        TestWorld w =
            MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
        for (int r = 0; r < world; ++r) {
          w.planes[r]->set_segment_bytes(512);
          w.planes[r]->set_shm_enabled(shm);
          w.planes[r]->set_shm_ring_bytes(8192);
          w.planes[r]->set_hier_mode(HierMode::OFF);
          if (arm.crossover > 0) {
            w.planes[r]->set_crossover_bytes(arm.crossover);
          }
        }
        // Ragged per-rank blocks (fp32 counts; ~3-5 KB each).
        std::vector<std::vector<float>> ins(world);
        std::vector<int64_t> block_bytes(world);
        std::vector<double> expect;
        for (int r = 0; r < world; ++r) {
          const int64_t cnt = 800 + 131 * r;
          ins[r].resize(cnt);
          for (int64_t i = 0; i < cnt; ++i) {
            ins[r][i] =
                0.5f * static_cast<float>((i * 3 + r * 7) % 17 - 8);
            expect.push_back(ins[r][i]);
          }
          block_bytes[r] = cnt * 4;
        }
        double max_abs = 0.0;
        for (double v : expect) max_abs = std::max(max_abs, std::fabs(v));
        const double tol =
            (arm.comp == WireCompression::NONE   ? 0.0
             : arm.comp == WireCompression::FP16 ? 2e-3
             : arm.comp == WireCompression::INT8 ? 0.03
                                                 : 0.4) *
            std::max(max_abs, 1.0);
        std::vector<ByteBuf> outs(world);
        std::atomic<int> bad{0};
        std::vector<std::thread> threads;
        for (int r = 0; r < world; ++r) {
          threads.emplace_back([&, r] {
            if (!w.planes[r]->Connect(w.peers).ok()) {
              ++bad;
              return;
            }
            if (arm.comp != WireCompression::NONE) {
              w.planes[r]->BeginCompressedOp(arm.comp, nullptr);
            }
            Status st = w.planes[r]->Allgatherv(
                ins[r].data(), block_bytes[r], block_bytes, &outs[r]);
            w.planes[r]->EndCompressedOp();
            if (!st.ok()) ++bad;
          });
        }
        for (auto& t : threads) t.join();
        const size_t total = expect.size();
        for (int r = 0; r < world && bad == 0; ++r) {
          if (outs[r].size() != total * 4) {
            ++bad;
            break;
          }
          const float* got = reinterpret_cast<const float*>(outs[r].data());
          for (size_t i = 0; i < total; ++i) {
            const double err = std::fabs(got[i] - expect[i]);
            if (arm.comp == WireCompression::NONE ? err != 0.0
                                                  : err > tol) {
              ++bad;
              break;
            }
          }
          // Bitwise world-wide, lossy or not (quantize-once owner codes).
          if (memcmp(outs[r].data(), outs[0].data(), total * 4) != 0) {
            ++bad;
          }
        }
        if (bad != 0) {
          std::fprintf(stderr,
                       "FAIL allgatherv world=%d comp=%s crossover=%lld "
                       "shm=%d\n",
                       world, WireCompressionName(arm.comp),
                       static_cast<long long>(arm.crossover), shm ? 1 : 0);
          ++failures;
        }
        for (auto& p : w.planes) p->Shutdown();
      }
    }
  }
}

// First-class broadcast (PR 19): worlds {2,3,5} (npo2 exercises the
// binomial vrank rotation) x TCP/shm x {dense-flat,dense-tree,fp16,int8,
// int4 tree + int4 flat}. Nonzero root. Dense results must be exact;
// compressed results ride the root's quantize-once codes, so every rank
// (root included, via self-decode) must hold BITWISE identical bytes even
// though they are lossy vs the originals.
void TestDataPlaneBroadcast() {
  struct Arm {
    WireCompression comp;
    bool flat;
  };
  const Arm arms[] = {
      {WireCompression::NONE, true}, {WireCompression::NONE, false},
      {WireCompression::FP16, false}, {WireCompression::INT8, false},
      {WireCompression::INT4, false}, {WireCompression::INT4, true},
  };
  for (bool shm : {false, true}) {
    for (const Arm& arm : arms) {
      for (int world : {2, 3, 5}) {
        const int64_t n = 3001;
        const int root = 1 % world;
        TestWorld w =
            MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
        for (int r = 0; r < world; ++r) {
          w.planes[r]->set_segment_bytes(512);
          w.planes[r]->set_shm_enabled(shm);
          w.planes[r]->set_shm_ring_bytes(8192);
          w.planes[r]->set_hier_mode(HierMode::OFF);
          // Force the schedule: floor above the payload -> flat, 0 -> tree.
          w.planes[r]->set_bcast_flat_max(arm.flat ? (int64_t{1} << 30) : 0);
        }
        std::vector<float> orig(n);
        for (int64_t i = 0; i < n; ++i) {
          orig[i] = 0.25f * static_cast<float>((i * 7 + 3) % 23 - 11);
        }
        double max_abs = 0.0;
        for (float v : orig) {
          max_abs = std::max(max_abs, static_cast<double>(std::fabs(v)));
        }
        const double tol =
            (arm.comp == WireCompression::NONE   ? 0.0
             : arm.comp == WireCompression::FP16 ? 2e-3
             : arm.comp == WireCompression::INT8 ? 0.03
                                                 : 0.4) *
            std::max(max_abs, 1.0);
        // Root starts from the payload; everyone else from poison.
        std::vector<std::vector<float>> bufs(
            world, std::vector<float>(n, -777.0f));
        bufs[root] = orig;
        std::atomic<int> bad{0};
        std::vector<std::thread> threads;
        for (int r = 0; r < world; ++r) {
          threads.emplace_back([&, r] {
            if (!w.planes[r]->Connect(w.peers).ok()) {
              ++bad;
              return;
            }
            if (arm.comp != WireCompression::NONE) {
              w.planes[r]->BeginCompressedOp(arm.comp, nullptr);
            }
            Status st = w.planes[r]->Broadcast(bufs[r].data(), n * 4, root);
            w.planes[r]->EndCompressedOp();
            if (!st.ok()) ++bad;
            if (std::strcmp(w.planes[r]->last_algo_label(),
                            arm.flat ? "bcast_flat" : "bcast_tree") != 0) {
              ++bad;
            }
            // Dense wire == raw; int4/int8 must actually shrink the wire.
            if (arm.comp == WireCompression::NONE &&
                w.planes[r]->op_wire_bytes() != w.planes[r]->op_raw_bytes()) {
              ++bad;
            }
            if (r == root && arm.comp == WireCompression::INT4 &&
                w.planes[r]->op_wire_bytes() * 2 >
                    w.planes[r]->op_raw_bytes()) {
              ++bad;
            }
          });
        }
        for (auto& t : threads) t.join();
        for (int r = 0; r < world && bad == 0; ++r) {
          // Bitwise vs the root's post-op buffer on EVERY rank.
          if (memcmp(bufs[r].data(), bufs[root].data(), n * 4) != 0) {
            ++bad;
            break;
          }
          for (int64_t i = 0; i < n; ++i) {
            const double err = std::fabs(bufs[r][i] - orig[i]);
            if (arm.comp == WireCompression::NONE ? err != 0.0 : err > tol) {
              ++bad;
              break;
            }
          }
        }
        if (bad != 0) {
          std::fprintf(stderr,
                       "FAIL broadcast world=%d comp=%s flat=%d shm=%d\n",
                       world, WireCompressionName(arm.comp),
                       arm.flat ? 1 : 0, shm ? 1 : 0);
          ++failures;
        }
        for (auto& p : w.planes) p->Shutdown();
      }
    }
  }
}

// First-class pairwise alltoallv (PR 19): worlds {2,3} x TCP/shm x
// {dense,fp16,int8,int4} with genuinely uneven splits including an empty
// block (the MoE capacity-overflow shape). Rank r's block for rank q must
// land exactly at q's recv offset for r; dense is exact, compressed within
// the wire mode's budget (each block quantized once at its sender).
void TestDataPlaneAlltoallv() {
  for (bool shm : {false, true}) {
    for (WireCompression comp :
         {WireCompression::NONE, WireCompression::FP16,
          WireCompression::INT8, WireCompression::INT4}) {
      for (int world : {2, 3}) {
        TestWorld w =
            MakeWorld(std::vector<std::string>(world, "127.0.0.1"));
        for (int r = 0; r < world; ++r) {
          w.planes[r]->set_segment_bytes(512);
          w.planes[r]->set_shm_enabled(shm);
          w.planes[r]->set_shm_ring_bytes(8192);
          w.planes[r]->set_hier_mode(HierMode::OFF);
        }
        // Uneven split matrix; (0 -> world-1) is an empty block.
        auto count = [&](int from, int to) -> int64_t {
          if (from == 0 && to == world - 1) return 0;
          return 501 + 217 * from + 131 * to;
        };
        auto val = [](int from, int to, int64_t i) {
          return 0.25f *
                 static_cast<float>((i * 3 + from * 7 + to * 11) % 21 - 10);
        };
        std::vector<std::vector<float>> ins(world);
        std::vector<std::vector<int64_t>> send_bytes(
            world, std::vector<int64_t>(world));
        std::vector<std::vector<int64_t>> recv_bytes(
            world, std::vector<int64_t>(world));
        for (int r = 0; r < world; ++r) {
          for (int q = 0; q < world; ++q) {
            send_bytes[r][q] = count(r, q) * 4;
            recv_bytes[r][q] = count(q, r) * 4;
            for (int64_t i = 0; i < count(r, q); ++i) {
              ins[r].push_back(val(r, q, i));
            }
          }
        }
        const double tol = (comp == WireCompression::NONE   ? 0.0
                            : comp == WireCompression::FP16 ? 2e-3
                            : comp == WireCompression::INT8 ? 0.03
                                                            : 0.4) *
                           3.0;
        std::vector<ByteBuf> outs(world);
        std::atomic<int> bad{0};
        std::vector<std::thread> threads;
        for (int r = 0; r < world; ++r) {
          threads.emplace_back([&, r] {
            if (!w.planes[r]->Connect(w.peers).ok()) {
              ++bad;
              return;
            }
            if (comp != WireCompression::NONE) {
              w.planes[r]->BeginCompressedOp(comp, nullptr);
            }
            Status st = w.planes[r]->Alltoallv(ins[r].data(), send_bytes[r],
                                               recv_bytes[r], &outs[r]);
            w.planes[r]->EndCompressedOp();
            if (!st.ok()) ++bad;
            if (std::strcmp(w.planes[r]->last_algo_label(), "pairwise") !=
                0) {
              ++bad;
            }
            if (comp == WireCompression::NONE &&
                w.planes[r]->op_wire_bytes() != w.planes[r]->op_raw_bytes()) {
              ++bad;
            }
          });
        }
        for (auto& t : threads) t.join();
        for (int r = 0; r < world && bad == 0; ++r) {
          int64_t total = 0;
          for (int q = 0; q < world; ++q) total += recv_bytes[r][q];
          if (static_cast<int64_t>(outs[r].size()) != total) {
            ++bad;
            break;
          }
          const float* got = reinterpret_cast<const float*>(outs[r].data());
          int64_t off = 0;
          for (int q = 0; q < world && bad == 0; ++q) {
            for (int64_t i = 0; i < count(q, r); ++i) {
              const double err = std::fabs(got[off + i] - val(q, r, i));
              if (comp == WireCompression::NONE ? err != 0.0 : err > tol) {
                ++bad;
                break;
              }
            }
            off += count(q, r);
          }
        }
        if (bad != 0) {
          std::fprintf(stderr, "FAIL alltoallv world=%d comp=%s shm=%d\n",
                       world, WireCompressionName(comp), shm ? 1 : 0);
          ++failures;
        }
        for (auto& p : w.planes) p->Shutdown();
      }
    }
  }
}

// Compressed hierarchical worlds: the leader (cross-host) phase carries the
// quantized hops, intra-host stages stay dense; result must still agree
// with the oracle and bitwise across every rank.
void TestDataPlaneCompressedHierarchical() {
  const int64_t n = 3000;
  const std::vector<std::vector<std::string>> topos = {
      {"127.0.0.1", "127.0.0.1", "localhost", "localhost"},  // 2x2
      {"127.0.0.1", "127.0.0.1", "localhost"},               // 2+1
  };
  for (const auto& hosts : topos) {
    for (WireCompression comp :
         {WireCompression::FP16, WireCompression::INT8,
          WireCompression::INT4}) {
      const int world = static_cast<int>(hosts.size());
      TestWorld w = MakeWorld(hosts);
      for (int r = 0; r < world; ++r) {
        w.planes[r]->set_segment_bytes(512);
        w.planes[r]->set_shm_ring_bytes(8192);
        w.planes[r]->set_hier_mode(HierMode::ON);
      }
      std::vector<std::vector<float>> outs(world, std::vector<float>(n));
      std::vector<double> expect(n, 0.0);
      for (int r = 0; r < world; ++r) {
        for (int64_t i = 0; i < n; ++i) {
          outs[r][i] =
              0.25f * static_cast<float>((i * 7 + r * 13) % 23 - 11);
          expect[i] += outs[r][i];
        }
      }
      double max_abs = 0.0;
      for (double v : expect) max_abs = std::max(max_abs, std::fabs(v));
      const double tol = (comp == WireCompression::FP16   ? 2e-3
                          : comp == WireCompression::INT8 ? 0.03
                                                          : 0.4) *
                         std::max(max_abs, 1.0);
      std::atomic<int> bad{0};
      std::vector<std::thread> threads;
      for (int r = 0; r < world; ++r) {
        threads.emplace_back([&, r] {
          if (!w.planes[r]->Connect(w.peers).ok() ||
              !w.planes[r]->hier_active()) {
            ++bad;
            return;
          }
          std::vector<float> residual(n, 0.0f);
          w.planes[r]->BeginCompressedOp(comp, residual.data());
          Status st = w.planes[r]->Allreduce(outs[r].data(), n,
                                             DataType::FLOAT32,
                                             ReduceOp::SUM);
          w.planes[r]->EndCompressedOp();
          if (!st.ok()) ++bad;
          // Tiny tensor through the compressed op: empty chunks and the
          // min-count edge must not wedge the two-level schedule.
          std::vector<float> tiny = {static_cast<float>(r + 1)};
          w.planes[r]->BeginCompressedOp(comp, nullptr);
          st = w.planes[r]->Allreduce(tiny.data(), 1, DataType::FLOAT32,
                                      ReduceOp::SUM);
          w.planes[r]->EndCompressedOp();
          if (!st.ok() ||
              std::fabs(tiny[0] - world * (world + 1) / 2.0f) > 0.5f) {
            ++bad;
          }
        });
      }
      for (auto& t : threads) t.join();
      for (int r = 0; r < world && bad == 0; ++r) {
        for (int64_t i = 0; i < n; ++i) {
          if (std::fabs(outs[r][i] - expect[i]) > tol) {
            ++bad;
            break;
          }
        }
        if (memcmp(outs[r].data(), outs[0].data(), n * 4) != 0) ++bad;
      }
      if (bad != 0) {
        std::fprintf(stderr,
                     "FAIL compressed hier allreduce world=%d comp=%s "
                     "(%d bad)\n",
                     world, WireCompressionName(comp), bad.load());
        ++failures;
      }
      for (auto& p : w.planes) p->Shutdown();
    }
  }
}

void TestReduceBufferOps() {
  float dst[4] = {1, 2, 3, 4};
  float src[4] = {4, 3, 2, 1};
  ReduceBuffer(dst, src, 4, DataType::FLOAT32, ReduceOp::SUM);
  CHECK_TRUE(dst[0] == 5 && dst[3] == 5);
  float dmin[2] = {1, 9};
  float smin[2] = {3, 2};
  ReduceBuffer(dmin, smin, 2, DataType::FLOAT32, ReduceOp::MIN);
  CHECK_TRUE(dmin[0] == 1 && dmin[1] == 2);
  int64_t dprod[2] = {2, -3};
  int64_t sprod[2] = {5, 7};
  ReduceBuffer(dprod, sprod, 2, DataType::INT64, ReduceOp::PRODUCT);
  CHECK_TRUE(dprod[0] == 10 && dprod[1] == -21);
  // bf16 accumulates through float (reference: half.cc custom MPI sum).
  uint16_t dbf[2] = {FloatToBf16Public(1.5f), FloatToBf16Public(-2.0f)};
  uint16_t sbf[2] = {FloatToBf16Public(0.5f), FloatToBf16Public(1.0f)};
  ReduceBuffer(dbf, sbf, 2, DataType::BFLOAT16, ReduceOp::SUM);
  CHECK_TRUE(std::fabs(Bf16ToFloatPublic(dbf[0]) - 2.0f) < 0.05f);
  CHECK_TRUE(std::fabs(Bf16ToFloatPublic(dbf[1]) - (-1.0f)) < 0.05f);
}

void TestGaussianProcessInterpolates() {
  GaussianProcess gp(/*noise=*/1e-6);
  std::vector<std::vector<double>> x = {{0.0}, {0.5}, {1.0}};
  std::vector<double> y = {1.0, 3.0, 2.0};
  gp.Fit(x, y);
  double mu, sigma;
  for (size_t i = 0; i < x.size(); ++i) {
    gp.Predict(x[i], &mu, &sigma);
    CHECK_TRUE(std::fabs(mu - y[i]) < 0.05);   // near-interpolation
    CHECK_TRUE(sigma < 0.2);                   // confident at data points
  }
  gp.Predict({0.25}, &mu, &sigma);
  CHECK_TRUE(mu > 1.0 && mu < 3.2);            // between neighbors
}

void TestBayesianOptimizerPicksBestSample() {
  BayesianOptimizer opt(/*dim=*/2, /*noise=*/1e-4);
  opt.AddSample({0.1, 0.1}, 1.0);
  opt.AddSample({0.9, 0.2}, 5.0);
  opt.AddSample({0.4, 0.8}, 3.0);
  std::vector<double> best = opt.BestSample();
  CHECK_TRUE(best[0] == 0.9 && best[1] == 0.2);
  std::vector<double> next = opt.NextSample();
  CHECK_TRUE(next.size() == 2);
  for (double v : next) CHECK_TRUE(v >= 0.0 && v <= 1.0);
}

void TestParameterManagerFreezesAtBest() {
  ParameterManager pm;
  pm.Initialize(/*cycle=*/1.0, /*fusion=*/64 << 20, /*cache=*/true,
                /*algo_crossover=*/256 << 10, /*tune_crossover=*/true,
                /*sa_enabled=*/true, /*tune_sa=*/true,
                /*hier_enabled=*/false, /*tune_hier=*/true,
                /*wire_compression=*/0, /*tune_compression=*/true,
                /*log=*/"", /*warmup=*/1, /*cycles_per_sample=*/1,
                /*max_samples=*/4, /*gp_noise=*/0.1);
  CHECK_TRUE(pm.active());
  double t = 0.0;
  // Drive synthetic traffic until tuning freezes (warmup 1 sample +
  // 4 scored samples x 3 median scores each).
  bool changed_at_least_once = false;
  for (int i = 0; i < 64; ++i) {
    t += 0.01;
    changed_at_least_once |= pm.Update(/*bytes=*/1 << 20, t);
  }
  CHECK_TRUE(changed_at_least_once);
  ParameterManager::Params p = pm.Current();
  CHECK_TRUE(p.cycle_time_ms >= 0.5 && p.cycle_time_ms <= 50.0);
  CHECK_TRUE(p.fusion_threshold >= (1 << 20));
  CHECK_TRUE(p.algo_crossover >= (4 << 10) && p.algo_crossover <= (4 << 20));
  // The compression categorical stays inside the automatic menu
  // {none, fp16, int8} — int4 is never auto-selected.
  CHECK_TRUE(p.wire_compression >= 0 && p.wire_compression <= 2);

  // Pinned algorithm (tune_crossover=false), pinned hier (tune_hier=false)
  // and pinned compression (tune_compression=false): the excluded
  // coordinates are held at their initial values.
  ParameterManager pinned;
  pinned.Initialize(/*cycle=*/1.0, /*fusion=*/64 << 20, /*cache=*/true,
                    /*algo_crossover=*/123456, /*tune_crossover=*/false,
                    /*sa_enabled=*/false, /*tune_sa=*/false,
                    /*hier_enabled=*/true, /*tune_hier=*/false,
                    /*wire_compression=*/3, /*tune_compression=*/false,
                    /*log=*/"", /*warmup=*/1, /*cycles_per_sample=*/1,
                    /*max_samples=*/4, /*gp_noise=*/0.1);
  t = 0.0;
  for (int i = 0; i < 64; ++i) {
    t += 0.01;
    pinned.Update(/*bytes=*/1 << 20, t);
  }
  CHECK_TRUE(pinned.Current().algo_crossover == 123456);
  CHECK_TRUE(pinned.Current().hier_enabled);
  CHECK_TRUE(pinned.Current().wire_compression == 3);
}

// --- metrics registry (metrics.{h,cpp}) ------------------------------------

void TestMetricsConcurrentIncrements() {
  // 8 threads hammering one counter, one gauge, and one histogram through
  // freshly-resolved handles: no increment may be lost (counter/histogram
  // count are atomic adds) and the dump must reflect the exact totals.
  Metrics m;
  constexpr int kThreads = 8;
  constexpr int kIters = 20000;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([&m, t] {
      Counter* c = m.GetCounter("test_ops_total", "ops");
      Histogram* h = m.GetHistogram("test_lat_seconds", "lat", {0.5, 2.0});
      Gauge* g = m.GetGauge("test_depth", "depth");
      for (int i = 0; i < kIters; ++i) {
        c->Inc();
        h->Observe(i % 2 == 0 ? 0.25 : 1.0);
        g->Set(t);
      }
    });
  }
  for (auto& t : ts) t.join();
  CHECK_TRUE(m.GetCounter("test_ops_total", "ops")->Get() ==
             kThreads * kIters);
  Histogram* h = m.GetHistogram("test_lat_seconds", "lat", {0.5, 2.0});
  CHECK_TRUE(h->Count() == kThreads * kIters);
  CHECK_TRUE(h->BucketCount(0) == kThreads * kIters / 2);  // 0.25 <= 0.5
  CHECK_TRUE(h->BucketCount(1) == kThreads * kIters / 2);  // 0.5 < 1.0 <= 2
  CHECK_TRUE(h->BucketCount(2) == 0);                      // +Inf bucket
  // Sum is CAS-accumulated: exact (all observed values are binary fractions).
  CHECK_TRUE(h->Sum() == kThreads * kIters * (0.25 + 1.0) / 2);
  double depth = m.GetGauge("test_depth", "depth")->Get();
  CHECK_TRUE(depth >= 0 && depth < kThreads);  // some thread's last Set
}

void TestMetricsHistogramBucketBoundaries() {
  // Prometheus contract: `le` is INCLUSIVE — a value exactly on a bound
  // lands in that bucket; the first value past the last bound goes to +Inf.
  Metrics m;
  Histogram* h =
      m.GetHistogram("test_bytes", "bytes", {10.0, 100.0, 1000.0});
  h->Observe(10.0);    // bucket 0 (le=10)
  h->Observe(10.5);    // bucket 1
  h->Observe(100.0);   // bucket 1 (le=100)
  h->Observe(1000.0);  // bucket 2
  h->Observe(1000.5);  // +Inf
  CHECK_TRUE(h->BucketCount(0) == 1);
  CHECK_TRUE(h->BucketCount(1) == 2);
  CHECK_TRUE(h->BucketCount(2) == 1);
  CHECK_TRUE(h->BucketCount(3) == 1);
  CHECK_TRUE(h->Count() == 5);

  std::string dump = m.Dump();
  // Cumulative rendering: le="100" must count buckets 0+1 = 3.
  CHECK_TRUE(dump.find("test_bytes_bucket{le=\"100\"} 3") !=
             std::string::npos);
  CHECK_TRUE(dump.find("test_bytes_bucket{le=\"+Inf\"} 5") !=
             std::string::npos);
  CHECK_TRUE(dump.find("test_bytes_count 5") != std::string::npos);
}

void TestMetricsDumpDeterminism() {
  // Identical contents registered in different orders must render to the
  // SAME text (families sorted by name, series by label string) — the
  // aggregator and tests diff dumps across ranks.
  auto build = [](bool reversed) {
    auto m = std::make_unique<Metrics>();
    auto add = [&](int which) {
      if (which == 0) {
        m->GetCounter("zz_total", "z", {{"op", "A"}})->Add(3);
      } else if (which == 1) {
        m->GetCounter("zz_total", "z", {{"op", "B"}})->Add(4);
      } else {
        m->GetGauge("aa_depth", "a")->Set(7);
      }
    };
    if (reversed) { add(2); add(1); add(0); }
    else { add(0); add(1); add(2); }
    return m;
  };
  std::string d1 = build(false)->Dump();
  std::string d2 = build(true)->Dump();
  CHECK_TRUE(d1 == d2);
  // aa_depth sorts before zz_total; labeled series sort by label string.
  CHECK_TRUE(d1.find("aa_depth") < d1.find("zz_total"));
  CHECK_TRUE(d1.find("zz_total{op=\"A\"} 3") < d1.find("zz_total{op=\"B\"} 4"));
  // Every non-comment line is `name{labels} value` — well-formed exposition.
  CHECK_TRUE(d1.find("# TYPE aa_depth gauge") != std::string::npos);
  CHECK_TRUE(d1.find("# TYPE zz_total counter") != std::string::npos);
}

void TestMetricsLabelEscaping() {
  Metrics m;
  m.GetCounter("esc_total", "esc", {{"name", "a\"b\\c\nd"}})->Inc();
  std::string dump = m.Dump();
  CHECK_TRUE(dump.find("esc_total{name=\"a\\\"b\\\\c\\nd\"} 1") !=
             std::string::npos);
}

void TestDataPlaneWireCountersInRegistry() {
  // The DataPlane's cumulative byte accounting must live in the injected
  // registry (single source of truth for hvdtpu_wire_stats AND /metrics).
  Metrics m;
  DataPlane plane(0, 1);
  plane.set_metrics(&m);
  CHECK_TRUE(plane.total_raw_bytes() == 0);
  Counter* raw = m.GetCounter("hvdtpu_allreduce_raw_bytes_total", "");
  raw->Add(123);
  CHECK_TRUE(plane.total_raw_bytes() == 123);
  CHECK_TRUE(m.Dump().find("hvdtpu_allreduce_raw_bytes_total 123") !=
             std::string::npos);
}

void TestClockOffsetEstimator() {
  // Min-RTT sample wins: the second sample (RTT 10) beats the first
  // (RTT 100); offset = t2 - midpoint(t1, t3).
  std::vector<ClockSample> samples = {
      {1000, 2000, 1100},   // rtt 100: offset 2000 - 1050 = 950, err 51
      {2000, 2505, 2010},   // rtt 10:  offset 2505 - 2005 = 500, err 6
  };
  ClockEstimate est = EstimateClockOffset(samples);
  CHECK_TRUE(est.valid);
  CHECK_TRUE(est.offset_us == 500);
  CHECK_TRUE(est.err_us == 6);
  // Bogus samples (clock went backwards) are skipped; none usable ->
  // invalid.
  ClockEstimate bad = EstimateClockOffset({{100, 0, 50}});
  CHECK_TRUE(!bad.valid);
  CHECK_TRUE(!EstimateClockOffset({}).valid);
}

void TestTraceSamplerGating() {
  TraceSampler s;
  CHECK_TRUE(!s.enabled());
  CHECK_TRUE(!s.SampleOp());  // disabled: never samples
  s.set_every_n(3);
  CHECK_TRUE(s.enabled());
  int sampled = 0;
  bool first = s.SampleOp();
  CHECK_TRUE(first);  // the first op is always sampled when enabled
  sampled += first ? 1 : 0;
  for (int i = 0; i < 8; ++i) sampled += s.SampleOp() ? 1 : 0;
  CHECK_TRUE(sampled == 3);  // ops 0, 3, 6 of the 9 rolled
  TraceSampler every;
  every.set_every_n(1);
  for (int i = 0; i < 4; ++i) CHECK_TRUE(every.SampleOp());
}

void TestTimelineSpanAndMetadata() {
  char path[] = "/tmp/hvdtpu_tl_span_XXXXXX";
  int fd = mkstemp(path);
  CHECK_TRUE(fd >= 0);
  close(fd);
  {
    Timeline tl;
    tl.Initialize(path, /*rank=*/3);
    const int64_t t0 = Timeline::SteadyAbsUs();
    tl.Span("hops", "SENDRECV", t0, t0 + 250,
            "{\"bytes\": 42, \"wait_us\": 7}");
    // A span predating the timeline origin clamps to ts 0, never negative.
    tl.Span("hops", "EARLY", t0 - 10'000'000, t0 - 9'999'000, "");
    tl.Metadata("{\"clock_offset_us\": -12, \"clock_err_us\": 5}");
    tl.Shutdown();
  }
  FILE* f = fopen(path, "r");
  CHECK_TRUE(f != nullptr);
  std::string text;
  char buf[512];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), f)) > 0) text.append(buf, n);
  fclose(f);
  unlink(path);
  CHECK_TRUE(text.find("\"ph\": \"X\"") != std::string::npos);
  CHECK_TRUE(text.find("\"dur\": 250") != std::string::npos);
  CHECK_TRUE(text.find("\"pid\": \"hops\"") != std::string::npos);
  CHECK_TRUE(text.find("\"tid\": 3") != std::string::npos);
  CHECK_TRUE(text.find("\"wait_us\": 7") != std::string::npos);
  CHECK_TRUE(text.find("trace_meta") != std::string::npos);
  CHECK_TRUE(text.find("\"clock_offset_us\": -12") != std::string::npos);
  CHECK_TRUE(text.find("\"ts\": -") == std::string::npos);  // no negatives
  // The clamp shrinks the DURATION too: a fully pre-origin span must not
  // spill past its true end (here: entirely before the origin -> dur 0).
  CHECK_TRUE(text.find("\"name\": \"EARLY\", \"ph\": \"X\", \"ts\": 0, "
                       "\"dur\": 0") != std::string::npos);
  // The file as a whole must still be a JSON array (same writer contract
  // as the op events).
  CHECK_TRUE(!text.empty() && text[0] == '[');
  CHECK_TRUE(text.find(']') != std::string::npos);
}

// Minimal decoder over the flight-recorder dump image (the production
// decoder is horovod_tpu/flightrec.py; this one pins the binary layout at
// the C++ boundary so a silent repack breaks HERE, not in a post-mortem).
struct FlightImage {
  int32_t rank = 0, world = 0, reason = -1, detail = 0;
  int64_t clock_offset = 0, clock_err = 0, write_count = 0;
  uint32_t capacity = 0, record_bytes = 0;
  std::vector<std::string> names;
  std::vector<FlightRecord> recs;
};

template <typename T>
T GetAt(const std::string& img, size_t off) {
  T v;
  std::memcpy(&v, img.data() + off, sizeof(T));
  return v;
}

FlightImage DecodeFlightImage(const std::string& img) {
  FlightImage out;
  CHECK_TRUE(img.size() >= kFlightHeaderBytes);
  CHECK_TRUE(std::memcmp(img.data(), kFlightMagic, 8) == 0);
  CHECK_TRUE(GetAt<uint32_t>(img, 8) == 1);   // version
  CHECK_TRUE(GetAt<uint32_t>(img, 12) == kFlightHeaderBytes);
  out.rank = GetAt<int32_t>(img, 16);
  out.world = GetAt<int32_t>(img, 20);
  out.clock_offset = GetAt<int64_t>(img, 24);
  out.clock_err = GetAt<int64_t>(img, 32);
  out.write_count = GetAt<int64_t>(img, 56);
  out.capacity = GetAt<uint32_t>(img, 64);
  out.record_bytes = GetAt<uint32_t>(img, 68);
  const uint32_t names = GetAt<uint32_t>(img, 72);
  const uint32_t name_bytes = GetAt<uint32_t>(img, 76);
  out.reason = GetAt<int32_t>(img, 80);
  out.detail = GetAt<int32_t>(img, 84);
  size_t off = kFlightHeaderBytes;
  for (uint32_t i = 0; i < names; ++i) {
    out.names.emplace_back(img.data() + off);  // NUL-terminated slot
    off += name_bytes;
  }
  while (off + out.record_bytes <= img.size()) {
    FlightRecord r;
    r.t_end_us = GetAt<int64_t>(img, off);
    const uint64_t w1 = GetAt<uint64_t>(img, off + 8);
    r.dur_us = static_cast<uint32_t>(w1 & 0xffffffffu);
    r.type = static_cast<FlightEvent>(
        static_cast<int32_t>((w1 >> 32) & 0xffff));
    r.lane = static_cast<uint16_t>(w1 >> 48);
    r.bytes = GetAt<int64_t>(img, off + 16);
    const uint64_t w3 = GetAt<uint64_t>(img, off + 24);
    r.name_id = static_cast<int32_t>(w3 & 0xffffffffu);
    r.arg = static_cast<int32_t>(w3 >> 32);
    const uint64_t w4 = GetAt<uint64_t>(img, off + 32);
    r.send_peer = static_cast<int32_t>(w4 & 0xffffffffu);
    r.recv_peer = static_cast<int32_t>(w4 >> 32);
    out.recs.push_back(r);
    off += out.record_bytes;
  }
  return out;
}

void TestFlightRecorderSnapshotRoundtrip() {
  FlightRecorder fr;
  fr.Configure(64, "", /*rank=*/2, /*world=*/4);
  fr.SetClock(1234, 56);
  const int nid = fr.InternName("layer0/kernel");
  CHECK_TRUE(nid == 1);  // slot 0 is the overflow name
  CHECK_TRUE(fr.InternName("layer0/kernel") == nid);
  fr.Record(FlightEvent::OP_BEGIN, nid, 4096, -1, -1, 1000, 1000, 0, 0);
  fr.Record(FlightEvent::SENDRECV, -1, 8192, 1, 3, 1100, 1400, 250, 2);
  fr.Record(FlightEvent::OP_END, nid, 4096, -1, -1, 1000, 1500, 0, 0);
  FlightImage img = DecodeFlightImage(
      fr.Snapshot(DumpReason::ON_DEMAND, -1));
  CHECK_TRUE(img.rank == 2 && img.world == 4);
  CHECK_TRUE(img.clock_offset == 1234 && img.clock_err == 56);
  CHECK_TRUE(img.reason == static_cast<int32_t>(DumpReason::ON_DEMAND));
  CHECK_TRUE(img.write_count == 3);
  CHECK_TRUE(img.recs.size() == 3);
  CHECK_TRUE(img.names.size() == 2 && img.names[1] == "layer0/kernel");
  CHECK_TRUE(img.recs[0].type == FlightEvent::OP_BEGIN);
  CHECK_TRUE(img.recs[0].name_id == nid);
  const FlightRecord& hop = img.recs[1];
  CHECK_TRUE(hop.type == FlightEvent::SENDRECV);
  CHECK_TRUE(hop.send_peer == 1 && hop.recv_peer == 3);
  CHECK_TRUE(hop.bytes == 8192 && hop.dur_us == 300 && hop.arg == 250);
  CHECK_TRUE(hop.lane == 2 && hop.name_id == -1);
  CHECK_TRUE(img.recs[2].t_end_us == 1500 && img.recs[2].dur_us == 500);
}

void TestFlightRecorderWraparoundOldestFirst() {
  FlightRecorder fr;
  fr.Configure(64, "", 0, 1);
  for (int i = 0; i < 150; ++i) {
    fr.Record(FlightEvent::SEND, -1, i, -1, -1, i, i, 0, 1);
  }
  FlightImage img = DecodeFlightImage(
      fr.Snapshot(DumpReason::ON_DEMAND, -1));
  CHECK_TRUE(img.write_count == 150);
  CHECK_TRUE(img.recs.size() == 64);
  // Oldest kept record is #86 (150 - 64), newest #149, strictly in order.
  CHECK_TRUE(img.recs.front().bytes == 86);
  CHECK_TRUE(img.recs.back().bytes == 149);
  for (size_t i = 1; i < img.recs.size(); ++i) {
    CHECK_TRUE(img.recs[i].bytes == img.recs[i - 1].bytes + 1);
  }
}

void TestFlightRecorderNameOverflowSharesSlotZero() {
  FlightRecorder fr;
  fr.Configure(64, "", 0, 1);
  int last = 0;
  for (int i = 0; i < kFlightMaxNames + 10; ++i) {
    last = fr.InternName("t" + std::to_string(i));
  }
  CHECK_TRUE(last == 0);  // overflowed names share the reserved slot
  FlightImage img = DecodeFlightImage(
      fr.Snapshot(DumpReason::ON_DEMAND, -1));
  CHECK_TRUE(img.names.size() == kFlightMaxNames);
  CHECK_TRUE(img.names[0] == "<names-overflowed>");
  CHECK_TRUE(img.names[1] == "t0");
}

void TestFlightRecorderConcurrentWriters() {
  // The ring is claimed by fetch_add and written with relaxed word stores:
  // hammer it from several threads (TSan build included in check-tsan)
  // while a reader snapshots mid-flight.
  FlightRecorder fr;
  fr.Configure(256, "", 0, 1);
  std::atomic<bool> stop{false};
  std::thread reader([&] {
    while (!stop.load()) {
      std::string img = fr.Snapshot(DumpReason::ON_DEMAND, -1);
      CHECK_TRUE(img.size() >= kFlightHeaderBytes);
    }
  });
  std::vector<std::thread> writers;
  for (int t = 0; t < 4; ++t) {
    writers.emplace_back([&fr, t] {
      for (int i = 0; i < 5000; ++i) {
        fr.Record(FlightEvent::SEND, -1, i, t, -1, i, i + 1, 0, 1);
      }
    });
  }
  for (auto& w : writers) w.join();
  stop.store(true);
  reader.join();
  CHECK_TRUE(fr.record_count() == 4 * 5000);
  FlightImage img = DecodeFlightImage(
      fr.Snapshot(DumpReason::ON_DEMAND, -1));
  CHECK_TRUE(img.recs.size() == 256);
  for (const FlightRecord& r : img.recs) {
    CHECK_TRUE(r.type == FlightEvent::SEND && r.dur_us == 1);
  }
}

void TestFlightRecorderDumpLatchAndOnDemand() {
  char tmpl[] = "/tmp/hvdtpu_frec_XXXXXX";
  char* dir = mkdtemp(tmpl);
  CHECK_TRUE(dir != nullptr);
  FlightRecorder fr;
  fr.Configure(64, dir, /*rank=*/1, /*world=*/2);
  fr.Record(FlightEvent::OP_BEGIN, -1, 1, -1, -1, 1, 1, 0, 0);
  // First fatal dump writes; the second is latched out (the original
  // failure's forensics must survive a later cascade).
  CHECK_TRUE(fr.DumpToFile(DumpReason::ABORT, 3, "", true));
  CHECK_TRUE(!fr.DumpToFile(DumpReason::STALL, -1, "", true));
  const std::string path = std::string(dir) + "/flightrec.1.bin";
  CHECK_TRUE(fr.dump_path() == path);
  FILE* f = std::fopen(path.c_str(), "rb");
  CHECK_TRUE(f != nullptr);
  std::string img;
  char buf[4096];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0) img.append(buf, n);
  std::fclose(f);
  FlightImage dec = DecodeFlightImage(img);
  CHECK_TRUE(dec.reason == static_cast<int32_t>(DumpReason::ABORT));
  CHECK_TRUE(dec.detail == 3);
  // On-demand dumps bypass the latch.
  const std::string alt = std::string(dir) + "/ondemand.bin";
  CHECK_TRUE(fr.DumpToFile(DumpReason::ON_DEMAND, -1, alt, false));
  std::remove(path.c_str());
  std::remove(alt.c_str());
  std::remove(dir);
}

void TestFlightRecorderSigtermDoesNotBurnLatch() {
  // A SIGTERM dump (watchdog/launcher cleanup — classified as "not the
  // cause" by the post-mortem) must leave the fatal latch armed so a
  // LATER genuine fatal can still record the real story; the reverse
  // order (fatal first) stays protected.
  char tmpl[] = "/tmp/hvdtpu_frst_XXXXXX";
  char* dir = mkdtemp(tmpl);
  CHECK_TRUE(dir != nullptr);
  FlightRecorder fr;
  fr.Configure(64, dir, 0, 1);
  fr.Record(FlightEvent::OP_BEGIN, -1, 1, -1, -1, 1, 1, 0, 0);
  fr.SignalDump(SIGTERM);
  // The abort cascade after the SIGTERM still gets its dump...
  CHECK_TRUE(fr.DumpToFile(DumpReason::ABORT, 2, "", true));
  // ...and now the latch holds: a later SIGTERM cannot overwrite it.
  const std::string path = fr.dump_path();
  FILE* f = std::fopen(path.c_str(), "rb");
  CHECK_TRUE(f != nullptr);
  std::string img;
  char buf[4096];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0) img.append(buf, n);
  std::fclose(f);
  CHECK_TRUE(DecodeFlightImage(img).reason ==
             static_cast<int32_t>(DumpReason::ABORT));
  CHECK_TRUE(!fr.DumpToFile(DumpReason::STALL, -1, "", true));
  std::remove(path.c_str());
  std::remove(dir);
}

void TestFlightLaneCodes() {
  CHECK_TRUE(FlightLaneCode("tcp") == 1);
  CHECK_TRUE(FlightLaneCode("shm") == 2);
  CHECK_TRUE(FlightLaneCode("tcp-zc") == 3);
  CHECK_TRUE(FlightLaneCode("local") == 0);
  CHECK_TRUE(FlightLaneCode(nullptr) == 0);
}

void TestDataPlaneRecordsFlightHops() {
  // A threaded 2-rank in-process world with the recorder attached: every
  // hop of an UNSAMPLED op (no tracer at all) must land in the ring.
  FlightRecorder fr0, fr1;
  fr0.Configure(1024, "", 0, 2);
  fr1.Configure(1024, "", 1, 2);
  DataPlane a(0, 2), b(1, 2);
  a.set_flightrec(&fr0);
  b.set_flightrec(&fr1);
  CHECK_TRUE(a.Listen().ok());
  CHECK_TRUE(b.Listen().ok());
  std::vector<PeerAddr> peers = {{"127.0.0.1", a.port()},
                                 {"127.0.0.1", b.port()}};
  Status sa, sb;
  std::thread tb([&] { sb = b.Connect(peers); });
  sa = a.Connect(peers);
  tb.join();
  CHECK_TRUE(sa.ok() && sb.ok());
  std::vector<float> va(1024, 1.0f), vb(1024, 2.0f);
  std::thread tr([&] {
    sb = b.Allreduce(vb.data(), 1024, DataType::FLOAT32, ReduceOp::SUM);
  });
  sa = a.Allreduce(va.data(), 1024, DataType::FLOAT32, ReduceOp::SUM);
  tr.join();
  CHECK_TRUE(sa.ok() && sb.ok());
  CHECK_TRUE(va[0] == 3.0f && vb[0] == 3.0f);
  FlightImage img = DecodeFlightImage(
      fr0.Snapshot(DumpReason::ON_DEMAND, -1));
  bool saw_hop = false, saw_reduce = false;
  for (const FlightRecord& r : img.recs) {
    if (r.type == FlightEvent::SENDRECV || r.type == FlightEvent::SEND ||
        r.type == FlightEvent::RECV) {
      saw_hop = true;
      CHECK_TRUE(r.send_peer == 1 || r.recv_peer == 1);
      CHECK_TRUE(r.bytes > 0);
    }
    if (r.type == FlightEvent::REDUCE) saw_reduce = true;
  }
  CHECK_TRUE(saw_hop);
  (void)saw_reduce;  // algo-dependent (RD at this size): hops are the pin
  a.Shutdown();
  b.Shutdown();
}

void TestIoControlWaitAccounting() {
  // A controlled recv with no data must accrue peer-wait time; completing
  // the transfer stops the clock.
  int sv[2];
  CHECK_TRUE(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  IoControl ctl;
  ctl.detect_slice_ms = 5;
  std::thread sender([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(30));
    const char b[4] = {1, 2, 3, 4};
    CHECK_TRUE(SendAll(sv[1], b, sizeof(b), nullptr) == 0);
  });
  char out[4];
  CHECK_TRUE(RecvAll(sv[0], out, sizeof(out), &ctl) == 0);
  sender.join();
  // ~30 ms blocked: the accounting must see most of it (scheduler slack
  // allowed) and not wildly more.
  CHECK_TRUE(ctl.WaitUs() >= 10'000);
  CHECK_TRUE(ctl.WaitUs() < 5'000'000);
  close(sv[0]);
  close(sv[1]);
}

void TestP2QuantileTracksSortedQuantiles() {
  // Deterministic LCG stream; the P² estimates must land near the exact
  // sorted quantiles (P² error on smooth distributions is a few percent).
  uint64_t seed = 42;
  auto next = [&seed]() {
    seed = seed * 6364136223846793005ULL + 1442695040888963407ULL;
    return static_cast<double>((seed >> 33) % 100000);
  };
  P2Quantile p50, p99;
  p50.Init(0.5);
  p99.Init(0.99);
  std::vector<double> all;
  for (int i = 0; i < 20000; ++i) {
    const double x = next();
    all.push_back(x);
    p50.Observe(x);
    p99.Observe(x);
  }
  std::sort(all.begin(), all.end());
  const double exact50 = all[all.size() / 2];
  const double exact99 = all[static_cast<size_t>(all.size() * 0.99)];
  CHECK_TRUE(std::abs(p50.Value() - exact50) < 0.05 * exact50 + 1);
  CHECK_TRUE(std::abs(p99.Value() - exact99) < 0.05 * exact99 + 1);
  // Tiny streams are exact (sorted initial buffer).
  P2Quantile small;
  small.Init(0.5);
  small.Observe(30);
  small.Observe(10);
  small.Observe(20);
  CHECK_TRUE(small.Value() == 20);
}

void TestPerfStatsBaselineAndSentry() {
  PerfStats ps;
  ps.Configure(true, 50.0, 5);
  const int slot = ps.KeySlot("grad/0|ring|shm|0|none");
  CHECK_TRUE(slot >= 1);
  CHECK_TRUE(ps.KeySlot("grad/0|ring|shm|0|none") == slot);  // stable
  PerfStats::OpSample s;
  s.wall_us = 1000;
  s.wait_us = 100;
  s.wire_us = 700;
  s.reduce_us = 150;
  s.codec_us = 0;
  for (int i = 0; i < 10; ++i) {
    PerfStats::Anomaly a = ps.RecordOp(slot, s);
    CHECK_TRUE(!a.fired);  // steady state never trips the sentry
  }
  // 10% slower: inside the 50% threshold.
  PerfStats::OpSample mild = s;
  mild.wall_us = 1100;
  CHECK_TRUE(!ps.RecordOp(slot, mild).fired);
  // 3x slower, excess in the wire bucket, slow peer named.
  PerfStats::OpSample slow = s;
  slow.wall_us = 3000;
  slow.wire_us = 2700;
  slow.slow_peer = 3;
  PerfStats::Anomaly a = ps.RecordOp(slot, slow);
  CHECK_TRUE(a.fired);
  CHECK_TRUE(a.phase == PerfPhase::WIRE);
  CHECK_TRUE(a.slow_peer == 3);
  CHECK_TRUE(a.ratio > 2.0);
  CHECK_TRUE(ps.anomalies_total() == 1);
  const PerfSlot* sl = ps.slot(slot);
  CHECK_TRUE(sl != nullptr &&
             sl->anomalies.load(std::memory_order_relaxed) == 1);
  // A reduce-bound slowdown attributes REDUCE, not WIRE.
  PerfStats::OpSample rslow = s;
  rslow.wall_us = 3000;
  rslow.reduce_us = 2200;
  PerfStats::Anomaly ra = ps.RecordOp(slot, rslow);
  CHECK_TRUE(ra.fired && ra.phase == PerfPhase::REDUCE);
  CHECK_TRUE(ra.slow_peer == -1);  // only wait/wire name a peer
  // Warmup gate: a fresh key never fires before min_samples.
  const int fresh = ps.KeySlot("other|ring|shm|0|none");
  PerfStats::OpSample burst = s;
  burst.wall_us = 100;
  ps.RecordOp(fresh, burst);
  burst.wall_us = 100000;
  CHECK_TRUE(!ps.RecordOp(fresh, burst).fired);
}

void TestPerfStatsKeyOverflowSharesSlotZero() {
  PerfStats ps;
  ps.Configure(true, 50.0, 5);
  for (int i = 0; i < kPerfMaxKeys + 16; ++i) {
    const int slot = ps.KeySlot("key" + std::to_string(i));
    if (i < kPerfMaxKeys - 1) {
      CHECK_TRUE(slot == i + 1);
    } else {
      CHECK_TRUE(slot == 0);  // table full: the shared overflow slot
    }
  }
  CHECK_TRUE(ps.slot_count() == kPerfMaxKeys);
  // The overflow slot streams stats but never sentries: its baseline
  // mixes every overflowed key, so a small op judged against big-op
  // history would fire forever.
  PerfStats::OpSample warm;
  warm.wall_us = 100;
  for (int i = 0; i < 8; ++i) CHECK_TRUE(!ps.RecordOp(0, warm).fired);
  PerfStats::OpSample spike;
  spike.wall_us = 100000;  // 1000x the slot-0 baseline
  CHECK_TRUE(!ps.RecordOp(0, spike).fired);
  CHECK_TRUE(ps.slot(0)->count.load(std::memory_order_relaxed) == 9);
  // Disabled stats hand every key slot 0 and never fire.
  PerfStats off;
  off.Configure(false, 50.0, 5);
  CHECK_TRUE(off.KeySlot("anything") == 0);
  PerfStats::OpSample s;
  s.wall_us = 100;
  CHECK_TRUE(!off.RecordOp(0, s).fired);
  CHECK_TRUE(off.SnapshotJson().find("\"enabled\": false") !=
             std::string::npos);
}

void TestPerfStatsConcurrentWritersAndReader() {
  // The production contract is single-writer, but the hot path must stay
  // correct (and TSan-clean) under explicitly concurrent writers plus a
  // mid-flight snapshot reader.
  PerfStats ps;
  ps.Configure(true, 1e12, 1);  // sentry effectively off: count integrity
  const int slot_a = ps.KeySlot("a");
  const int slot_b = ps.KeySlot("b");
  constexpr int kWriters = 4;
  constexpr int kPerWriter = 4000;
  std::atomic<bool> stop{false};
  std::thread reader([&] {
    while (!stop.load(std::memory_order_acquire)) {
      std::string json = ps.SnapshotJson();
      CHECK_TRUE(json.find("\"keys\"") != std::string::npos);
    }
  });
  std::vector<std::thread> writers;
  for (int w = 0; w < kWriters; ++w) {
    writers.emplace_back([&ps, slot_a, slot_b, w] {
      PerfStats::OpSample s;
      for (int i = 0; i < kPerWriter; ++i) {
        s.wall_us = 100 + (i % 7);
        s.wire_us = 50;
        s.wait_us = 25;
        ps.RecordOp(w % 2 == 0 ? slot_a : slot_b, s);
      }
    });
  }
  for (auto& t : writers) t.join();
  stop.store(true, std::memory_order_release);
  reader.join();
  const PerfSlot* a = ps.slot(slot_a);
  const PerfSlot* b = ps.slot(slot_b);
  CHECK_TRUE(a->count.load(std::memory_order_relaxed) ==
             kWriters / 2 * kPerWriter);
  CHECK_TRUE(b->count.load(std::memory_order_relaxed) ==
             kWriters / 2 * kPerWriter);
  const double ew = a->pub_ewma[0].load(std::memory_order_relaxed);
  CHECK_TRUE(ew >= 100 && ew <= 107);
}

void TestPerfStatsSnapshotJsonShape() {
  PerfStats ps;
  ps.Configure(true, 50.0, 20);
  const int slot = ps.KeySlot("grad\"quote\\slash|ring|tcp|0|int8");
  PerfStats::OpSample s;
  s.wall_us = 1234;
  s.wait_us = 100;
  s.wire_us = 1000;
  s.reduce_us = 100;
  s.codec_us = 34;
  ps.RecordOp(slot, s);
  const std::string json = ps.SnapshotJson();
  // Escaped key, all five phase buckets, count, and the sample ring.
  CHECK_TRUE(json.find("grad\\\"quote\\\\slash|ring|tcp|0|int8") !=
             std::string::npos);
  for (const char* phase : {"wall", "wait", "wire", "reduce", "codec"}) {
    CHECK_TRUE(json.find("\"" + std::string(phase) + "\": ") !=
               std::string::npos);
  }
  CHECK_TRUE(json.find("\"count\": 1") != std::string::npos);
  CHECK_TRUE(json.find("\"samples_us\": [1234]") != std::string::npos);
  CHECK_TRUE(json.find("\"p50_us\"") != std::string::npos);
  CHECK_TRUE(json.find("\"p99_us\"") != std::string::npos);
}

void TestDataPlanePerfPhaseAccumulation() {
  // A 2-rank in-process world with perf enabled and NO tracer/recorder:
  // the phase accumulators alone must light up for an unsampled op.
  DataPlane a(0, 2), b(1, 2);
  a.set_perf_enabled(true);
  b.set_perf_enabled(true);
  CHECK_TRUE(a.Listen().ok());
  CHECK_TRUE(b.Listen().ok());
  std::vector<PeerAddr> peers = {{"127.0.0.1", a.port()},
                                 {"127.0.0.1", b.port()}};
  Status sa, sb;
  std::thread tb([&] { sb = b.Connect(peers); });
  sa = a.Connect(peers);
  tb.join();
  CHECK_TRUE(sa.ok() && sb.ok());
  constexpr int64_t kCount = 1 << 18;  // 1 MB: big enough to see wire time
  std::vector<float> va(kCount, 1.0f), vb(kCount, 2.0f);
  std::thread tr([&] {
    sb = b.Allreduce(vb.data(), kCount, DataType::FLOAT32, ReduceOp::SUM);
  });
  sa = a.Allreduce(va.data(), kCount, DataType::FLOAT32, ReduceOp::SUM);
  tr.join();
  CHECK_TRUE(sa.ok() && sb.ok());
  CHECK_TRUE(va[0] == 3.0f);
  // Hop phases were accumulated (wait or wire — scheduling decides the
  // split) and the slow-peer tracker names the only peer when any wait
  // was seen at all.
  CHECK_TRUE(a.op_wait_us() + a.op_wire_us() > 0);
  CHECK_TRUE(a.op_wait_us() >= 0 && a.op_wire_us() >= 0);
  CHECK_TRUE(a.op_reduce_us() >= 0 && a.op_codec_us() >= 0);
  if (a.op_slow_peer() != -1) CHECK_TRUE(a.op_slow_peer() == 1);
  // An empty op early-returns before any hop runs — it must NOT inherit
  // the previous op's phase buckets (ObserveOp reads them regardless).
  CHECK_TRUE(a.Allreduce(va.data(), 0, DataType::FLOAT32,
                         ReduceOp::SUM).ok());
  CHECK_TRUE(a.op_wait_us() == 0 && a.op_wire_us() == 0);
  CHECK_TRUE(a.op_reduce_us() == 0 && a.op_codec_us() == 0);
  CHECK_TRUE(a.op_slow_peer() == -1);
  a.Shutdown();
  b.Shutdown();
}

void TestPerfStatsPerKeyWarnThrottle() {
  // ISSUE 14 satellite: the sentry's WARN throttle is per KEY, not a global
  // 1/s — a chatty slow key must not starve a second, different slow key's
  // first warning (two-key regression pin).
  PerfStats ps;
  ps.Configure(true, 50.0, 5);
  const int a = ps.KeySlot("chatty|ring|shm|0|none|ALLREDUCE");
  const int b = ps.KeySlot("quiet|ring|shm|0|none|ALLREDUCE");
  const int64_t t0 = 1000000;
  CHECK_TRUE(ps.ShouldWarn(a, t0));        // first anomaly of A logs
  CHECK_TRUE(!ps.ShouldWarn(a, t0 + 10));  // A throttled inside its window
  // The regression: B fires 10 us after A — under the old global throttle
  // this was silently swallowed for a second.
  CHECK_TRUE(ps.ShouldWarn(b, t0 + 10));
  CHECK_TRUE(!ps.ShouldWarn(b, t0 + 20));
  // Windows expire independently.
  CHECK_TRUE(ps.ShouldWarn(a, t0 + 1000000));
  CHECK_TRUE(!ps.ShouldWarn(b, t0 + 500000));
  CHECK_TRUE(ps.ShouldWarn(b, t0 + 10 + 1000000));
  // Out-of-range slots never warn (the disabled-stats slot-0 path is a
  // real slot and may warn; invalid ids must not touch memory).
  CHECK_TRUE(!ps.ShouldWarn(-1, t0));
  CHECK_TRUE(!ps.ShouldWarn(9999, t0));
  // Concurrent anomalies on ONE key inside one window: exactly one winner.
  const int c = ps.KeySlot("concurrent|ring|shm|0|none|ALLREDUCE");
  std::atomic<int> winners{0};
  std::vector<std::thread> threads;
  for (int i = 0; i < 4; ++i) {
    threads.emplace_back([&] {
      if (ps.ShouldWarn(c, 42000000)) winners.fetch_add(1);
    });
  }
  for (auto& t : threads) t.join();
  CHECK_TRUE(winners.load() == 1);
}

void TestProfilerPhaseScopePublishesAndRestores() {
  ProfThreadState* t = ProfThread();
  CHECK_TRUE(t->phase.load() == -1);
  CHECK_TRUE(t->op_id.load() == 0);
  {
    ProfOpScope op(7);
    CHECK_TRUE(t->op_id.load() == 7);
    CHECK_TRUE(t->phase.load() == static_cast<int32_t>(PerfPhase::WALL));
    {
      ProfPhaseScope wire(PerfPhase::WIRE);
      CHECK_TRUE(t->phase.load() == static_cast<int32_t>(PerfPhase::WIRE));
      {
        ProfPhaseScope wait(PerfPhase::WAIT);
        CHECK_TRUE(t->phase.load() ==
                   static_cast<int32_t>(PerfPhase::WAIT));
      }
      // Nested scope restored the outer phase, not the base.
      CHECK_TRUE(t->phase.load() == static_cast<int32_t>(PerfPhase::WIRE));
    }
    CHECK_TRUE(t->phase.load() == static_cast<int32_t>(PerfPhase::WALL));
  }
  CHECK_TRUE(t->phase.load() == -1);
  CHECK_TRUE(t->op_id.load() == 0);
}

void TestProfilerDisabledIsNoop() {
  SamplingProfiler p;
  p.Configure(false, 97, 1024, ProfClock::CPU, 0);
  p.RegisterThread();  // must not create a timer
  p.Start();
  CHECK_TRUE(!p.running());
  CHECK_TRUE(p.registered_threads() == 0);
  p.Stop();
  CHECK_TRUE(p.FoldedJson().find("\"enabled\": false") != std::string::npos);
  CHECK_TRUE(p.FoldedText().empty());
  CHECK_TRUE(p.InternOp("anything") == 0);
}

void TestProfilerSamplesTaggedByPhaseAndOp() {
  // A worker thread burns CPU inside ProfOpScope + REDUCE while a 250 Hz
  // CPU-clock window runs: samples must land, tagged with the published
  // phase and op, and fold into both the JSON and flamegraph outputs.
  SamplingProfiler p;
  p.Configure(true, 250, 4096, ProfClock::CPU, 3);
  const int op = p.InternOp("grad/layer0");
  CHECK_TRUE(op >= 1);
  CHECK_TRUE(p.InternOp("grad/layer0") == op);  // stable
  std::atomic<bool> stop{false};
  std::atomic<bool> ready{false};
  std::thread worker([&] {
    p.RegisterThread();
    ready.store(true);
    ProfOpScope op_scope(op);
    ProfPhaseScope reduce(PerfPhase::REDUCE);
    volatile double sink = 0;
    while (!stop.load(std::memory_order_relaxed)) {
      for (int i = 0; i < 1000; ++i) sink += i * 0.5;
    }
    p.UnregisterThread();
  });
  while (!ready.load()) std::this_thread::yield();
  p.Start();
  CHECK_TRUE(p.running());
  // CPU-clock timers need the worker to BURN ~n/hz seconds of CPU; a
  // loaded CI box may schedule it slowly, so wait on samples, not time.
  for (int i = 0; i < 400 && p.sample_count() < 5; ++i) {
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  p.Stop();
  CHECK_TRUE(!p.running());
  stop.store(true);
  worker.join();
  CHECK_TRUE(p.sample_count() >= 5);
  const std::string json = p.FoldedJson();
  CHECK_TRUE(json.find("\"enabled\": true") != std::string::npos);
  CHECK_TRUE(json.find("\"rank\": 3") != std::string::npos);
  CHECK_TRUE(json.find("\"clock\": \"cpu\"") != std::string::npos);
  CHECK_TRUE(json.find("\"reduce\"") != std::string::npos);
  CHECK_TRUE(json.find("grad/layer0") != std::string::npos);
  const std::string folded = p.FoldedText();
  CHECK_TRUE(folded.find("reduce;grad/layer0") != std::string::npos);
  // Every folded line is "stack count" with a positive count.
  size_t pos = 0;
  int lines = 0;
  while (pos < folded.size()) {
    const size_t eol = folded.find('\n', pos);
    CHECK_TRUE(eol != std::string::npos);
    const std::string line = folded.substr(pos, eol - pos);
    const size_t sp = line.rfind(' ');
    CHECK_TRUE(sp != std::string::npos && sp + 1 < line.size());
    CHECK_TRUE(std::atoll(line.c_str() + sp + 1) > 0);
    pos = eol + 1;
    ++lines;
  }
  CHECK_TRUE(lines > 0);
  // A new window clears the previous ring.
  p.Start();
  p.Stop();
  CHECK_TRUE(p.sample_count() == 0);
}

void TestProfilerWallClockSamplesBlockedThread() {
  // Wall-clock mode: a thread PARKED in a WAIT scope still accumulates
  // samples (the mode the chaos-delay acceptance test rides — blocked
  // time is exactly what it must see).
  SamplingProfiler p;
  p.Configure(true, 250, 4096, ProfClock::WALL, 0);
  std::atomic<bool> stop{false};
  std::atomic<bool> ready{false};
  std::thread worker([&] {
    p.RegisterThread();
    ready.store(true);
    ProfPhaseScope wait(PerfPhase::WAIT);
    while (!stop.load(std::memory_order_relaxed)) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
    p.UnregisterThread();
  });
  while (!ready.load()) std::this_thread::yield();
  p.Start();
  for (int i = 0; i < 400 && p.sample_count() < 5; ++i) {
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  p.Stop();
  stop.store(true);
  worker.join();
  CHECK_TRUE(p.sample_count() >= 5);
  CHECK_TRUE(p.FoldedJson().find("\"wait\"") != std::string::npos);
}

void TestProfilerSigprofStormDuringFlightDump() {
  // ISSUE 14 satellite (signal coexistence): a SIGPROF storm hammering the
  // thread that is writing a flight-recorder fatal dump must corrupt
  // nothing — the dump stays decodable and the profiler keeps sampling.
  SamplingProfiler p;
  p.Configure(true, 500, 4096, ProfClock::WALL, 0);
  p.RegisterThread();
  p.Start();

  FlightRecorder rec;
  char dir[] = "/tmp/hvdtpu_prof_storm_XXXXXX";
  CHECK_TRUE(mkdtemp(dir) != nullptr);
  rec.Configure(512, dir, 1, 2);
  const int name = rec.InternName("storm/op");
  for (int i = 0; i < 600; ++i) {
    rec.Record(FlightEvent::SENDRECV, name, 1024, 0, 0, i * 10, i * 10 + 5,
               2, 2);
  }
  // Storm: a sibling thread fires SIGPROF at this thread far faster than
  // the timer would, while the async-signal-safe dump runs.
  std::atomic<bool> stop{false};
  pthread_t victim = pthread_self();
  std::thread stormer([&] {
    while (!stop.load(std::memory_order_relaxed)) {
      pthread_kill(victim, SIGPROF);
      std::this_thread::sleep_for(std::chrono::microseconds(50));
    }
  });
  for (int i = 0; i < 20; ++i) {
    rec.SignalDump(SIGTERM);  // SIGTERM re-arms the latch: every pass writes
  }
  const std::string img = rec.Snapshot(DumpReason::ON_DEMAND, -1);
  stop.store(true);
  stormer.join();
  // Dump still valid: magic + the records survive.
  CHECK_TRUE(img.size() > kFlightHeaderBytes);
  CHECK_TRUE(std::memcmp(img.data(), kFlightMagic, sizeof(kFlightMagic)) ==
             0);
  const std::string path =
      std::string(dir) + "/flightrec.1.bin";
  FILE* f = std::fopen(path.c_str(), "rb");
  CHECK_TRUE(f != nullptr);
  if (f != nullptr) {
    char magic[8] = {0};
    CHECK_TRUE(std::fread(magic, 1, 8, f) == 8);
    CHECK_TRUE(std::memcmp(magic, kFlightMagic, 8) == 0);
    std::fclose(f);
  }
  p.Stop();
  p.UnregisterThread();
  unlink(path.c_str());
  rmdir(dir);
  // The fatal-signal handlers mask SIGPROF while they run (the other half
  // of coexistence): pin the installed disposition's mask.
  InstallFlightSignalHandlers();
  struct sigaction current;
  CHECK_TRUE(sigaction(SIGSEGV, nullptr, &current) == 0);
  CHECK_TRUE(sigismember(&current.sa_mask, SIGPROF) == 1);
}

// --- numerical-health telemetry (gradstats.h; docs/numerics.md) -------------

void TestCrc32cKnownAnswers() {
  // RFC 3720 B.4 test vectors (Castagnoli polynomial).
  const uint8_t zeros[32] = {0};
  CHECK_TRUE(Crc32c(zeros, 32) == 0x8a9136aau);
  uint8_t ones[32];
  memset(ones, 0xff, sizeof(ones));
  CHECK_TRUE(Crc32c(ones, 32) == 0x62a8ab43u);
  uint8_t inc[32];
  for (int i = 0; i < 32; ++i) inc[i] = static_cast<uint8_t>(i);
  CHECK_TRUE(Crc32c(inc, 32) == 0x46dd794eu);
  // "123456789" is the classic check value for CRC-32C: 0xe3069283.
  CHECK_TRUE(Crc32c("123456789", 9) == 0xe3069283u);
  // One flipped byte MUST change the fingerprint (the divergence probe's
  // whole premise, and exactly what chaos corrupt@op injects).
  uint8_t flipped[32] = {0};
  flipped[0] ^= 0x01;
  CHECK_TRUE(Crc32c(flipped, 32) != Crc32c(zeros, 32));
}

void TestMomentsCountNanInfAndNorm() {
  std::vector<float> v(1027, 0.0f);  // odd size: exercises the scalar tail
  for (size_t i = 0; i < v.size(); ++i) v[i] = (i % 2 == 0) ? 3.0f : -4.0f;
  GradMoments m;
  MomentsF32(v.data(), static_cast<int64_t>(v.size()), &m);
  CHECK_TRUE(m.count == static_cast<int64_t>(v.size()));
  CHECK_TRUE(m.nonfinite == 0);
  CHECK_TRUE(std::fabs(m.absmax - 4.0) < 1e-9);
  const double want = 514 * 9.0 + 513 * 16.0;
  CHECK_TRUE(std::fabs(m.sumsq - want) < 1e-6 * want);
  // NaN/Inf lanes are COUNTED, not folded into the norm: one bad element
  // must not erase the other thousand's magnitude.
  v[7] = std::numeric_limits<float>::quiet_NaN();
  v[900] = std::numeric_limits<float>::infinity();
  v[1024] = -std::numeric_limits<float>::infinity();  // in the scalar tail
  GradMoments m2;
  MomentsF32(v.data(), static_cast<int64_t>(v.size()), &m2);
  CHECK_TRUE(m2.nonfinite == 3);
  CHECK_TRUE(std::isfinite(m2.sumsq));
  CHECK_TRUE(std::fabs(m2.absmax - 4.0) < 1e-9);
  CHECK_TRUE(m2.sumsq < m.sumsq && m2.sumsq > 0.9 * m.sumsq);
}

void TestCopyMomentsMatchesMemcpyAndScan() {
  std::vector<float> src(4099);
  for (size_t i = 0; i < src.size(); ++i) {
    src[i] = std::sin(static_cast<double>(i)) * 7.5f;
  }
  src[17] = std::numeric_limits<float>::quiet_NaN();
  std::vector<float> dst(src.size(), -1.0f);
  GradMoments mc;
  CopyMomentsF32(dst.data(), src.data(),
                 static_cast<int64_t>(src.size()), &mc);
  CHECK_TRUE(memcmp(dst.data(), src.data(), src.size() * 4) == 0);
  GradMoments ms;
  MomentsF32(src.data(), static_cast<int64_t>(src.size()), &ms);
  CHECK_TRUE(mc.count == ms.count);
  CHECK_TRUE(mc.nonfinite == ms.nonfinite && mc.nonfinite == 1);
  CHECK_TRUE(std::fabs(mc.sumsq - ms.sumsq) < 1e-6 * (ms.sumsq + 1));
  CHECK_TRUE(mc.absmax == ms.absmax);
  // Streaming-store path (buffers past the NT threshold, including an
  // odd tail and a deliberately misaligned destination): bitwise-equal
  // copy, identical moments.
  const int64_t big = (4 << 20) / 4 + 13;
  std::vector<float> bsrc(static_cast<size_t>(big));
  for (int64_t i = 0; i < big; ++i) {
    bsrc[static_cast<size_t>(i)] = std::sin(static_cast<double>(i)) * 3.0f;
  }
  bsrc[12345] = std::numeric_limits<float>::infinity();
  std::vector<float> bdst(static_cast<size_t>(big) + 1, -7.0f);
  GradMoments mb;
  CopyMomentsF32(bdst.data() + 1, bsrc.data(), big, &mb);  // unaligned dst
  CHECK_TRUE(memcmp(bdst.data() + 1, bsrc.data(),
                    static_cast<size_t>(big) * 4) == 0);
  GradMoments mbs;
  MomentsF32(bsrc.data(), big, &mbs);
  CHECK_TRUE(mb.count == big && mb.nonfinite == 1);
  CHECK_TRUE(std::fabs(mb.sumsq - mbs.sumsq) < 1e-9 * (mbs.sumsq + 1));
  CHECK_TRUE(mb.absmax == mbs.absmax);
  // ByteBuf (default-init allocator): resize must not zero — fill, shrink,
  // regrow, and the old bytes reappear (proving no value-init pass runs).
  ByteBuf bb;
  bb.resize(64);
  memset(bb.data(), 0xAB, 64);
  bb.resize(0);
  bb.resize(64);
  CHECK_TRUE(bb[0] == 0xAB && bb[63] == 0xAB);
}

void TestWireCompressQualityAccumulation() {
  // Quality rides the quantize kernels: err2/sig2 must reflect the actual
  // round-trip error, so coarser codes score strictly lower SNR.
  std::vector<float> src(2000);
  for (size_t i = 0; i < src.size(); ++i) {
    src[i] = std::cos(static_cast<double>(i) * 0.37) * 2.0f + 0.1f;
  }
  auto snr_of = [&](WireCompression c) {
    std::vector<uint8_t> wire(
        static_cast<size_t>(WireBytes(c, src.size())));
    std::vector<float> decoded(src.size());
    GradQuality q;
    WireCompress(c, src.data(), static_cast<int64_t>(src.size()),
                 wire.data(), nullptr, nullptr, &q);
    CHECK_TRUE(q.count == static_cast<int64_t>(src.size()));
    CHECK_TRUE(q.sig2 > 0);
    // Cross-check err2 against an explicit decode pass.
    WireDecompress(c, wire.data(), static_cast<int64_t>(src.size()),
                   decoded.data());
    double err2 = 0;
    for (size_t i = 0; i < src.size(); ++i) {
      const double d = src[i] - decoded[i];
      err2 += d * d;
    }
    CHECK_TRUE(std::fabs(q.err2 - err2) < 1e-6 * (err2 + 1e-12));
    return q.err2 > 0 ? 10.0 * std::log10(q.sig2 / q.err2) : 1e9;
  };
  const double snr_fp16 = snr_of(WireCompression::FP16);
  const double snr_int8 = snr_of(WireCompression::INT8);
  const double snr_int4 = snr_of(WireCompression::INT4);
  CHECK_TRUE(snr_fp16 > snr_int8);
  CHECK_TRUE(snr_int8 > snr_int4);
  CHECK_TRUE(snr_int4 > 0);
  // With residual error feedback active, err2 equals the post-op residual
  // content (residual[i] = x - deq): the residual-norm telemetry contract.
  std::vector<float> residual(src.size(), 0.0f);
  std::vector<uint8_t> wire(
      static_cast<size_t>(WireBytes(WireCompression::INT8, src.size())));
  GradQuality q;
  WireCompress(WireCompression::INT8, src.data(),
               static_cast<int64_t>(src.size()), wire.data(),
               residual.data(), nullptr, &q);
  double res2 = 0;
  for (float r : residual) res2 += static_cast<double>(r) * r;
  CHECK_TRUE(std::fabs(q.err2 - res2) < 1e-6 * (res2 + 1e-12));
}

void TestResidualStoreResetReporting() {
  ResidualStore store;
  bool reset = true;
  float* a = store.Get("w", 100, &reset);
  CHECK_TRUE(a != nullptr);
  CHECK_TRUE(!reset);  // first use is not a reset
  a[0] = 1.5f;
  CHECK_TRUE(store.Get("w", 100, &reset) == a && !reset);
  CHECK_TRUE(a[0] == 1.5f);  // steady state keeps the feedback
  // Element count changed on a live key (reshape / refused fusion): the
  // feedback restarts from zero AND the caller is told.
  float* b = store.Get("w", 64, &reset);
  CHECK_TRUE(reset);
  CHECK_TRUE(b[0] == 0.0f);
  CHECK_TRUE(store.TotalBytes() == 64 * 4);
  // Cap overflow clears every live key: also a reset (fresh store so the
  // clear fires exactly at the probe, not mid-fill).
  ResidualStore full;
  for (size_t i = 0; i < ResidualStore::kMaxEntries; ++i) {
    full.Get("k" + std::to_string(i), 8, nullptr);
  }
  full.Get("one-more", 8, &reset);
  CHECK_TRUE(reset);
}

void TestGradStatsSlotsAndSnapshot() {
  GradStats gs;
  gs.Configure(true, NanPolicy::WARN, 16);
  CHECK_TRUE(gs.enabled());
  CHECK_TRUE(gs.nan_policy() == NanPolicy::WARN);
  CHECK_TRUE(gs.gradcheck_sample() == 16);
  const int s1 = gs.KeySlot("layer/w");
  const int s2 = gs.KeySlot("layer/bias");
  CHECK_TRUE(s1 >= 1 && s2 >= 1 && s1 != s2);
  CHECK_TRUE(gs.KeySlot("layer/w") == s1);
  GradMoments m;
  m.sumsq = 16.0;
  m.absmax = 3.0;
  m.count = 10;
  gs.RecordMoments(s1, m);
  gs.RecordMoments(s2, m);
  GradQuality q;
  q.err2 = 1.0;
  q.sig2 = 100.0;
  q.count = 10;
  gs.RecordQuality(s1, WireCompression::INT4, q);
  gs.NoteNonfinite(2);
  gs.NoteProbe();
  gs.NoteDivergence();
  gs.NoteResidualReset();
  const GradSlot* sl = gs.slot(s1);
  CHECK_TRUE(sl != nullptr);
  CHECK_TRUE(std::fabs(sl->pub_norm.load() - 4.0) < 1e-9);
  CHECK_TRUE(std::fabs(sl->pub_snr_db.load() - 20.0) < 1e-9);
  CHECK_TRUE(std::fabs(sl->pub_res_norm.load() - 1.0) < 1e-9);
  const std::string json = gs.SnapshotJson();
  // Shape: totals + both keys; SNR fields ONLY on the compressed key —
  // the bias slot (never quantized) must stay absent from the SNR report.
  CHECK_TRUE(json.find("\"nonfinite_total\": 2") != std::string::npos);
  CHECK_TRUE(json.find("\"probes_total\": 1") != std::string::npos);
  CHECK_TRUE(json.find("\"divergence_total\": 1") != std::string::npos);
  CHECK_TRUE(json.find("\"residual_resets_total\": 1") != std::string::npos);
  CHECK_TRUE(json.find("\"nancheck\": \"warn\"") != std::string::npos);
  CHECK_TRUE(json.find("layer/w") != std::string::npos);
  CHECK_TRUE(json.find("layer/bias") != std::string::npos);
  const size_t bias_at = json.find("layer/bias");
  const size_t w_at = json.find("\"key\": \"layer/w\"");
  const size_t snr_at = json.find("\"snr_db\":");
  CHECK_TRUE(snr_at != std::string::npos);
  // Exactly one snr_db field (only the quantized key carries one).
  CHECK_TRUE(json.find("\"snr_db\":", snr_at + 1) == std::string::npos);
  CHECK_TRUE(json.find("\"compression\": \"int4\"") != std::string::npos);
  (void)bias_at;
  (void)w_at;
  // Key overflow: past the cap everything shares slot 0.
  for (int i = 0; i < kGradMaxKeys + 8; ++i) {
    gs.KeySlot("overflow/" + std::to_string(i));
  }
  CHECK_TRUE(gs.KeySlot("one-more") == 0);
}

void TestGradStatsNonfiniteWarnThrottle() {
  // A NaN-flooded tensor warns (and flight-records) at most once per
  // window PER KEY; a second key's first event is never starved.
  GradStats gs;
  gs.Configure(true, NanPolicy::WARN, 0);
  const int s1 = gs.KeySlot("flood/w");
  const int s2 = gs.KeySlot("other/w");
  CHECK_TRUE(gs.ShouldWarnNonfinite(s1, 1000));      // first always passes
  CHECK_TRUE(!gs.ShouldWarnNonfinite(s1, 500000));   // inside the window
  CHECK_TRUE(gs.ShouldWarnNonfinite(s2, 600000));    // other key unstarved
  CHECK_TRUE(gs.ShouldWarnNonfinite(s1, 1000 + 1000000));  // window over
  CHECK_TRUE(!gs.ShouldWarnNonfinite(-1, 0));        // bad slot: quiet
}

void TestGradStatsDisabledIsNoop() {
  GradStats gs;
  gs.Configure(false, NanPolicy::ABORT, 4);
  CHECK_TRUE(!gs.enabled());
  CHECK_TRUE(gs.KeySlot("x") == 0);
  GradMoments m;
  m.count = 1;
  gs.RecordMoments(0, m);  // must not crash with no slot storage
  const std::string json = gs.SnapshotJson();
  CHECK_TRUE(json.find("\"enabled\": false") != std::string::npos);
}

void TestGradStatsConcurrentWritersAndReader() {
  // TSan fixture: four writers hammer two slots while a reader snapshots
  // — same weak-consistency contract as PerfStats (torn sets, never torn
  // values, never a crash).
  GradStats gs;
  gs.Configure(true, NanPolicy::WARN, 8);
  const int s1 = gs.KeySlot("a");
  const int s2 = gs.KeySlot("b");
  std::atomic<bool> stop{false};
  std::vector<std::thread> writers;
  for (int t = 0; t < 4; ++t) {
    writers.emplace_back([&, t] {
      GradMoments m;
      m.sumsq = 4.0 + t;
      m.absmax = 2.0;
      m.count = 8;
      GradQuality q;
      q.err2 = 0.5;
      q.sig2 = 50.0;
      q.count = 8;
      for (int i = 0; i < 2000; ++i) {
        gs.RecordMoments(t % 2 == 0 ? s1 : s2, m);
        gs.RecordQuality(t % 2 == 0 ? s1 : s2, WireCompression::INT8, q);
        gs.NoteNonfinite(1);
      }
    });
  }
  std::thread reader([&] {
    while (!stop.load(std::memory_order_relaxed)) {
      const std::string json = gs.SnapshotJson();
      CHECK_TRUE(json.find("\"keys\": [") != std::string::npos);
    }
  });
  for (auto& w : writers) w.join();
  stop.store(true, std::memory_order_relaxed);
  reader.join();
  CHECK_TRUE(gs.nonfinite_total() == 4 * 2000);
  CHECK_TRUE(gs.slot(s1)->count.load() +
                 gs.slot(s2)->count.load() ==
             4 * 2000);
}

}  // namespace
}  // namespace hvdtpu

int main() {
  using namespace hvdtpu;
  TestRequestRoundtrip();
  TestResponseRoundtrip();
  TestReaderTruncationIsSafe();
  TestHalfConversionRoundtrip();
  TestHalfConversionSpecialValues();
  TestHalfConversionExhaustive();
  TestHalfRoundToNearestEven();
  TestReduceBufferHalfMatchesScalar();
  TestSendRecvSegmented();
  TestSendAllVecExactConcatenation();
  TestShmRingWraparound();
  TestShmDoorbellBatchingCoalesces();
  TestShmInPlaceViewsAlignedAcrossWrap();
  TestShmViewsNeverMisaligned();
  TestNumaProbeAndPolicy();
  TestShmDoorbellWakeup();
  TestShmAbortCleanup();
  TestShmKilledPeerWakesWaiter();
  TestIoControlRecvFailsFastOnPeerClose();
  TestIoControlAbortBreaksBlockedRecv();
  TestIoControlReadDeadlineTripsOnSilentPeer();
  TestShmReadDeadlineTripsOnSilentPeer();
  TestZeroCopyProbeFallbackBitwise(ZeroCopyMode::ON);
  TestZeroCopyProbeFallbackBitwise(ZeroCopyMode::AUTO);
  TestZeroCopyTcpSendBitwise(ZeroCopyMode::ON);
  TestZeroCopyTcpSendBitwise(ZeroCopyMode::AUTO);
  TestZeroCopyTcpSendBitwise(ZeroCopyMode::URING);
  TestZeroCopyKilledPeerFailsFast();
  TestDataPlaneAllreduceAlgos();
  TestDataPlaneZeroCopyMatchesCopyPathBitwise();
  TestDataPlaneZeroCopyDropAborts();
  TestDataPlaneScaleAlgosBitwise();
  TestDataPlaneScaleAlgosDropAborts();
  TestDataPlaneHierarchicalAllreduce();
  TestWireQuantizerRoundTrip();
  TestWireInt4PackingAndTail();
  TestWireErrorFeedbackConvergence();
  TestDataPlaneCompressedAllreduce();
  TestDataPlaneReduceScatter();
  TestDataPlaneAllgatherv();
  TestDataPlaneBroadcast();
  TestDataPlaneAlltoallv();
  TestDataPlaneCompressedHierarchical();
  TestReduceBufferOps();
  TestMetricsConcurrentIncrements();
  TestMetricsHistogramBucketBoundaries();
  TestMetricsDumpDeterminism();
  TestMetricsLabelEscaping();
  TestDataPlaneWireCountersInRegistry();
  TestGaussianProcessInterpolates();
  TestBayesianOptimizerPicksBestSample();
  TestParameterManagerFreezesAtBest();
  TestClockOffsetEstimator();
  TestTraceSamplerGating();
  TestTimelineSpanAndMetadata();
  TestIoControlWaitAccounting();
  TestFlightRecorderSnapshotRoundtrip();
  TestFlightRecorderWraparoundOldestFirst();
  TestFlightRecorderNameOverflowSharesSlotZero();
  TestFlightRecorderConcurrentWriters();
  TestFlightRecorderDumpLatchAndOnDemand();
  TestFlightRecorderSigtermDoesNotBurnLatch();
  TestFlightLaneCodes();
  TestDataPlaneRecordsFlightHops();
  TestP2QuantileTracksSortedQuantiles();
  TestPerfStatsBaselineAndSentry();
  TestPerfStatsKeyOverflowSharesSlotZero();
  TestPerfStatsConcurrentWritersAndReader();
  TestPerfStatsSnapshotJsonShape();
  TestDataPlanePerfPhaseAccumulation();
  TestPerfStatsPerKeyWarnThrottle();
  TestProfilerPhaseScopePublishesAndRestores();
  TestProfilerDisabledIsNoop();
  TestProfilerSamplesTaggedByPhaseAndOp();
  TestProfilerWallClockSamplesBlockedThread();
  TestProfilerSigprofStormDuringFlightDump();
  TestCrc32cKnownAnswers();
  TestMomentsCountNanInfAndNorm();
  TestCopyMomentsMatchesMemcpyAndScan();
  TestWireCompressQualityAccumulation();
  TestResidualStoreResetReporting();
  TestGradStatsSlotsAndSnapshot();
  TestGradStatsNonfiniteWarnThrottle();
  TestGradStatsDisabledIsNoop();
  TestGradStatsConcurrentWritersAndReader();
  if (failures == 0) {
    std::printf("native unit tests: ALL OK\n");
    return 0;
  }
  std::fprintf(stderr, "native unit tests: %d failure(s)\n", failures);
  return 1;
}
