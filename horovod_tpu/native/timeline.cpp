#include "timeline.h"

namespace hvdtpu {

Timeline::~Timeline() { Shutdown(); }

void Timeline::Initialize(const std::string& path, int rank) {
  MutexLock st(state_mu_);
  if (initialized_ || path.empty()) return;
  file_ = fopen(path.c_str(), "w");
  if (file_ == nullptr) return;
  rank_ = rank;
  start_ = std::chrono::steady_clock::now();
  fputs("[\n", file_);
  first_ = true;
  {
    // Drop events raced in after a previous Shutdown drained the writer,
    // and clear the previous run's stop flag.
    MutexLock lk(mu_);
    while (!queue_.empty()) queue_.pop();
    stop_ = false;
  }
  initialized_ = true;
  writer_ = std::thread([this] { WriterLoop(); });
}

void Timeline::Shutdown() {
  {
    // Flip the flag under state_mu_: Emit holds state_mu_ for its whole
    // body, so after this block no emitter can be touching timeline state.
    MutexLock st(state_mu_);
    if (!initialized_) return;
    initialized_ = false;
  }
  {
    MutexLock lk(mu_);
    stop_ = true;
  }
  cv_.NotifyAll();
  if (writer_.joinable()) writer_.join();
  MutexLock st(state_mu_);
  fputs("\n]\n", file_);
  fclose(file_);
  file_ = nullptr;
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

namespace {

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out += '\\';
      out += c;
    } else if (static_cast<unsigned char>(c) < 0x20) {
      out += ' ';
    } else {
      out += c;
    }
  }
  return out;
}

}  // namespace

void Timeline::Push(std::string json) {
  MutexLock lk(mu_);
  queue_.push(Event{std::move(json)});
}

int64_t Timeline::init_steady_us() {
  MutexLock st(state_mu_);
  if (!initialized_) return 0;
  return std::chrono::duration_cast<std::chrono::microseconds>(
             start_.time_since_epoch())
      .count();
}

void Timeline::Span(const std::string& track, const std::string& name,
                    int64_t start_abs_us, int64_t end_abs_us,
                    const std::string& args_json) {
  int64_t origin_us;
  int rank;
  {
    MutexLock st(state_mu_);
    if (!initialized_) return;
    origin_us = std::chrono::duration_cast<std::chrono::microseconds>(
                    start_.time_since_epoch())
                    .count();
    rank = rank_;
  }
  // A span whose start predates the timeline (runtime start mid-op, or a
  // FUSION-WAIT whose tensor was enqueued before tracing began) is clamped
  // to the origin rather than dropped or emitted at a negative ts — and
  // the duration shrinks with it, so the rendered span still ENDS at its
  // true end instead of spilling past it.
  int64_t ts = start_abs_us - origin_us;
  if (ts < 0) ts = 0;
  int64_t end_ts = end_abs_us - origin_us;
  int64_t dur = end_ts - ts;
  if (dur < 0) dur = 0;
  std::string e = "{\"name\": \"" + JsonEscape(name) + "\", \"ph\": \"X\"";
  e += ", \"ts\": " + std::to_string(ts);
  e += ", \"dur\": " + std::to_string(dur);
  e += ", \"pid\": \"" + JsonEscape(track) + "\", \"tid\": " +
       std::to_string(rank);
  if (!args_json.empty()) e += ", \"args\": " + args_json;
  e += "}";
  Push(std::move(e));
}

void Timeline::Metadata(const std::string& args_json) {
  int64_t ts;
  int rank;
  {
    MutexLock st(state_mu_);
    if (!initialized_) return;
    ts = NowUs();
    rank = rank_;
  }
  std::string e = "{\"name\": \"trace_meta\", \"ph\": \"i\", \"s\": \"g\"";
  e += ", \"ts\": " + std::to_string(ts);
  e += ", \"pid\": \"" + std::string(kTraceMetaTrack) + "\", \"tid\": " +
       std::to_string(rank);
  if (!args_json.empty()) e += ", \"args\": " + args_json;
  e += "}";
  Push(std::move(e));
}

void Timeline::Emit(const std::string& name, char ph,
                    const std::string& args_json, const std::string& cat) {
  // Snapshot under state_mu_ (so a concurrent runtime Shutdown/Initialize
  // can't mutate start_/rank_ mid-read), then build the JSON outside it —
  // emitters shouldn't serialize on heap work.
  int64_t ts;
  int rank;
  {
    MutexLock st(state_mu_);
    if (!initialized_) return;
    ts = NowUs();
    rank = rank_;
  }
  // One row ("pid") per tensor name, one thread row per rank — mirrors the
  // reference's tensor-as-process layout (timeline.cc:254-276). Built with
  // std::string so long tensor names can't truncate into invalid JSON.
  std::string e = "{\"name\": \"";
  e += JsonEscape(cat.empty() ? name : cat);
  e += "\", \"ph\": \"";
  e += ph;
  e += "\", \"ts\": " + std::to_string(ts);
  e += ", \"pid\": \"" + JsonEscape(name) + "\", \"tid\": " +
       std::to_string(rank);
  if (!args_json.empty()) e += ", \"args\": " + args_json;
  if (!cat.empty()) e += ", \"cat\": \"" + JsonEscape(cat) + "\"";
  e += "}";
  Push(std::move(e));
}

void Timeline::WriterLoop() {
  MutexLock lk(mu_);
  while (true) {
    // Batched drain: per-event wakes preempt the collective thread on
    // small hosts, and a short free-running timer fires mid-op — so the
    // writer is nudged only at OP BOUNDARIES (OpDone/Shutdown), where the
    // emitting thread is about to idle on the control plane anyway. The
    // 1 s timed wait is a backstop for op-less stretches (metadata-only
    // traces, mark-cycles while idle).
    while (!stop_ && queue_.empty()) cv_.WaitForMs(lk, 1000);
    while (!queue_.empty()) {
      Event e = std::move(queue_.front());
      queue_.pop();
      lk.Unlock();
      if (!first_) fputs(",\n", file_);
      first_ = false;
      fputs(e.json.c_str(), file_);
      lk.Lock();
    }
    if (stop_ && queue_.empty()) break;
  }
  fflush(file_);
}

void Timeline::NegotiateStart(const std::string& name) {
  Emit(name, 'B', "", "NEGOTIATE");
}

void Timeline::NegotiateEnd(const std::string& name) { Emit(name, 'E', ""); }

void Timeline::QueueStart(const std::string& name) {
  Emit(name, 'B', "", "QUEUE");
}

void Timeline::ActivityStart(const std::string& name,
                             const std::string& activity,
                             const std::string& transport,
                             const std::string& compression) {
  std::string args;
  if (!transport.empty()) {
    args = "\"transport\": \"" + JsonEscape(transport) + "\"";
  }
  if (!compression.empty()) {
    if (!args.empty()) args += ", ";
    args += "\"compression\": \"" + JsonEscape(compression) + "\"";
  }
  Emit(name, 'B', args.empty() ? std::string() : "{" + args + "}", activity);
}

void Timeline::ActivityEnd(const std::string& name) { Emit(name, 'E', ""); }

void Timeline::OpDone(const std::string& name, const std::string& result,
                      int64_t raw_bytes, int64_t wire_bytes) {
  // Escape like every other arg: failure reasons embed tensor names, and a
  // quote/backslash there would corrupt the whole trace file.
  std::string args = "{\"result\": \"" + JsonEscape(result) + "\"";
  if (raw_bytes >= 0 && wire_bytes >= 0) {
    args += ", \"raw_bytes\": " + std::to_string(raw_bytes) +
            ", \"wire_bytes\": " + std::to_string(wire_bytes);
  }
  Emit(name, 'E', args + "}");
  // Op boundary: the only wake the hot path pays. The background thread
  // is about to return to the control-plane pump, so the writer's drain
  // (this op's phases + any sampled hop spans) runs in the gap between
  // ops instead of preempting a pipelined exchange.
  cv_.NotifyOne();
}

void Timeline::MarkCycle() {
  MutexLock st(state_mu_);
  if (!initialized_) return;
  char buf[160];
  snprintf(buf, sizeof(buf),
           "{\"name\": \"CYCLE %d\", \"ph\": \"i\", \"ts\": %lld, "
           "\"pid\": \"cycle\", \"tid\": %d, \"s\": \"g\"}",
           cycle_++, static_cast<long long>(NowUs()), rank_);
  MutexLock lk(mu_);
  queue_.push(Event{std::string(buf)});
}

}  // namespace hvdtpu
