#include "socket_util.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <mutex>
#include <thread>

#include "profiler.h"

namespace hvdtpu {

int TcpListen(int port, int backlog, int* out_port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, backlog) < 0) {
    close(fd);
    return -1;
  }
  if (out_port != nullptr) {
    socklen_t len = sizeof(addr);
    if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) < 0) {
      close(fd);
      return -1;
    }
    *out_port = ntohs(addr.sin_port);
  }
  return fd;
}

int TcpAccept(int listen_fd) {
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

int TcpAcceptTimeout(int listen_fd, int timeout_ms) {
  if (!Readable(listen_fd, timeout_ms)) {
    errno = ETIMEDOUT;
    return -1;
  }
  return TcpAccept(listen_fd);
}

int TcpConnectRetry(const std::string& host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_str = std::to_string(port);
  while (true) {
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res) == 0 &&
        res != nullptr) {
      int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0) {
        if (connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          freeaddrinfo(res);
          return fd;
        }
        close(fd);
      }
      freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() >= deadline) {
      errno = ETIMEDOUT;
      return -1;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
}

int IoSliceMs(const IoControl* ctl) {
  if (ctl == nullptr) return 100;
  int64_t s = ctl->detect_slice_ms;
  return static_cast<int>(s < 1 ? 1 : (s > 1000 ? 1000 : s));
}

namespace {

// One sliced poll while a controlled transfer makes no progress. Returns -1
// (transfer must fail) on plane abort, observed peer death (POLLERR/POLLHUP
// with nothing left to read / POLLOUT side errors), or the no-progress
// deadline; 0 to retry the I/O.
int CtlWait(int fd, short events, IoControl* ctl, double last_progress) {
  if (ctl->is_aborted()) {
    errno = ECANCELED;
    return -1;
  }
  pollfd pfd{fd, events, 0};
  const double wait_t0 = MonoSeconds();
  int rc;
  {
    // Sampling-profiler phase tag (profiler.h): a sample landing inside
    // this poll is blocked-on-peer time, the same split AddWaitUs feeds
    // the perf-attribution WAIT bucket.
    ProfPhaseScope prof_wait(PerfPhase::WAIT);
    rc = poll(&pfd, 1, IoSliceMs(ctl));
  }
  // Peer-wait accounting for the tracing layer: every microsecond inside
  // this poll is time the transfer stalled on the peer, not the wire.
  ctl->AddWaitUs(static_cast<int64_t>((MonoSeconds() - wait_t0) * 1e6));
  if (rc > 0 && (pfd.revents & POLLNVAL) != 0) {
    ctl->MarkPeerFailed();
    errno = ECONNRESET;
    return -1;
  }
  if (rc > 0 && (pfd.revents & POLLERR) != 0) {
    // POLLERR is ambiguous on a socket with the zero-copy lane armed:
    // pending MSG_ZEROCOPY completion notifications sit on the error queue
    // and raise it without any real failure. SO_ERROR tells them apart —
    // zero means "errqueue data only" (the sending thread reaps it), so
    // the transfer just retries; nonzero is a genuine socket error.
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 ||
        soerr != 0) {
      ctl->MarkPeerFailed();
      errno = soerr != 0 ? soerr : ECONNRESET;
      return -1;
    }
    if ((pfd.revents & (events | POLLHUP)) == 0) {
      // Nothing but the errqueue flag: avoid a hard spin while the sender
      // thread drains its completions.
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      return 0;
    }
  }
  if (rc > 0 && (pfd.revents & POLLHUP) != 0 &&
      (pfd.revents & POLLIN) == 0) {
    // Hangup with no readable residue: the peer is gone. (POLLIN|POLLHUP
    // still drains buffered bytes; recv() == 0 catches the EOF after.)
    ctl->MarkPeerFailed();
    errno = ECONNRESET;
    return -1;
  }
  if (ctl->read_deadline_secs > 0 &&
      MonoSeconds() - last_progress > ctl->read_deadline_secs) {
    // Socket alive but silent past the deadline: a hung peer. Declare it
    // dead rather than blocking the world forever (the transport-level
    // analog of the coordinator's stall shutdown).
    ctl->MarkPeerFailed();
    errno = ETIMEDOUT;
    return -1;
  }
  return 0;
}

}  // namespace

int SendAll(int fd, const void* buf, size_t len, IoControl* ctl) {
  const char* p = static_cast<const char*>(buf);
  double last_progress = ctl != nullptr ? MonoSeconds() : 0;
  while (len > 0) {
    ssize_t n = send(fd, p, len,
                     MSG_NOSIGNAL | (ctl != nullptr ? MSG_DONTWAIT : 0));
    if (n < 0) {
      if (errno == EINTR) continue;
      if (ctl != nullptr && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (CtlWait(fd, POLLOUT, ctl, last_progress) != 0) return -1;
        continue;
      }
      if (ctl != nullptr) ctl->MarkPeerFailed();  // EPIPE/ECONNRESET/...
      return -1;
    }
    p += n;
    len -= static_cast<size_t>(n);
    if (ctl != nullptr && n > 0) last_progress = MonoSeconds();
  }
  return 0;
}

int RecvAll(int fd, void* buf, size_t len, IoControl* ctl) {
  char* p = static_cast<char*>(buf);
  double last_progress = ctl != nullptr ? MonoSeconds() : 0;
  while (len > 0) {
    ssize_t n = recv(fd, p, len, ctl != nullptr ? MSG_DONTWAIT : 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (ctl != nullptr && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (CtlWait(fd, POLLIN, ctl, last_progress) != 0) return -1;
        continue;
      }
      if (ctl != nullptr) ctl->MarkPeerFailed();
      return -1;
    }
    if (n == 0) {
      if (ctl != nullptr) ctl->MarkPeerFailed();
      errno = ECONNRESET;
      return -1;  // peer closed
    }
    p += n;
    len -= static_cast<size_t>(n);
    if (ctl != nullptr) last_progress = MonoSeconds();
  }
  return 0;
}

int SendAllVec(int fd, struct iovec* iov, int iovcnt, IoControl* ctl) {
  double last_progress = ctl != nullptr ? MonoSeconds() : 0;
  int i = 0;
  while (i < iovcnt) {
    if (iov[i].iov_len == 0) {
      ++i;
      continue;
    }
    msghdr mh;
    memset(&mh, 0, sizeof(mh));
    mh.msg_iov = iov + i;
    mh.msg_iovlen = static_cast<size_t>(iovcnt - i);
    ssize_t n = sendmsg(fd, &mh,
                        MSG_NOSIGNAL | (ctl != nullptr ? MSG_DONTWAIT : 0));
    if (n < 0) {
      if (errno == EINTR) continue;
      if (ctl != nullptr && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (CtlWait(fd, POLLOUT, ctl, last_progress) != 0) return -1;
        continue;
      }
      if (ctl != nullptr) ctl->MarkPeerFailed();
      return -1;
    }
    if (ctl != nullptr && n > 0) last_progress = MonoSeconds();
    // Advance past fully sent iovecs, then trim the partial head.
    size_t left = static_cast<size_t>(n);
    while (i < iovcnt && left >= iov[i].iov_len) {
      left -= iov[i].iov_len;
      ++i;
    }
    if (i < iovcnt && left > 0) {
      iov[i].iov_base = static_cast<char*>(iov[i].iov_base) + left;
      iov[i].iov_len -= left;
    }
  }
  return 0;
}

int SendRecvSegmented(
    int send_fd, const void* send_buf, size_t send_bytes, int recv_fd,
    void* recv_buf, size_t recv_bytes, size_t segment_bytes,
    const std::function<void(const uint8_t*, size_t, size_t)>& on_segment,
    IoControl* ctl) {
  if (segment_bytes == 0 || segment_bytes > recv_bytes) {
    segment_bytes = recv_bytes;
  }
  int send_rc = 0;
  // No sender thread for receive-only calls (TcpTransport::RecvSegmented
  // delegates here with send_bytes == 0 on every segmented ring hop).
  std::thread sender;
  if (send_bytes > 0) {
    sender = std::thread(
        [&] { send_rc = SendAll(send_fd, send_buf, send_bytes, ctl); });
  }
  int recv_rc = 0;
  if (recv_bytes > 0) {
    if (!on_segment) {
      recv_rc = RecvAll(recv_fd, recv_buf, recv_bytes, ctl);
    } else {
      // Receiver thread lands segments and publishes a high-water mark; the
      // calling thread consumes them (runs on_segment) as they arrive.
      // Segments are disjoint, so the mutex only guards the counters — the
      // handoff of each buffer region rides the received/consumed ordering.
      std::mutex mu;
      std::condition_variable cv;
      size_t received = 0;
      bool done = false;
      std::thread receiver([&] {
        char* p = static_cast<char*>(recv_buf);
        size_t off = 0;
        int rc = 0;
        while (off < recv_bytes) {
          size_t len = std::min(segment_bytes, recv_bytes - off);
          rc = RecvAll(recv_fd, p + off, len, ctl);
          if (rc != 0) break;
          off += len;
          {
            std::lock_guard<std::mutex> lk(mu);
            received = off;
          }
          cv.notify_one();
        }
        {
          std::lock_guard<std::mutex> lk(mu);
          done = true;
          if (rc != 0) recv_rc = rc;
        }
        cv.notify_one();
      });
      size_t consumed = 0;
      while (consumed < recv_bytes) {
        size_t avail;
        bool finished;
        {
          std::unique_lock<std::mutex> lk(mu);
          cv.wait(lk, [&] { return received > consumed || done; });
          avail = received;
          finished = done;
        }
        if (avail > consumed) {
          on_segment(static_cast<const uint8_t*>(recv_buf) + consumed,
                     consumed, avail - consumed);
          consumed = avail;
        } else if (finished) {
          break;  // receive error: recv_rc is set
        }
      }
      receiver.join();
    }
  }
  if (sender.joinable()) sender.join();
  return (send_rc != 0 || recv_rc != 0) ? -1 : 0;
}

int SendFrame(int fd, const std::vector<uint8_t>& payload) {
  // One vectored syscall for [u64 length][payload]: the header no longer
  // rides its own send (and, under TCP_NODELAY, its own packet).
  uint64_t len = payload.size();
  iovec iov[2] = {{&len, sizeof(len)},
                  {const_cast<uint8_t*>(payload.data()), payload.size()}};
  return SendAllVec(fd, iov, len > 0 ? 2 : 1);
}

int RecvFrame(int fd, std::vector<uint8_t>* payload) {
  uint64_t len = 0;
  if (RecvAll(fd, &len, sizeof(len)) != 0) return -1;
  payload->resize(len);
  if (len > 0 && RecvAll(fd, payload->data(), len) != 0) return -1;
  return 0;
}

bool Readable(int fd, int timeout_ms) {
  pollfd pfd{fd, POLLIN, 0};
  return poll(&pfd, 1, timeout_ms) > 0 && (pfd.revents & POLLIN) != 0;
}

void CloseFd(int fd) {
  if (fd >= 0) close(fd);
}

}  // namespace hvdtpu
