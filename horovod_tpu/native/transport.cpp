#include "transport.h"

#include <thread>

#include "socket_util.h"

namespace hvdtpu {

int TcpTransport::Send(const void* buf, size_t len) {
  if (len == 0) return 0;
  return SendAll(fd_, buf, len, ctl_);
}

int TcpTransport::Recv(void* buf, size_t len) {
  if (len == 0) return 0;
  return RecvAll(fd_, buf, len, ctl_);
}

int TcpTransport::RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                                const SegmentFn& on_segment) {
  if (len == 0) {
    return 0;
  }
  if (!on_segment) return RecvAll(fd_, buf, len, ctl_);
  if (segment_bytes == 0 || len < 2 * segment_bytes) {
    // One (or barely two) segments: background-receiver machinery buys
    // nothing; land the payload and run the callback once.
    int rc = RecvAll(fd_, buf, len, ctl_);
    if (rc == 0) on_segment(0, len);
    return rc;
  }
  // Reuse the pipelined receiver (background thread lands segments, the
  // calling thread consumes them) with a zero-byte send side.
  return SendRecvSegmented(-1, nullptr, 0, fd_, buf, len, segment_bytes,
                           on_segment, ctl_);
}

int TcpTransport::SendRecv(const void* send_buf, size_t send_bytes,
                           void* recv_buf, size_t recv_bytes,
                           size_t segment_bytes, const SegmentFn& on_segment) {
  if (on_segment && segment_bytes > 0 && recv_bytes >= 2 * segment_bytes) {
    return SendRecvSegmented(fd_, send_buf, send_bytes, fd_, recv_buf,
                             recv_bytes, segment_bytes, on_segment, ctl_);
  }
  int rc = 0;
  if (InlineSendSafe(send_bytes)) {
    // Payload fits the kernel socket buffers: blocking send then receive on
    // the calling thread — both peers sending first cannot deadlock, and
    // skipping the sender thread is the bulk of the small-message win.
    if (send_bytes > 0) rc = SendAll(fd_, send_buf, send_bytes, ctl_);
    if (rc == 0 && recv_bytes > 0) rc = RecvAll(fd_, recv_buf, recv_bytes, ctl_);
  } else {
    int send_rc = 0;
    std::thread sender([&] {
      if (send_bytes > 0) send_rc = SendAll(fd_, send_buf, send_bytes, ctl_);
    });
    int recv_rc = 0;
    if (recv_bytes > 0) recv_rc = RecvAll(fd_, recv_buf, recv_bytes, ctl_);
    sender.join();
    rc = (send_rc != 0 || recv_rc != 0) ? -1 : 0;
  }
  if (rc == 0 && on_segment && recv_bytes > 0) on_segment(0, recv_bytes);
  return rc;
}

}  // namespace hvdtpu
