#include "transport.h"

#include <errno.h>
#include <poll.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <thread>

#include "socket_util.h"

#if defined(__linux__)
#include <linux/errqueue.h>
#if __has_include(<linux/io_uring.h>)
#include <linux/io_uring.h>
#define HVD_HAVE_IO_URING 1
#endif
#endif

namespace hvdtpu {

namespace {

// Constants newer than this box's uapi headers (the kernel is probed at
// runtime either way; stale headers must not force the copy path).
#if defined(HVD_HAVE_IO_URING)
constexpr uint8_t kIoringOpSendZc = 47;      // IORING_OP_SEND_ZC (>= 6.0)
constexpr uint32_t kIoringCqeFNotif = 1u << 3;  // IORING_CQE_F_NOTIF
#endif

}  // namespace

// ---------------------------------------------------------------------------
// ZeroCopySender
// ---------------------------------------------------------------------------

#if defined(HVD_HAVE_IO_URING)
struct ZeroCopySender::UringLayout {
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned sq_mask = 0;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned cq_mask = 0;
  io_uring_cqe* cqes = nullptr;
  io_uring_sqe* sqes = nullptr;
  bool single_mmap = false;
  int64_t notifs_pending = 0;  // SEND_ZC buffer-release CQEs not yet seen
  bool send_zc_ok = true;      // flips off on -EINVAL (pre-6.0 kernel)
};
#else
struct ZeroCopySender::UringLayout {};
#endif

void ZeroCopySender::Init(int fd, ZeroCopyMode mode) {
  if (probed_) return;
  probed_ = true;
  fd_ = fd;
  mode_ = mode;
  lane_ = Lane::NONE;
  if (mode == ZeroCopyMode::OFF || fd < 0) return;
#if defined(HVD_HAVE_IO_URING)
  if (mode == ZeroCopyMode::URING) {
    // Probe order (docs/collectives.md): io_uring ring first; a failed
    // setup (seccomp'd container, old kernel, RLIMIT_MEMLOCK) falls
    // through to the MSG_ZEROCOPY probe below.
    io_uring_params params;
    memset(&params, 0, sizeof(params));
    long rfd = syscall(SYS_io_uring_setup, 8, &params);
    if (rfd >= 0) {
      ring_fd_ = static_cast<int>(rfd);
      sq_mem_bytes_ =
          params.sq_off.array + params.sq_entries * sizeof(unsigned);
      cq_mem_bytes_ =
          params.cq_off.cqes + params.cq_entries * sizeof(io_uring_cqe);
      const bool single = (params.features & IORING_FEAT_SINGLE_MMAP) != 0;
      if (single) {
        sq_mem_bytes_ = cq_mem_bytes_ =
            sq_mem_bytes_ > cq_mem_bytes_ ? sq_mem_bytes_ : cq_mem_bytes_;
      }
      sq_mem_ = mmap(nullptr, sq_mem_bytes_, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, ring_fd_, IORING_OFF_SQ_RING);
      cq_mem_ = single ? sq_mem_
                       : mmap(nullptr, cq_mem_bytes_, PROT_READ | PROT_WRITE,
                              MAP_SHARED | MAP_POPULATE, ring_fd_,
                              IORING_OFF_CQ_RING);
      sqe_mem_bytes_ = params.sq_entries * sizeof(io_uring_sqe);
      sqe_mem_ = mmap(nullptr, sqe_mem_bytes_, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, ring_fd_, IORING_OFF_SQES);
      if (sq_mem_ != MAP_FAILED && cq_mem_ != MAP_FAILED &&
          sqe_mem_ != MAP_FAILED) {
        uring_ = new UringLayout();
        auto* sqb = static_cast<uint8_t*>(sq_mem_);
        auto* cqb = static_cast<uint8_t*>(cq_mem_);
        uring_->sq_head =
            reinterpret_cast<unsigned*>(sqb + params.sq_off.head);
        uring_->sq_tail =
            reinterpret_cast<unsigned*>(sqb + params.sq_off.tail);
        uring_->sq_mask =
            *reinterpret_cast<unsigned*>(sqb + params.sq_off.ring_mask);
        uring_->sq_array =
            reinterpret_cast<unsigned*>(sqb + params.sq_off.array);
        uring_->cq_head =
            reinterpret_cast<unsigned*>(cqb + params.cq_off.head);
        uring_->cq_tail =
            reinterpret_cast<unsigned*>(cqb + params.cq_off.tail);
        uring_->cq_mask =
            *reinterpret_cast<unsigned*>(cqb + params.cq_off.ring_mask);
        uring_->cqes =
            reinterpret_cast<io_uring_cqe*>(cqb + params.cq_off.cqes);
        uring_->sqes = static_cast<io_uring_sqe*>(sqe_mem_);
        uring_->single_mmap = single;
        lane_ = Lane::URING;
        return;
      }
      UringClose();
    }
  }
#endif  // HVD_HAVE_IO_URING
#if defined(SO_ZEROCOPY) && defined(MSG_ZEROCOPY)
  int one = 1;
  if (setsockopt(fd, SOL_SOCKET, SO_ZEROCOPY, &one, sizeof(one)) == 0) {
    lane_ = Lane::MSG_ZC;
  }
#endif
  // EOPNOTSUPP/ENOPROTOOPT (AF_UNIX, old kernel): stay on the copy path.
}

ZeroCopySender::~ZeroCopySender() { UringClose(); }

void ZeroCopySender::UringClose() {
#if defined(HVD_HAVE_IO_URING)
  if (sq_mem_ != nullptr && sq_mem_ != MAP_FAILED) {
    munmap(sq_mem_, sq_mem_bytes_);
  }
  if (cq_mem_ != nullptr && cq_mem_ != MAP_FAILED && cq_mem_ != sq_mem_) {
    munmap(cq_mem_, cq_mem_bytes_);
  }
  if (sqe_mem_ != nullptr && sqe_mem_ != MAP_FAILED) {
    munmap(sqe_mem_, sqe_mem_bytes_);
  }
#endif
  sq_mem_ = cq_mem_ = sqe_mem_ = nullptr;
  if (ring_fd_ >= 0) close(ring_fd_);
  ring_fd_ = -1;
  delete uring_;
  uring_ = nullptr;
}

int ZeroCopySender::ReapCompletions() {
#if defined(SO_ZEROCOPY) && defined(MSG_ZEROCOPY)
  for (;;) {
    // Completion notifications ride the socket error queue as
    // sock_extended_err control messages (SO_EE_ORIGIN_ZEROCOPY), each
    // acking the inclusive range [ee_info, ee_data] of zerocopy sends.
    alignas(cmsghdr) char ctrl[128];
    msghdr mh;
    memset(&mh, 0, sizeof(mh));
    mh.msg_control = ctrl;
    mh.msg_controllen = sizeof(ctrl);
    ssize_t r = recvmsg(fd_, &mh, MSG_ERRQUEUE | MSG_DONTWAIT);
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
        return 0;  // queue empty
      }
      return -1;
    }
    for (cmsghdr* cm = CMSG_FIRSTHDR(&mh); cm != nullptr;
         cm = CMSG_NXTHDR(&mh, cm)) {
      auto* ee = reinterpret_cast<sock_extended_err*>(CMSG_DATA(cm));
      if (ee->ee_origin == SO_EE_ORIGIN_ZEROCOPY) {
        completed_ +=
            static_cast<int64_t>(ee->ee_data) - ee->ee_info + 1;
        if ((ee->ee_code & SO_EE_CODE_ZEROCOPY_COPIED) != 0) {
          ++copied_notifs_;
        }
      } else {
        // A real transmission error (ICMP, route gone) queued behind the
        // notifications: surface it as a lane failure.
        errno = ee->ee_errno != 0 ? ee->ee_errno : ECONNRESET;
        return -1;
      }
    }
  }
#else
  return 0;
#endif
}

int ZeroCopySender::DrainCompletions(IoControl* ctl) {
  double last_progress = MonoSeconds();
  while (completed_ < issued_) {
    int64_t before = completed_;
    if (ReapCompletions() != 0) {
      if (ctl != nullptr) ctl->MarkPeerFailed();
      return -1;
    }
    if (completed_ > before) {
      last_progress = MonoSeconds();
      continue;
    }
    if (ctl != nullptr && ctl->is_aborted()) {
      errno = ECANCELED;
      return -1;
    }
    if (ctl != nullptr && ctl->read_deadline_secs > 0 &&
        MonoSeconds() - last_progress > ctl->read_deadline_secs) {
      // The peer must consume our bytes for the kernel to release the
      // pages; a silent peer therefore stalls the drain exactly like a
      // stalled read — same escalation (docs/fault-tolerance.md).
      ctl->MarkPeerFailed();
      errno = ETIMEDOUT;
      return -1;
    }
    // poll with no requested events still reports POLLERR, which is how
    // errqueue readiness surfaces — the completion wait folds into the
    // same slice discipline as every other blocking transport op.
    pollfd pfd{fd_, 0, 0};
    const double wait_t0 = MonoSeconds();
    poll(&pfd, 1, IoSliceMs(ctl));
    if (ctl != nullptr) {
      // Peer-wait accounting (tracing): a completion drain parks on the
      // peer consuming our bytes, exactly like a blocked send.
      ctl->AddWaitUs(static_cast<int64_t>((MonoSeconds() - wait_t0) * 1e6));
    }
    if ((pfd.revents & (POLLHUP | POLLNVAL)) != 0 &&
        (pfd.revents & POLLERR) == 0) {
      if (ctl != nullptr) ctl->MarkPeerFailed();
      errno = ECONNRESET;
      return -1;
    }
  }
  return 0;
}

#if defined(HVD_HAVE_IO_URING)
namespace {
// Reap every currently-visible CQE; reports (via out-params) the result
// CQE, if one appeared, and adjusts the SEND_ZC notification debt. Returns
// the number of CQEs consumed.
struct UringCqeScan {
  bool got_result = false;
  ssize_t res = 0;
};
}  // namespace

int ZeroCopySender::UringSubmitSend(const void* buf, size_t len,
                                    IoControl* ctl) {
  // One SQE at a time, partial sends looped — a submission lane, not a
  // batching engine; the payloads here are single large buffers. SEND_ZC
  // (zero-copy, two CQEs: result + buffer-release notification) when the
  // kernel has it, with an -EINVAL downgrade to plain IORING_OP_SEND.
  const char* p = static_cast<const char*>(buf);
  size_t off = 0;
  double last_progress = MonoSeconds();
  auto reap_visible = [&](UringCqeScan* scan) -> int {
    unsigned chead = *uring_->cq_head;
    unsigned ctail = __atomic_load_n(uring_->cq_tail, __ATOMIC_ACQUIRE);
    int consumed = 0;
    while (chead != ctail) {
      io_uring_cqe* cqe = &uring_->cqes[chead & uring_->cq_mask];
      if ((cqe->flags & kIoringCqeFNotif) != 0) {
        --uring_->notifs_pending;
      } else if (scan != nullptr) {
        scan->got_result = true;
        scan->res = cqe->res;
        if ((cqe->flags & IORING_CQE_F_MORE) != 0) {
          ++uring_->notifs_pending;  // SEND_ZC: release CQE still due
        }
      }
      ++chead;
      ++consumed;
    }
    __atomic_store_n(uring_->cq_head, chead, __ATOMIC_RELEASE);
    return consumed;
  };
  auto wait_for_cqes = [&]() -> int {
    // Nothing visible in the mapped ring: with a CQ of only ~16 entries,
    // deferred SEND_ZC notifications can land in the kernel's overflow
    // backlog, which is flushed into the ring only by an enter() with
    // GETEVENTS — poll() alone would wait forever on backlogged CQEs.
    (void)syscall(SYS_io_uring_enter, ring_fd_, 0, 0,
                  IORING_ENTER_GETEVENTS, nullptr, 0);
    if (ctl != nullptr && ctl->is_aborted()) {
      errno = ECANCELED;
      return -1;
    }
    if (ctl != nullptr && ctl->read_deadline_secs > 0 &&
        MonoSeconds() - last_progress > ctl->read_deadline_secs) {
      ctl->MarkPeerFailed();
      errno = ETIMEDOUT;
      return -1;
    }
    pollfd pfd{ring_fd_, POLLIN, 0};
    const double wait_t0 = MonoSeconds();
    poll(&pfd, 1, IoSliceMs(ctl));
    if (ctl != nullptr) {
      ctl->AddWaitUs(static_cast<int64_t>((MonoSeconds() - wait_t0) * 1e6));
    }
    return 0;
  };
  while (off < len) {
    if (ctl != nullptr && ctl->is_aborted()) {
      errno = ECANCELED;
      return -1;
    }
    // Keep notification headroom in the tiny CQ: drain before staging
    // another SEND_ZC when half the ring could already be owed.
    while (uring_->notifs_pending >= 8) {
      if (reap_visible(nullptr) > 0) {
        last_progress = MonoSeconds();
        continue;
      }
      if (wait_for_cqes() != 0) return -1;
    }
    unsigned tail = *uring_->sq_tail;
    unsigned idx = tail & uring_->sq_mask;
    io_uring_sqe* sqe = &uring_->sqes[idx];
    memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = uring_->send_zc_ok
                      ? kIoringOpSendZc
                      : static_cast<uint8_t>(IORING_OP_SEND);
    sqe->fd = fd_;
    sqe->addr = reinterpret_cast<uint64_t>(p + off);
    sqe->len = static_cast<uint32_t>(len - off);
    sqe->msg_flags = MSG_NOSIGNAL;
    uring_->sq_array[idx] = idx;
    __atomic_store_n(uring_->sq_tail, tail + 1, __ATOMIC_RELEASE);
    // The SQE is staged exactly once; only the enter() is retried on
    // EINTR/partial consumption — re-staging would queue a duplicate send
    // of the same byte range and corrupt the stream.
    int to_submit = 1;
    while (to_submit > 0) {
      long rc =
          syscall(SYS_io_uring_enter, ring_fd_, to_submit, 0, 0, nullptr, 0);
      if (rc < 0) {
        if (errno == EINTR) continue;
        return -1;
      }
      to_submit -= static_cast<int>(rc);
    }
    // Wait for the result CQE (and count SEND_ZC notification CQEs as they
    // arrive; stragglers are drained after the final byte).
    UringCqeScan scan;
    while (!scan.got_result) {
      if (reap_visible(&scan) > 0) {
        last_progress = MonoSeconds();
        continue;
      }
      if (wait_for_cqes() != 0) return -1;
    }
    ssize_t res = scan.res;
    if (res < 0) {
      if (res == -EINVAL && uring_->send_zc_ok) {
        // Kernel without SEND_ZC: downgrade this lane to plain OP_SEND
        // submissions (still the io_uring lane, no longer zero-copy).
        uring_->send_zc_ok = false;
        continue;
      }
      if (res == -EAGAIN || res == -EINTR) continue;
      errno = static_cast<int>(-res);
      if (ctl != nullptr) ctl->MarkPeerFailed();
      return -1;
    }
    off += static_cast<size_t>(res);
    last_progress = MonoSeconds();
  }
  // Drain outstanding SEND_ZC notifications: the caller reuses the buffer
  // the moment we return, so every page reference must be gone.
  while (uring_->notifs_pending > 0) {
    if (reap_visible(nullptr) > 0) {
      last_progress = MonoSeconds();
      continue;
    }
    if (wait_for_cqes() != 0) return -1;
  }
  return 0;
}
#else
int ZeroCopySender::UringSubmitSend(const void*, size_t, IoControl*) {
  errno = EOPNOTSUPP;
  return 1;
}
#endif  // HVD_HAVE_IO_URING

int ZeroCopySender::SendAll(const void* buf, size_t len, IoControl* ctl) {
  if (lane_ == Lane::URING) {
    int rc = UringSubmitSend(buf, len, ctl);
    if (rc > 0) {
      lane_ = Lane::NONE;  // ring unusable at send time: copy path from here
      return 1;
    }
    if (rc == 0) ++sends_;
    return rc;
  }
#if defined(SO_ZEROCOPY) && defined(MSG_ZEROCOPY)
  const char* p = static_cast<const char*>(buf);
  size_t off = 0;
  const int64_t issued_before = issued_;
  const int64_t copied_before = copied_notifs_;
  double last_progress = MonoSeconds();
  while (off < len) {
    ssize_t n = send(fd_, p + off, len - off,
                     MSG_NOSIGNAL | MSG_DONTWAIT | MSG_ZEROCOPY);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EOPNOTSUPP && off == 0 && issued_ == issued_before) {
        // Probe passed but the send path refused (e.g. a socket family
        // that accepts SO_ZEROCOPY but not the flag): permanent fallback.
        lane_ = Lane::NONE;
        return 1;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == ENOBUFS) {
        const bool optmem_full = errno == ENOBUFS;
        // Full socket buffer (EAGAIN) or the optmem pinned-page accounting
        // limit (ENOBUFS — /proc/sys/net/core/optmem_max is 128 KB on many
        // hosts, far below one large send): reap completions to release
        // pinned pages before deciding how to wait.
        int64_t completed_before_reap = completed_;
        if (ReapCompletions() != 0) {
          if (ctl != nullptr) ctl->MarkPeerFailed();
          return -1;
        }
        if (completed_ > completed_before_reap) {
          last_progress = MonoSeconds();  // peer consumed: real progress
          continue;
        }
        if (optmem_full && completed_ == issued_) {
          // ENOBUFS with NOTHING outstanding: the accounting budget cannot
          // hold even one in-flight send on this host — zero-copy cannot
          // function. Disable the lane; untransmitted bytes take the copy
          // path (same byte stream, just copied).
          lane_ = Lane::NONE;
          if (off == 0) return 1;  // clean decline: caller falls back
          int rc = hvdtpu::SendAll(fd_, p + off, len - off, ctl);
          if (rc == 0) ++sends_;  // the prefix did ride zero-copy
          return rc;
        }
        if (ctl != nullptr && ctl->is_aborted()) {
          errno = ECANCELED;
          return -1;
        }
        if (ctl != nullptr && ctl->read_deadline_secs > 0 &&
            MonoSeconds() - last_progress > ctl->read_deadline_secs) {
          ctl->MarkPeerFailed();
          errno = ETIMEDOUT;
          return -1;
        }
        // ENOBUFS with sends outstanding: writable space is NOT the gate —
        // poll for errqueue readiness (POLLERR, events=0) so we sleep until
        // completions arrive instead of busy-spinning on an already
        // writable socket. EAGAIN waits for writability as usual.
        pollfd pfd{fd_, static_cast<short>(optmem_full ? 0 : POLLOUT), 0};
        const double wait_t0 = MonoSeconds();
        poll(&pfd, 1, IoSliceMs(ctl));
        if (ctl != nullptr) {
          ctl->AddWaitUs(
              static_cast<int64_t>((MonoSeconds() - wait_t0) * 1e6));
        }
        if ((pfd.revents & POLLNVAL) != 0) {
          if (ctl != nullptr) ctl->MarkPeerFailed();
          errno = ECONNRESET;
          return -1;
        }
        continue;
      }
      if (ctl != nullptr) ctl->MarkPeerFailed();
      return -1;
    }
    ++issued_;  // one errqueue notification per successful zerocopy send
    off += static_cast<size_t>(n);
    last_progress = MonoSeconds();
  }
  if (DrainCompletions(ctl) != 0) return -1;
  ++sends_;
  if (mode_ == ZeroCopyMode::AUTO &&
      copied_notifs_ - copied_before >= issued_ - issued_before &&
      issued_ > issued_before) {
    // Every completion of this send reported SO_EE_CODE_ZEROCOPY_COPIED:
    // the kernel copied anyway (loopback, non-SG NIC). Pinning pages and
    // reaping notifications is pure overhead then — back off to the plain
    // copy path for the rest of this connection's life.
    lane_ = Lane::NONE;
  }
  return 0;
#else
  (void)buf;
  (void)len;
  (void)ctl;
  lane_ = Lane::NONE;
  return 1;
#endif
}

// ---------------------------------------------------------------------------
// TcpTransport
// ---------------------------------------------------------------------------

int TcpTransport::Send(const void* buf, size_t len) {
  if (len == 0) return 0;
  if (zc_.ShouldUse(len)) {
    int rc = zc_.SendAll(buf, len, ctl_);
    if (rc <= 0) return rc;
    ++zc_fallbacks_;  // rc > 0: lane declined, fall through to the copy path
  } else if (zc_mode_ != ZeroCopyMode::OFF && len >= ZeroCopySender::kMinBytes) {
    ++zc_fallbacks_;  // zero-copy requested but unavailable on this lane
  }
  return SendAll(fd_, buf, len, ctl_);
}

int TcpTransport::Recv(void* buf, size_t len) {
  if (len == 0) return 0;
  return RecvAll(fd_, buf, len, ctl_);
}

int TcpTransport::RecvSegmented(void* buf, size_t len, size_t segment_bytes,
                                size_t view_align,
                                const SegmentFn& on_segment) {
  (void)view_align;  // TCP lands every byte in buf: views are buf-backed
  if (len == 0) {
    return 0;
  }
  if (!on_segment) return RecvAll(fd_, buf, len, ctl_);
  if (segment_bytes == 0 || len < 2 * segment_bytes) {
    // One (or barely two) segments: background-receiver machinery buys
    // nothing; land the payload and run the callback once.
    int rc = RecvAll(fd_, buf, len, ctl_);
    if (rc == 0) on_segment(static_cast<const uint8_t*>(buf), 0, len);
    return rc;
  }
  // Reuse the pipelined receiver (background thread lands segments, the
  // calling thread consumes them) with a zero-byte send side.
  return SendRecvSegmented(-1, nullptr, 0, fd_, buf, len, segment_bytes,
                           on_segment, ctl_);
}

int TcpTransport::SendRecv(const void* send_buf, size_t send_bytes,
                           void* recv_buf, size_t recv_bytes,
                           size_t segment_bytes, size_t view_align,
                           const SegmentFn& on_segment) {
  if (on_segment && segment_bytes > 0 && recv_bytes >= 2 * segment_bytes) {
    // Sender thread + segmented receive on the calling thread. The send
    // side goes through Send() so large payloads ride the zero-copy lane.
    int send_rc = 0;
    std::thread sender([&] { send_rc = Send(send_buf, send_bytes); });
    int recv_rc = RecvSegmented(recv_buf, recv_bytes, segment_bytes,
                                view_align, on_segment);
    sender.join();
    return (send_rc != 0 || recv_rc != 0) ? -1 : 0;
  }
  int rc = 0;
  if (InlineSendSafe(send_bytes)) {
    // Payload fits the kernel socket buffers: blocking send then receive on
    // the calling thread — both peers sending first cannot deadlock, and
    // skipping the sender thread is the bulk of the small-message win.
    if (send_bytes > 0) rc = SendAll(fd_, send_buf, send_bytes, ctl_);
    if (rc == 0 && recv_bytes > 0) rc = RecvAll(fd_, recv_buf, recv_bytes, ctl_);
  } else {
    int send_rc = 0;
    std::thread sender([&] { send_rc = Send(send_buf, send_bytes); });
    int recv_rc = 0;
    if (recv_bytes > 0) recv_rc = RecvAll(fd_, recv_buf, recv_bytes, ctl_);
    sender.join();
    rc = (send_rc != 0 || recv_rc != 0) ? -1 : 0;
  }
  if (rc == 0 && on_segment && recv_bytes > 0) {
    on_segment(static_cast<const uint8_t*>(recv_buf), 0, recv_bytes);
  }
  return rc;
}

}  // namespace hvdtpu
