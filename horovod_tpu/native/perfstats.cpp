#include "perfstats.h"

#include <algorithm>
#include <cinttypes>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace hvdtpu {

const char* PerfPhaseName(PerfPhase p) {
  switch (p) {
    case PerfPhase::WALL:
      return "wall";
    case PerfPhase::WAIT:
      return "wait";
    case PerfPhase::WIRE:
      return "wire";
    case PerfPhase::REDUCE:
      return "reduce";
    case PerfPhase::CODEC:
      return "codec";
  }
  return "?";
}

// ---------------------------------------------------------------------------
// P² quantile estimator
// ---------------------------------------------------------------------------

void P2Quantile::Observe(double x) {
  if (n_ < 5) {
    // Initial buffer: insert sorted.
    int i = static_cast<int>(n_);
    while (i > 0 && h_[i - 1] > x) {
      h_[i] = h_[i - 1];
      --i;
    }
    h_[i] = x;
    ++n_;
    if (n_ == 5) {
      for (int k = 0; k < 5; ++k) pos_[k] = k + 1;
    }
    return;
  }
  // Find the cell; adjust extreme markers.
  int k;
  if (x < h_[0]) {
    h_[0] = x;
    k = 0;
  } else if (x >= h_[4]) {
    h_[4] = x;
    k = 3;
  } else {
    k = 0;
    while (k < 3 && x >= h_[k + 1]) ++k;
  }
  for (int i = k + 1; i < 5; ++i) pos_[i] += 1;
  ++n_;
  // Desired positions for {min, q/2, q, (1+q)/2, max}.
  const double np1 = static_cast<double>(n_);
  const double want[5] = {1.0, 1.0 + (np1 - 1.0) * q_ / 2.0,
                          1.0 + (np1 - 1.0) * q_,
                          1.0 + (np1 - 1.0) * (1.0 + q_) / 2.0, np1};
  for (int i = 1; i <= 3; ++i) {
    const double d = want[i] - pos_[i];
    if ((d >= 1.0 && pos_[i + 1] - pos_[i] > 1.0) ||
        (d <= -1.0 && pos_[i - 1] - pos_[i] < -1.0)) {
      const double sgn = d >= 0 ? 1.0 : -1.0;
      // Piecewise-parabolic (P²) interpolation; fall back to linear when
      // the parabola would leave the bracketing markers.
      const double qp =
          h_[i] + sgn / (pos_[i + 1] - pos_[i - 1]) *
                      ((pos_[i] - pos_[i - 1] + sgn) * (h_[i + 1] - h_[i]) /
                           (pos_[i + 1] - pos_[i]) +
                       (pos_[i + 1] - pos_[i] - sgn) * (h_[i] - h_[i - 1]) /
                           (pos_[i] - pos_[i - 1]));
      if (h_[i - 1] < qp && qp < h_[i + 1]) {
        h_[i] = qp;
      } else {
        const int j = i + static_cast<int>(sgn);
        h_[i] += sgn * (h_[j] - h_[i]) / (pos_[j] - pos_[i]);
      }
      pos_[i] += sgn;
    }
  }
}

double P2Quantile::Value() const {
  if (n_ == 0) return 0.0;
  if (n_ < 5) {
    // Exact quantile of the sorted initial buffer (nearest-rank).
    const int64_t idx =
        std::min<int64_t>(n_ - 1,
                          static_cast<int64_t>(q_ * static_cast<double>(n_)));
    return h_[idx];
  }
  return h_[2];
}

// ---------------------------------------------------------------------------
// PerfStats
// ---------------------------------------------------------------------------

namespace {

// Writer-side spinlock guard for one slot. Production has a single writer
// (the background loop), so the acquire is one uncontended test-and-set;
// the lock exists to keep explicitly concurrent writers (unit fixtures)
// and the TSan model honest.
class SlotLock {
 public:
  explicit SlotLock(PerfSlot* s) : s_(s) {
    while (s_->lock.test_and_set(std::memory_order_acquire)) {
    }
  }
  ~SlotLock() { s_->lock.clear(std::memory_order_release); }

 private:
  PerfSlot* s_;
};

void InitSlot(PerfSlot* s, const std::string& key) {
  for (int p = 0; p < kPerfPhases; ++p) {
    s->p50[p].Init(0.5);
    s->p99[p].Init(0.99);
  }
  s->key = key;
}

// JSON number: integers render exactly, everything else with enough digits
// to round-trip (same policy as the metrics exposition renderer).
std::string Num(double v) {
  char buf[64];
  if (std::isfinite(v) && v == static_cast<double>(static_cast<int64_t>(v)) &&
      std::abs(v) < 1e15) {
    snprintf(buf, sizeof(buf), "%" PRId64, static_cast<int64_t>(v));
  } else if (!std::isfinite(v)) {
    return "0";  // JSON has no inf/nan; perf stats never produce them anyway
  } else {
    snprintf(buf, sizeof(buf), "%.17g", v);
    double parsed = strtod(buf, nullptr);
    for (int prec = 1; prec < 17; ++prec) {
      char cand[64];
      snprintf(cand, sizeof(cand), "%.*g", prec, v);
      if (strtod(cand, nullptr) == parsed) {
        memcpy(buf, cand, sizeof(cand));
        break;
      }
    }
  }
  return buf;
}

// One phase-indexed JSON object from a published atomic array.
std::string PhaseObj(const std::atomic<double>* vals) {
  std::string out = "{";
  for (int p = 0; p < kPerfPhases; ++p) {
    if (p > 0) out += ", ";
    out += "\"";
    out += PerfPhaseName(static_cast<PerfPhase>(p));
    out += "\": ";
    out += Num(vals[p].load(std::memory_order_relaxed));
  }
  out += "}";
  return out;
}

}  // namespace

// JSON string escape for key signatures (tensor names are user-controlled:
// quotes/backslashes/control bytes must not break the /perfz payload or the
// perf_profile anomaly log core.cpp assembles).
std::string JsonEscapeString(const std::string& s) {
  std::string out = "\"";
  for (char c : s) {
    switch (c) {
      case '"':
        out += "\\\"";
        break;
      case '\\':
        out += "\\\\";
        break;
      case '\n':
        out += "\\n";
        break;
      case '\t':
        out += "\\t";
        break;
      case '\r':
        out += "\\r";
        break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  out += "\"";
  return out;
}

void PerfStats::Configure(bool enabled, double slowdown_pct,
                          int64_t min_samples) {
  enabled_ = enabled;
  slowdown_pct_ = slowdown_pct;
  min_samples_ = min_samples > 0 ? min_samples : 1;
  if (!enabled_) return;
  slots_.reset(new PerfSlot[kPerfMaxKeys]);
  InitSlot(&slots_[0], "<keys-overflowed>");
  key_ids_.clear();
  nslots_.store(1, std::memory_order_release);
  anomalies_total_.store(0, std::memory_order_relaxed);
}

int PerfStats::KeySlot(const std::string& key) {
  if (!enabled_) return 0;
  auto it = key_ids_.find(key);
  if (it != key_ids_.end()) return it->second;
  const int n = nslots_.load(std::memory_order_relaxed);  // atomic-ok: single-writer reads its own count
  if (n >= kPerfMaxKeys) return 0;  // table full: share the overflow slot
  InitSlot(&slots_[n], key);
  nslots_.store(n + 1, std::memory_order_release);  // publish complete slot
  key_ids_.emplace(key, n);
  return n;
}

PerfStats::Anomaly PerfStats::RecordOp(int slot, const OpSample& s) {
  Anomaly a;
  if (!enabled_ || slot < 0 ||
      slot >= nslots_.load(std::memory_order_acquire)) {
    return a;
  }
  PerfSlot* sl = &slots_[slot];
  const double phase_vals[kPerfPhases] = {
      static_cast<double>(s.wall_us), static_cast<double>(s.wait_us),
      static_cast<double>(s.wire_us), static_cast<double>(s.reduce_us),
      static_cast<double>(s.codec_us)};
  SlotLock lk(sl);
  const int64_t n = sl->count.load(std::memory_order_relaxed);

  // Sentry BEFORE the baseline absorbs this sample: a 3x-slower op must be
  // judged against the history, not against itself. The shared overflow
  // slot 0 mixes every key past the table cap into one baseline — a 4KB op
  // judged against 64MB history would fire forever — so it streams stats
  // but never sentries.
  if (slowdown_pct_ > 0 && slot != 0 && n >= min_samples_) {
    const double baseline = sl->ewma[0];
    if (baseline > 0 &&
        phase_vals[0] > baseline * (1.0 + slowdown_pct_ / 100.0)) {
      a.fired = true;
      a.ratio = phase_vals[0] / baseline;
      a.baseline_us = baseline;
      // Dominant phase: largest excess over its own baseline. A slowdown
      // with no phase excess (all buckets at baseline, wall still slow —
      // e.g. a descheduled process) stays attributed to WALL.
      double best = 0;
      for (int p = 1; p < kPerfPhases; ++p) {
        const double excess = phase_vals[p] - sl->ewma[p];
        if (excess > best) {
          best = excess;
          a.phase = static_cast<PerfPhase>(p);
        }
      }
      if (a.phase == PerfPhase::WAIT || a.phase == PerfPhase::WIRE) {
        a.slow_peer = s.slow_peer;
      }
      sl->anomalies.fetch_add(1, std::memory_order_relaxed);
      anomalies_total_.fetch_add(1, std::memory_order_relaxed);
    }
  }

  // Streaming update + publish. EWMA warmup: behave as a running mean for
  // the first samples (a fixed alpha would let the very first op pin the
  // baseline), then settle at alpha = 0.1.
  const double alpha = std::max(0.1, 1.0 / static_cast<double>(n + 1));
  for (int p = 0; p < kPerfPhases; ++p) {
    sl->ewma[p] = n == 0 ? phase_vals[p]
                         : sl->ewma[p] + alpha * (phase_vals[p] - sl->ewma[p]);
    sl->p50[p].Observe(phase_vals[p]);
    sl->p99[p].Observe(phase_vals[p]);
    sl->pub_ewma[p].store(sl->ewma[p], std::memory_order_relaxed);
    sl->pub_p50[p].store(sl->p50[p].Value(), std::memory_order_relaxed);
    sl->pub_p99[p].store(sl->p99[p].Value(), std::memory_order_relaxed);
  }
  sl->samples[n % kPerfSampleRing].store(s.wall_us,
                                         std::memory_order_relaxed);
  sl->last_wall_us.store(s.wall_us, std::memory_order_relaxed);
  sl->count.store(n + 1, std::memory_order_relaxed);
  return a;
}

bool PerfStats::ShouldWarn(int slot, int64_t now_us, int64_t min_gap_us) {
  if (slot < 0 || slot >= nslots_.load(std::memory_order_acquire)) {
    return false;
  }
  PerfSlot* sl = &slots_[slot];
  int64_t last = sl->last_warn_us.load(std::memory_order_relaxed);
  // 0 = never warned: the first anomaly of a key always logs. The CAS
  // claims the window — a concurrent loser sees the fresh stamp and stays
  // quiet.
  while (last == 0 || now_us - last >= min_gap_us) {
    if (sl->last_warn_us.compare_exchange_weak(last, now_us,
                                               std::memory_order_relaxed)) {
      return true;
    }
  }
  return false;
}

std::string PerfStats::SnapshotJson() const {
  std::string out = "{\"version\": 1, \"enabled\": ";
  out += enabled_ ? "true" : "false";
  out += ", \"slowdown_pct\": " + Num(slowdown_pct_);
  out += ", \"min_samples\": " + Num(static_cast<double>(min_samples_));
  out += ", \"anomalies_total\": " +
         Num(static_cast<double>(anomalies_total()));
  out += ", \"keys\": [";
  const int n = slot_count();
  bool first = true;
  for (int i = 0; i < n; ++i) {
    const PerfSlot& sl = slots_[i];
    const int64_t cnt = sl.count.load(std::memory_order_relaxed);
    if (cnt == 0) continue;  // overflow slot (or racing insert) never hit
    if (!first) out += ", ";
    first = false;
    out += "{\"key\": " + JsonEscapeString(sl.key);
    out += ", \"count\": " + Num(static_cast<double>(cnt));
    out += ", \"ewma_us\": " + PhaseObj(sl.pub_ewma);
    out += ", \"p50_us\": " + PhaseObj(sl.pub_p50);
    out += ", \"p99_us\": " + PhaseObj(sl.pub_p99);
    out += ", \"anomalies\": " +
           Num(static_cast<double>(sl.anomalies.load(
               std::memory_order_relaxed)));
    out += ", \"last_wall_us\": " +
           Num(static_cast<double>(sl.last_wall_us.load(
               std::memory_order_relaxed)));
    out += ", \"samples_us\": [";
    const int64_t have = std::min<int64_t>(cnt, kPerfSampleRing);
    for (int64_t k = 0; k < have; ++k) {
      if (k > 0) out += ", ";
      out += Num(static_cast<double>(
          sl.samples[k].load(std::memory_order_relaxed)));
    }
    out += "]}";
  }
  out += "]}";
  return out;
}

}  // namespace hvdtpu
