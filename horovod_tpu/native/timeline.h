// Chrome-tracing timeline writer.
//
// Reference: horovod/common/timeline.{h,cc} — per-op JSON events viewable in
// chrome://tracing / Perfetto, with NEGOTIATE / QUEUE / operation phases
// (phase names from horovod/common/common.h:32-66). Here events are written
// by a dedicated writer thread fed through a lock-free-enough queue, like the
// reference's async writer (timeline.cc:185-380).
#pragma once

#include <atomic>
#include <chrono>
#include <cstdio>
#include <queue>
#include <string>
#include <thread>

#include "common.h"

namespace hvdtpu {

class Timeline {
 public:
  ~Timeline();

  // No-op unless initialized. file comes from HVDTPU_TIMELINE.
  HVDTPU_CALLED_ON(any)
  void Initialize(const std::string& path, int rank) EXCLUDES(state_mu_, mu_);
  HVDTPU_CALLED_ON(any)
  void Shutdown() EXCLUDES(state_mu_, mu_);
  HVDTPU_CALLED_ON(any)
  bool Initialized() const { return initialized_; }

  // Phase events for a named tensor (tensor name becomes the trace "pid" row,
  // like the reference, timeline.cc:254-276).
  HVDTPU_CALLED_ON(any)
  void NegotiateStart(const std::string& name);
  HVDTPU_CALLED_ON(any)
  void NegotiateEnd(const std::string& name);
  HVDTPU_CALLED_ON(any)
  void QueueStart(const std::string& name);
  // `transport` (optional) tags the op with the data-plane lane summary
  // ("shm", "tcp", "shm+tcp", with "+hier" under the two-level allreduce) as
  // a Chrome-trace arg — visible in the Perfetto slice details.
  // `compression` (optional) sits next to it: the op's effective wire
  // compression ("none", "fp16", "int8", "int4").
  HVDTPU_CALLED_ON(any)
  void ActivityStart(const std::string& name, const std::string& activity,
                     const std::string& transport = "",
                     const std::string& compression = "");
  HVDTPU_CALLED_ON(any)
  void ActivityEnd(const std::string& name);
  // raw_bytes/wire_bytes (optional, -1 = omit): payload this rank would
  // have sent uncompressed vs bytes actually sent, from the data plane's
  // per-op counters — the compression-ratio measurement surface
  // (docs/timeline.md).
  HVDTPU_CALLED_ON(any)
  void OpDone(const std::string& name, const std::string& result,
              int64_t raw_bytes = -1, int64_t wire_bytes = -1);
  HVDTPU_CALLED_ON(any)
  void MarkCycle() EXCLUDES(state_mu_, mu_);  // HVDTPU_TIMELINE_MARK_CYCLES

  // --- distributed-tracing surface (docs/tracing.md) ----------------------
  // Complete ('X') span on track `track` (one Perfetto row per track per
  // rank). start/end are ABSOLUTE steady-clock microseconds (SteadyAbsUs);
  // the timeline converts to its own origin at emission, so emitters can
  // timestamp without taking state_mu_. args_json: "{...}" or "".
  HVDTPU_CALLED_ON(any)
  void Span(const std::string& track, const std::string& name,
            int64_t start_abs_us, int64_t end_abs_us,
            const std::string& args_json) EXCLUDES(state_mu_, mu_);
  // Trace-metadata instant on the reserved kTraceMetaTrack row: clock
  // offset ± error bound vs rank 0, steady/wall anchors — everything
  // scripts/trace_analyze.py needs to align this rank's events globally.
  HVDTPU_CALLED_ON(any)
  void Metadata(const std::string& args_json) EXCLUDES(state_mu_, mu_);
  // Absolute steady-clock now in microseconds (the spans' time base).
  HVDTPU_CALLED_ON(any)
  static int64_t SteadyAbsUs() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }
  // Absolute steady us of this timeline's ts origin (0 if uninitialized).
  HVDTPU_CALLED_ON(any)
  int64_t init_steady_us() EXCLUDES(state_mu_);

  static constexpr const char* kTraceMetaTrack = "__hvdtpu_trace_meta";

 private:
  struct Event {
    std::string json;
  };
  void Emit(const std::string& name, char ph, const std::string& args_json,
            const std::string& cat = "") EXCLUDES(state_mu_, mu_);
  // Queue one rendered event WITHOUT waking the writer: every emitter runs
  // on (or inside) the collective path, where a per-event futex wake
  // preempts the pipelined overlap on small hosts (measured up to ~8% at
  // 16 MB on a 1-CPU box). The writer is nudged at op boundaries (OpDone)
  // and otherwise drains on a 1 s backstop; Shutdown notifies for the
  // prompt final drain.
  void Push(std::string json) EXCLUDES(mu_);
  void WriterLoop() EXCLUDES(mu_);
  int64_t NowUs() const REQUIRES(state_mu_);

  // Lifecycle state can be mutated by the background thread (runtime
  // start/stop requests) while user threads Emit from EnqueueOp — state_mu_
  // guards it. Lock order: state_mu_ before mu_ (Emit/MarkCycle take both).
  Mutex state_mu_ ACQUIRED_BEFORE(mu_);
  // Lock-free fast-path check in Initialized(); every WRITE happens under
  // state_mu_ so Emit's snapshot (rank_/start_) stays consistent with it.
  std::atomic<bool> initialized_{false};  // atomic: seqcst(init latch, read via implicit loads)
  int rank_ GUARDED_BY(state_mu_) = 0;
  std::chrono::steady_clock::time_point start_ GUARDED_BY(state_mu_);
  int cycle_ GUARDED_BY(state_mu_) = 0;
  // Writer-thread-owned between Initialize and Shutdown: Initialize writes
  // file_/first_ before spawning writer_, Shutdown touches them only after
  // join(). Not GUARDED_BY — ownership transfers via thread start/join,
  // which the analysis cannot express (and no lock is ever needed).
  FILE* file_ = nullptr;
  bool first_ = true;
  Mutex mu_;
  CondVar cv_;
  std::queue<Event> queue_ GUARDED_BY(mu_);
  bool stop_ GUARDED_BY(mu_) = false;
  std::thread writer_;
};

}  // namespace hvdtpu
