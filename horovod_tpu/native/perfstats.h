// Always-on perf attribution: streaming per-op baselines + slowdown sentry
// (docs/observability.md "Live perf attribution").
//
// The sampled tracing layer (tracing.h) explains a slow op AFTER the fact
// and only for every Nth op; the flight recorder (flightrec.h) explains a
// DEAD job. This subsystem watches performance continuously while the job
// runs: unsampled, allocation-free streaming statistics — EWMA plus
// P²-style p50/p99 estimators — of op wall time and of the wait / wire /
// reduce / codec phase buckets, keyed by {tensor-set signature, algo,
// transport, hier, compression, op}. The phase buckets come from the SAME
// IoControl wait accounting and hop/reduce/quantize instrumentation points
// the flight recorder already proved fit the <2% observability budget at
// every-op granularity (DataPlane::TraceHop accumulates them per op).
//
// On top of the baselines sits the slowdown sentry: each completed op is
// compared against its key's rolling baseline, and past
// HVDTPU_PERF_SLOWDOWN_PCT the core emits an ANOMALY flight-recorder event
// plus a hvdtpu_perf_anomalies_total{phase=...} counter naming the dominant
// phase (and, for wire-slow ops, the slowest hop peer). Snapshots are JSON
// (hvdtpu_perfstats_snapshot C API -> hvd.perf_report() / the /perfz
// endpoint, decoded by horovod_tpu/perfstats.py), and each job can persist
// its per-key baselines as perf_profile.<rank>.json at shutdown for the
// cross-run regression sentry (scripts/perf_diff.py).
//
// Reference analog: none — upstream Horovod's timeline-driven tuning
// workflow (arxiv 1802.05799) and the 1810.11112 characterization do this
// analysis offline, by hand; here it is live and machine-checkable.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>

#include "thread_roles.h"

namespace hvdtpu {

// Phase buckets the streaming statistics track per key. Mirrored in
// horovod_tpu/perfstats.py PERF_PHASES (scripts/check_invariants.py
// ENUM-MIRROR): the codes cross the C++/Python boundary inside the /perfz
// JSON and the ANOMALY flight record's arg word.
enum class PerfPhase : int32_t {
  WALL = 0,    // whole-op wall time (the baseline the sentry compares)
  WAIT = 1,    // blocked on a peer (sliced polls, futex waits, zc drains)
  WIRE = 2,    // hop time actually moving bytes (hop duration - wait)
  REDUCE = 3,  // reduction kernels
  CODEC = 4,   // wire-compression quantize/dequantize
};
constexpr int kPerfPhases = 5;

const char* PerfPhaseName(PerfPhase p);

// Quote + escape `s` as a JSON string literal (quotes, backslashes, control
// bytes). Shared by the snapshot renderer and the anomaly log the core
// assembles into perf_profile.<rank>.json — tensor names are user-controlled
// and must not corrupt either payload.
std::string JsonEscapeString(const std::string& s);

// Streaming keyed-statistics sizing: a training job's steady state is a few
// dozen (fused tensor-set x parameter) combinations; keys past the cap
// share the overflow slot 0 so the hot path never allocates.
constexpr int kPerfMaxKeys = 256;
// Recent raw wall-time samples kept per key (ring): what perf_diff.py
// bootstraps its cross-run confidence intervals on.
constexpr int kPerfSampleRing = 64;

// P² single-quantile estimator (Jain & Chlamtac 1985): five markers track a
// running quantile in O(1) memory with no sample buffer — the classic
// streaming-quantile fit for an allocation-free hot path. Single writer;
// readers see the published value through PerfStats' atomics, never this.
class P2Quantile {
 public:
  HVDTPU_CALLED_ON(background)
  void Init(double q) {
    q_ = q;
    n_ = 0;
  }
  HVDTPU_CALLED_ON(background)
  void Observe(double x);
  // Current estimate: exact while n < 5 (sorted initial buffer), the P²
  // middle marker after.
  HVDTPU_CALLED_ON(any)
  double Value() const;
  HVDTPU_CALLED_ON(any)
  int64_t count() const { return n_; }

 private:
  double q_ = 0.5;
  int64_t n_ = 0;
  double h_[5] = {0};  // marker heights
  double pos_[5] = {0};  // marker positions (1-based)
};

// One key's streaming state. Writer fields are guarded by a per-slot
// spinlock (writers are the background loop in production — effectively
// uncontended — but the lock keeps explicitly concurrent writers, like the
// TSan unit fixture, correct). Published fields are relaxed atomics any
// thread may read mid-update: readers see torn SETS (a count newer than its
// p99), never torn values — the metrics registry's weak-consistency
// contract.
struct PerfSlot {
  // Writer-owned estimator state (guarded by lock).
  P2Quantile p50[kPerfPhases];
  P2Quantile p99[kPerfPhases];
  double ewma[kPerfPhases] = {0};
  std::atomic_flag lock = ATOMIC_FLAG_INIT;

  // Published, lock-free readable.
  std::atomic<int64_t> count{0};  // atomic: relaxed-counter
  std::atomic<double> pub_ewma[kPerfPhases] = {};  // atomic: relaxed-counter
  std::atomic<double> pub_p50[kPerfPhases] = {};  // atomic: relaxed-counter
  std::atomic<double> pub_p99[kPerfPhases] = {};  // atomic: relaxed-counter
  std::atomic<int64_t> anomalies{0};  // atomic: relaxed-counter
  std::atomic<int64_t> last_wall_us{0};  // atomic: relaxed-counter
  std::atomic<int64_t> samples[kPerfSampleRing] = {};  // atomic: relaxed-counter
  // Sentry WARN throttle stamp, PER KEY (steady-clock us; 0 = never
  // warned). A global 1/s throttle let one chatty slow key starve the
  // first warning for a second, different key — the operator's "rank N
  // just went codec-bound" signal. CAS-claimed so concurrent writers
  // (the TSan fixture) warn at most once per window per key.
  std::atomic<int64_t> last_warn_us{0};  // atomic: relaxed-counter

  std::string key;  // immutable once the slot is published
};

class PerfStats {
 public:
  // enabled=false turns RecordOp into one branch. slowdown_pct <= 0
  // disables the sentry (baselines still stream); min_samples is the
  // per-key warmup before the sentry may fire. Call before the background
  // loop starts.
  HVDTPU_CALLED_ON(background)
  void Configure(bool enabled, double slowdown_pct, int64_t min_samples);
  HVDTPU_CALLED_ON(any)
  bool enabled() const { return enabled_; }
  HVDTPU_CALLED_ON(any)
  double slowdown_pct() const { return slowdown_pct_; }
  HVDTPU_CALLED_ON(any)
  int64_t min_samples() const { return min_samples_; }

  // Intern `key` -> slot id (>= 1; 0 = the shared overflow slot once the
  // table fills). Background (collective-driving) thread only — it owns
  // the lookup map, like FlightRecorder::InternName. The slot itself is
  // release-published so snapshot readers only see complete entries.
  HVDTPU_CALLED_ON(background)
  int KeySlot(const std::string& key);

  struct OpSample {
    int64_t wall_us = 0;
    int64_t wait_us = 0;
    int64_t wire_us = 0;
    int64_t reduce_us = 0;
    int64_t codec_us = 0;
    int slow_peer = -1;  // hop peer with the most wait this op (-1 none)
  };
  struct Anomaly {
    bool fired = false;
    PerfPhase phase = PerfPhase::WALL;  // dominant phase of the excess
    double ratio = 1.0;                 // wall / baseline
    double baseline_us = 0.0;
    int slow_peer = -1;  // meaningful when phase is WAIT/WIRE
  };

  // Record one completed op against `slot` and run the sentry: fires once
  // the slot has min_samples and wall exceeds its EWMA baseline by
  // slowdown_pct. The overflow slot 0 streams stats but never sentries
  // (its baseline mixes unrelated keys). Thread-safe (per-slot spinlock);
  // no allocation.
  HVDTPU_CALLED_ON(background)
  Anomaly RecordOp(int slot, const OpSample& s);

  // Per-key WARN throttle for the sentry's log line: true at most once per
  // min_gap_us PER SLOT (each key gets its own window — a chatty slow key
  // cannot starve a different key's first warning). The counter and flight
  // ring record every anomaly regardless; only the LOG rides this. CAS on
  // the slot's stamp, so it is thread-safe and claims exactly one winner.
  HVDTPU_CALLED_ON(background)
  bool ShouldWarn(int slot, int64_t now_us,
                  int64_t min_gap_us = 1000000);

  // Keyed-baseline snapshot as JSON (the /perfz payload and the body of
  // perf_profile.<rank>.json). Readers touch atomics + immutable keys only
  // — callable from any thread while writers run.
  HVDTPU_CALLED_ON(any)
  std::string SnapshotJson() const;

  HVDTPU_CALLED_ON(any)
  int slot_count() const {
    return nslots_.load(std::memory_order_acquire);
  }
  HVDTPU_CALLED_ON(any)
  int64_t anomalies_total() const {
    return anomalies_total_.load(std::memory_order_relaxed);
  }
  HVDTPU_CALLED_ON(any)
  const PerfSlot* slot(int i) const {  // tests/introspection
    return i >= 0 && i < slot_count() ? &slots_[i] : nullptr;
  }

 private:
  bool enabled_ = false;
  double slowdown_pct_ = 50.0;
  int64_t min_samples_ = 20;
  std::unique_ptr<PerfSlot[]> slots_;
  std::atomic<int> nslots_{0};  // atomic: release-publish
  std::unordered_map<std::string, int> key_ids_;  // background thread only
  std::atomic<int64_t> anomalies_total_{0};  // atomic: relaxed-counter
};

}  // namespace hvdtpu
