#include "gradstats.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <limits>

#include "compressed.h"  // WireCompression / WireCompressionName
#include "perfstats.h"   // JsonEscapeString

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

namespace hvdtpu {

const char* NanPolicyName(NanPolicy p) {
  switch (p) {
    case NanPolicy::OFF:
      return "off";
    case NanPolicy::WARN:
      return "warn";
    case NanPolicy::ABORT:
      return "abort";
  }
  return "?";
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli)
// ---------------------------------------------------------------------------

namespace {

// Software slice-by-1 table for the reflected Castagnoli polynomial
// 0x82F63B78, built once. The hardware path below covers every modern x86;
// the table keeps non-SSE4.2 hosts (and other arches) correct, if slower.
const uint32_t* Crc32cTable() {
  static const uint32_t* table = [] {
    static uint32_t t[256];
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
      }
      t[i] = c;
    }
    return t;
  }();
  return table;
}

#if defined(__x86_64__)
bool HaveSse42() {
  static const bool ok = __builtin_cpu_supports("sse4.2") != 0;
  return ok;
}

__attribute__((target("sse4.2")))
uint32_t Crc32cHw(const uint8_t* p, size_t len, uint32_t crc) {
  while (len >= 8) {
    uint64_t v;
    memcpy(&v, p, 8);
    crc = static_cast<uint32_t>(_mm_crc32_u64(crc, v));
    p += 8;
    len -= 8;
  }
  while (len > 0) {
    crc = _mm_crc32_u8(crc, *p);
    ++p;
    --len;
  }
  return crc;
}
#endif

}  // namespace

uint32_t Crc32c(const void* data, size_t len, uint32_t seed) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint32_t crc = ~seed;
#if defined(__x86_64__)
  if (HaveSse42()) return ~Crc32cHw(p, len, crc);
#endif
  const uint32_t* t = Crc32cTable();
  for (size_t i = 0; i < len; ++i) {
    crc = t[(crc ^ p[i]) & 0xff] ^ (crc >> 8);
  }
  return ~crc;
}

// ---------------------------------------------------------------------------
// Moments kernels
// ---------------------------------------------------------------------------

namespace {



void MomentsF32Scalar(const float* src, int64_t count, GradMoments* m) {
  double sumsq = 0, absmax = m->absmax;
  int64_t nonfinite = 0;
  for (int64_t i = 0; i < count; ++i) {
    const float x = src[i];
    if (std::isfinite(x)) {
      sumsq += static_cast<double>(x) * static_cast<double>(x);
      const double a = std::fabs(static_cast<double>(x));
      if (a > absmax) absmax = a;
    } else {
      ++nonfinite;
    }
  }
  m->sumsq += sumsq;
  m->absmax = absmax;
  m->nonfinite += nonfinite;
  m->count += count;
}

#if defined(__x86_64__)
bool MomentsHaveAvx2() {
  static const bool ok = __builtin_cpu_supports("avx2") != 0;
  return ok;
}

// Fast 16-lane scan with optional fused copy (regular stores ONLY — a
// streaming-store variant was tried and REJECTED by the paired A/B: the
// collective re-reads this buffer chunk by chunk right after the copy,
// and NT stores cost 0.75x/0.87x at 16/64 MB in post-copy misses,
// BENCH_r10.json). The hot loop is UNMASKED — load, (store,) fmadd,
// and+max; five vector ops per 16 floats, cheap enough to ride a
// memory-bound copy even on a CPU-oversubscribed box (the earlier
// masked/movemask variant cost a visible fraction of the op under
// 4-ranks-per-core contention). The non-finite check is LAZY: any
// NaN/Inf input makes the accumulated sumsq non-finite (x*x propagates
// NaN and Inf through fmadd) — the wrapper detects that and reruns the
// precise masked pass, so clean tensors (the overwhelmingly common case)
// pay nothing for the sentinel. Block-local float accumulators drain
// into the double total every 4096 lanes so a 16M-element tensor loses
// no precision.
template <bool kCopy>
__attribute__((target("avx2,fma")))
void MomentsF32FastAvx2(float* dst, const float* src, int64_t count,
                        double* sumsq_out, double* absmax_out) {
  const __m256 abs_mask =
      _mm256_castsi256_ps(_mm256_set1_epi32(0x7fffffff));
  double sumsq = 0;
  __m256 vmax0 = _mm256_setzero_ps(), vmax1 = _mm256_setzero_ps();
  int64_t i = 0;
  while (i + 16 <= count) {
    const int64_t block_end = std::min<int64_t>(count - 15, i + 4096);
    __m256 vsum0 = _mm256_setzero_ps(), vsum1 = _mm256_setzero_ps();
    for (; i < block_end; i += 16) {
      __m256 x0 = _mm256_loadu_ps(src + i);
      __m256 x1 = _mm256_loadu_ps(src + i + 8);
      if (kCopy) {
        _mm256_storeu_ps(dst + i, x0);
        _mm256_storeu_ps(dst + i + 8, x1);
      }
      vsum0 = _mm256_fmadd_ps(x0, x0, vsum0);
      vsum1 = _mm256_fmadd_ps(x1, x1, vsum1);
      vmax0 = _mm256_max_ps(vmax0, _mm256_and_ps(x0, abs_mask));
      vmax1 = _mm256_max_ps(vmax1, _mm256_and_ps(x1, abs_mask));
    }
    alignas(32) float tmp[8];
    _mm256_store_ps(tmp, _mm256_add_ps(vsum0, vsum1));
    for (int k = 0; k < 8; ++k) sumsq += tmp[k];
  }
  alignas(32) float tmp[8];
  _mm256_store_ps(tmp, _mm256_max_ps(vmax0, vmax1));
  double absmax = 0;
  bool max_nan = false;
  for (int k = 0; k < 8; ++k) {
    if (tmp[k] != tmp[k]) max_nan = true;
    if (tmp[k] > absmax) absmax = tmp[k];
  }
  for (; i < count; ++i) {
    const float x = src[i];
    if (kCopy) dst[i] = x;
    sumsq += static_cast<double>(x) * static_cast<double>(x);
    const double a = std::fabs(static_cast<double>(x));
    if (a > absmax) absmax = a;
    if (x != x) max_nan = true;
  }
  *sumsq_out = sumsq;
  // A NaN lane can slip through max_ps (max(acc, NaN) takes the second
  // operand, but max(NaN, x) later drops it) — surface it through absmax
  // so the wrapper's non-finite detection stays sound.
  *absmax_out = max_nan
                    ? std::numeric_limits<double>::quiet_NaN()
                    : absmax;
}

// Detect-and-redo wrapper: run the fast unmasked kernel; when its totals
// came back non-finite (some input was NaN/Inf — or a square overflowed
// fp32, which the precise double-accumulating pass also repairs), rescan
// with the exact masked scalar pass. Copying is complete either way.
template <bool kCopy>
void MomentsF32Fast(float* dst, const float* src, int64_t count,
                    GradMoments* m) {
  double sumsq = 0, absmax = 0;
  MomentsF32FastAvx2<kCopy>(dst, src, count, &sumsq, &absmax);
  if (!std::isfinite(sumsq) || !std::isfinite(absmax)) {
    MomentsF32Scalar(src, count, m);  // exact: masked + counted
    return;
  }
  m->sumsq += sumsq;
  if (absmax > m->absmax) m->absmax = absmax;
  m->count += count;
}
#endif  // __x86_64__

}  // namespace

void MomentsF32(const float* src, int64_t count, GradMoments* m) {
  if (count <= 0) return;
#if defined(__x86_64__)
  if (MomentsHaveAvx2()) {
    MomentsF32Fast<false>(nullptr, src, count, m);
    return;
  }
#endif
  MomentsF32Scalar(src, count, m);
}

void CopyMomentsF32(float* dst, const float* src, int64_t count,
                    GradMoments* m) {
  if (count <= 0) return;
#if defined(__x86_64__)
  if (MomentsHaveAvx2()) {
    MomentsF32Fast<true>(dst, src, count, m);
    return;
  }
#endif
  memcpy(dst, src, static_cast<size_t>(count) * 4);
  MomentsF32Scalar(src, count, m);
}

// ---------------------------------------------------------------------------
// GradStats
// ---------------------------------------------------------------------------

namespace {

// Writer-side spinlock guard, same single-writer rationale as perfstats.cpp.
class GradSlotLock {
 public:
  explicit GradSlotLock(GradSlot* s) : s_(s) {
    while (s_->lock.test_and_set(std::memory_order_acquire)) {
    }
  }
  ~GradSlotLock() { s_->lock.clear(std::memory_order_release); }

 private:
  GradSlot* s_;
};

// JSON number, clamped finite (JSON has no inf/nan; a degenerate SNR of an
// all-zero tensor renders as 0).
std::string GNum(double v) {
  if (!std::isfinite(v)) return "0";
  char buf[64];
  // Magnitude gate BEFORE the int64 cast: casting a double >= 2^63 to
  // int64_t is UB ([conv.fpint]) and gradient norms/MSE are unbounded —
  // a pre-divergence absmax of 1e20 must render, not trip UBSan.
  if (std::abs(v) < 1e15 &&
      v == static_cast<double>(static_cast<int64_t>(v))) {
    snprintf(buf, sizeof(buf), "%lld",
             static_cast<long long>(static_cast<int64_t>(v)));
  } else {
    snprintf(buf, sizeof(buf), "%.9g", v);
  }
  return buf;
}

}  // namespace

void GradStats::Configure(bool enabled, NanPolicy policy, int64_t sample_n) {
  enabled_ = enabled;
  policy_ = policy;
  sample_n_ = sample_n > 0 ? sample_n : 0;
  if (!enabled_) return;
  slots_.reset(new GradSlot[kGradMaxKeys]);
  slots_[0].key = "<keys-overflowed>";
  key_ids_.clear();
  nslots_.store(1, std::memory_order_release);
  nonfinite_total_.store(0, std::memory_order_relaxed);
  probes_total_.store(0, std::memory_order_relaxed);
  divergence_total_.store(0, std::memory_order_relaxed);
  residual_resets_total_.store(0, std::memory_order_relaxed);
}

int GradStats::KeySlot(const std::string& key) {
  if (!enabled_) return 0;
  auto it = key_ids_.find(key);
  if (it != key_ids_.end()) return it->second;
  const int n = nslots_.load(std::memory_order_relaxed);  // atomic-ok: single-writer reads its own count
  if (n >= kGradMaxKeys) return 0;  // table full: share the overflow slot
  slots_[n].key = key;
  nslots_.store(n + 1, std::memory_order_release);  // publish complete slot
  key_ids_.emplace(key, n);
  return n;
}

void GradStats::RecordMoments(int slot, const GradMoments& m) {
  if (!enabled_ || slot < 0 ||
      slot >= nslots_.load(std::memory_order_acquire) || m.count <= 0) {
    return;
  }
  GradSlot* sl = &slots_[slot];
  const double norm = std::sqrt(m.sumsq);
  GradSlotLock lk(sl);
  const int64_t n = sl->count.load(std::memory_order_relaxed);
  // EWMA warmup: running mean first, then alpha = 0.1 (perfstats.cpp
  // rationale — the very first step must not pin the baseline).
  const double alpha = std::max(0.1, 1.0 / static_cast<double>(n + 1));
  sl->ewma_norm =
      n == 0 ? norm : sl->ewma_norm + alpha * (norm - sl->ewma_norm);
  sl->pub_norm.store(norm, std::memory_order_relaxed);
  sl->pub_ewma_norm.store(sl->ewma_norm, std::memory_order_relaxed);
  sl->pub_absmax.store(m.absmax, std::memory_order_relaxed);
  if (m.nonfinite > 0) {
    sl->nonfinite.fetch_add(m.nonfinite, std::memory_order_relaxed);
  }
  sl->count.store(n + 1, std::memory_order_relaxed);
}

void GradStats::RecordQuality(int slot, WireCompression c,
                              const GradQuality& q) {
  if (!enabled_ || slot < 0 ||
      slot >= nslots_.load(std::memory_order_acquire) || q.count <= 0) {
    return;
  }
  GradSlot* sl = &slots_[slot];
  const double mse = q.err2 / static_cast<double>(q.count);
  // SNR of a perfectly-represented signal (err2 == 0, e.g. fp16 codes of
  // exactly-representable values) is unbounded; clamp at a recognizable
  // ceiling so the JSON stays finite and comparisons stay ordered.
  const double snr_db =
      q.err2 > 0 ? 10.0 * std::log10(q.sig2 > 0 ? q.sig2 / q.err2 : 1.0)
                 : 200.0;
  GradSlotLock lk(sl);
  const int64_t n = sl->q_count.load(std::memory_order_relaxed);
  const double alpha = std::max(0.1, 1.0 / static_cast<double>(n + 1));
  sl->ewma_snr_db =
      n == 0 ? snr_db : sl->ewma_snr_db + alpha * (snr_db - sl->ewma_snr_db);
  sl->pub_mse.store(mse, std::memory_order_relaxed);
  sl->pub_snr_db.store(snr_db, std::memory_order_relaxed);
  sl->pub_ewma_snr_db.store(sl->ewma_snr_db, std::memory_order_relaxed);
  sl->pub_res_norm.store(std::sqrt(q.err2), std::memory_order_relaxed);
  sl->comp.store(static_cast<int32_t>(c), std::memory_order_relaxed);
  sl->q_count.store(n + 1, std::memory_order_relaxed);
}

bool GradStats::ShouldWarnNonfinite(int slot, int64_t now_us,
                                    int64_t min_gap_us) {
  if (!enabled_ || slot < 0 ||
      slot >= nslots_.load(std::memory_order_acquire)) {
    return false;
  }
  GradSlot* sl = &slots_[slot];
  int64_t last = sl->last_warn_us.load(std::memory_order_relaxed);
  while (last == 0 || now_us - last >= min_gap_us) {
    if (sl->last_warn_us.compare_exchange_weak(last, now_us,
                                               std::memory_order_relaxed)) {
      return true;
    }
  }
  return false;
}

std::string GradStats::SnapshotJson() const {
  std::string out = "{\"version\": 1, \"enabled\": ";
  out += enabled_ ? "true" : "false";
  out += ", \"nancheck\": \"";
  out += NanPolicyName(policy_);
  out += "\", \"gradcheck_sample\": " + GNum(static_cast<double>(sample_n_));
  out += ", \"nonfinite_total\": " +
         GNum(static_cast<double>(nonfinite_total()));
  out += ", \"probes_total\": " + GNum(static_cast<double>(probes_total()));
  out += ", \"divergence_total\": " +
         GNum(static_cast<double>(divergence_total()));
  out += ", \"residual_resets_total\": " +
         GNum(static_cast<double>(residual_resets_total()));
  out += ", \"keys\": [";
  const int n = slot_count();
  bool first = true;
  for (int i = 0; i < n; ++i) {
    const GradSlot& sl = slots_[i];
    const int64_t cnt = sl.count.load(std::memory_order_relaxed);
    const int64_t qcnt = sl.q_count.load(std::memory_order_relaxed);
    if (cnt == 0 && qcnt == 0) continue;  // never hit
    if (!first) out += ", ";
    first = false;
    out += "{\"key\": " + JsonEscapeString(sl.key);
    out += ", \"count\": " + GNum(static_cast<double>(cnt));
    out += ", \"norm\": " +
           GNum(sl.pub_norm.load(std::memory_order_relaxed));
    out += ", \"ewma_norm\": " +
           GNum(sl.pub_ewma_norm.load(std::memory_order_relaxed));
    out += ", \"absmax\": " +
           GNum(sl.pub_absmax.load(std::memory_order_relaxed));
    out += ", \"nonfinite\": " +
           GNum(static_cast<double>(
               sl.nonfinite.load(std::memory_order_relaxed)));
    out += ", \"quant_count\": " + GNum(static_cast<double>(qcnt));
    if (qcnt > 0) {
      // SNR fields exist ONLY for keys the compressed wire actually
      // touched: skip-regex layers (biases/norms) and dense ops stay
      // absent from the per-layer SNR report by construction.
      out += ", \"compression\": \"";
      out += WireCompressionName(static_cast<WireCompression>(
          sl.comp.load(std::memory_order_relaxed)));
      out += "\", \"mse\": " +
             GNum(sl.pub_mse.load(std::memory_order_relaxed));
      out += ", \"snr_db\": " +
             GNum(sl.pub_snr_db.load(std::memory_order_relaxed));
      out += ", \"ewma_snr_db\": " +
             GNum(sl.pub_ewma_snr_db.load(std::memory_order_relaxed));
      out += ", \"residual_norm\": " +
             GNum(sl.pub_res_norm.load(std::memory_order_relaxed));
    }
    out += "}";
  }
  out += "]}";
  return out;
}

}  // namespace hvdtpu
