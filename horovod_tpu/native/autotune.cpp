#include "autotune.h"

#include <algorithm>
#include <cmath>

namespace hvdtpu {

// ---------------------------------------------------------------------------
// GaussianProcess
// ---------------------------------------------------------------------------

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0.0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2.0 * length_scale_ * length_scale_));
}

void GaussianProcess::Fit(const std::vector<std::vector<double>>& x,
                          const std::vector<double>& y) {
  n_ = x.size();
  x_ = x;
  fitted_ = false;
  if (n_ == 0) return;

  // Standardize targets so the unit-variance kernel prior fits.
  y_mean_ = 0.0;
  for (double v : y) y_mean_ += v;
  y_mean_ /= static_cast<double>(n_);
  double var = 0.0;
  for (double v : y) var += (v - y_mean_) * (v - y_mean_);
  y_std_ = n_ > 1 ? std::sqrt(var / static_cast<double>(n_)) : 1.0;
  if (y_std_ < 1e-12) y_std_ = 1.0;

  // K + noise I, then its Cholesky factor L (n is tens at most).
  std::vector<double> k(n_ * n_);
  for (size_t i = 0; i < n_; ++i) {
    for (size_t j = 0; j < n_; ++j) {
      k[i * n_ + j] = Kernel(x_[i], x_[j]) + (i == j ? noise_ : 0.0);
    }
  }
  chol_.assign(n_ * n_, 0.0);
  for (size_t i = 0; i < n_; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double s = k[i * n_ + j];
      for (size_t m = 0; m < j; ++m) s -= chol_[i * n_ + m] * chol_[j * n_ + m];
      if (i == j) {
        if (s <= 0) s = 1e-12;
        chol_[i * n_ + i] = std::sqrt(s);
      } else {
        chol_[i * n_ + j] = s / chol_[j * n_ + j];
      }
    }
  }

  // alpha = K^-1 y_std  via two triangular solves.
  std::vector<double> z(n_);
  for (size_t i = 0; i < n_; ++i) {
    double s = (y[i] - y_mean_) / y_std_;
    for (size_t m = 0; m < i; ++m) s -= chol_[i * n_ + m] * z[m];
    z[i] = s / chol_[i * n_ + i];
  }
  alpha_.assign(n_, 0.0);
  for (size_t ii = n_; ii-- > 0;) {
    double s = z[ii];
    for (size_t m = ii + 1; m < n_; ++m) s -= chol_[m * n_ + ii] * alpha_[m];
    alpha_[ii] = s / chol_[ii * n_ + ii];
  }
  fitted_ = true;
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mu,
                              double* sigma) const {
  if (!fitted_) {
    *mu = 0.0;
    *sigma = 1.0;
    return;
  }
  std::vector<double> ks(n_);
  for (size_t i = 0; i < n_; ++i) ks[i] = Kernel(x, x_[i]);
  double m = 0.0;
  for (size_t i = 0; i < n_; ++i) m += ks[i] * alpha_[i];
  // v = L^-1 ks; var = k(x,x) - v.v
  std::vector<double> v(n_);
  for (size_t i = 0; i < n_; ++i) {
    double s = ks[i];
    for (size_t j = 0; j < i; ++j) s -= chol_[i * n_ + j] * v[j];
    v[i] = s / chol_[i * n_ + i];
  }
  double var = Kernel(x, x);
  for (size_t i = 0; i < n_; ++i) var -= v[i] * v[i];
  if (var < 1e-12) var = 1e-12;
  *mu = m * y_std_ + y_mean_;
  *sigma = std::sqrt(var) * y_std_;
}

// ---------------------------------------------------------------------------
// BayesianOptimizer
// ---------------------------------------------------------------------------

namespace {

double NormCdf(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }
double NormPdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

}  // namespace

void BayesianOptimizer::AddSample(const std::vector<double>& x, double y) {
  xs_.push_back(x);
  ys_.push_back(y);
  gp_.Fit(xs_, ys_);
}

std::vector<double> BayesianOptimizer::BestSample() const {
  size_t best = 0;
  for (size_t i = 1; i < ys_.size(); ++i) {
    if (ys_[i] > ys_[best]) best = i;
  }
  return xs_.empty() ? std::vector<double>(dim_, 0.5) : xs_[best];
}

std::vector<double> BayesianOptimizer::NextSample() {
  if (xs_.empty()) return std::vector<double>(dim_, 0.5);
  double y_best = *std::max_element(ys_.begin(), ys_.end());
  const double xi = 0.01;  // exploration margin (reference uses the same form)

  std::vector<double> best_x(dim_, 0.5);
  double best_ei = -1.0;
  // Deterministic candidate sweep: identical on every rank given the same
  // samples, so no cross-rank disagreement is possible even if workers ran it.
  const int kCandidates = 512;
  for (int c = 0; c < kCandidates; ++c) {
    std::vector<double> x(dim_);
    for (int d = 0; d < dim_; ++d) {
      rng_ = rng_ * 6364136223846793005ull + 1442695040888963407ull;
      x[d] = static_cast<double>((rng_ >> 11) & 0xfffff) / 1048575.0;
    }
    double mu, sigma;
    gp_.Predict(x, &mu, &sigma);
    double z = (mu - y_best - xi) / sigma;
    double ei = (mu - y_best - xi) * NormCdf(z) + sigma * NormPdf(z);
    if (ei > best_ei) {
      best_ei = ei;
      best_x = x;
    }
  }
  return best_x;
}

// ---------------------------------------------------------------------------
// ParameterManager
// ---------------------------------------------------------------------------

namespace {

// Tuning ranges, log-scale (reference tunes fusion in [0, 64 MB] linear and
// cycle in [1, 25] ms multiples-of-5; log-scale covers the same span with
// better resolution at the low end that matters for latency).
constexpr double kCycleMinMs = 0.5, kCycleMaxMs = 50.0;
constexpr double kFusionMin = 1 << 20, kFusionMax = 256u << 20;
// Allreduce algorithm crossover (data_plane.h): recursive doubling below,
// pipelined ring above. Log-scale 4 KB .. 4 MB.
constexpr double kCrossMin = 4 << 10, kCrossMax = 4 << 20;

double FromUnit(double u, double lo, double hi) {
  return lo * std::pow(hi / lo, u);
}
double ToUnit(double v, double lo, double hi) {
  v = std::min(std::max(v, lo), hi);
  return std::log(v / lo) / std::log(hi / lo);
}

}  // namespace

void ParameterManager::Initialize(double cycle_time_ms,
                                  int64_t fusion_threshold, bool cache_enabled,
                                  int64_t algo_crossover, bool tune_crossover,
                                  bool sa_enabled, bool tune_sa,
                                  bool hier_enabled, bool tune_hier,
                                  int32_t wire_compression,
                                  bool tune_compression,
                                  const std::string& log_path,
                                  int warmup_samples, int cycles_per_sample,
                                  int max_samples, double gp_noise) {
  current_ = {cycle_time_ms, fusion_threshold, cache_enabled, algo_crossover,
              sa_enabled, hier_enabled, wire_compression};
  tune_crossover_ = tune_crossover;
  tune_sa_ = tune_sa;
  tune_hier_ = tune_hier;
  tune_compression_ = tune_compression;
  warmup_samples_ = warmup_samples;
  warmup_left_ = warmup_samples;
  cycles_per_sample_ = cycles_per_sample;
  max_samples_ = max_samples;
  opt_ = BayesianOptimizer(3 + (tune_crossover ? 1 : 0) + (tune_sa ? 1 : 0) +
                               (tune_hier ? 1 : 0) + (tune_compression ? 1 : 0),
                           gp_noise);
  if (!log_path.empty()) {
    log_ = fopen(log_path.c_str(), "w");
    if (log_ != nullptr) {
      fputs("cycle_time_ms,fusion_threshold_bytes,cache_enabled,"
            "algo_crossover_bytes,sa_enabled,hier_enabled,wire_compression,"
            "score_bytes_per_sec\n",
            log_);
    }
  }
  active_ = true;
  frozen_ = false;
  cycle_count_ = 0;
  bytes_acc_ = 0;
  sample_start_ = 0.0;
}

ParameterManager::~ParameterManager() {
  if (log_ != nullptr) fclose(log_);
}

std::vector<double> ParameterManager::ToVector(const Params& p) const {
  // Dim 2 is the categorical cache switch: a {0,1}-valued coordinate the
  // candidate sweep explores continuously and SetFromVector thresholds
  // (the GP analog of the reference's CategoricalParameter).
  std::vector<double> x = {
      ToUnit(p.cycle_time_ms, kCycleMinMs, kCycleMaxMs),
      ToUnit(static_cast<double>(p.fusion_threshold), kFusionMin, kFusionMax),
      p.cache_enabled ? 1.0 : 0.0};
  if (tune_crossover_) {
    x.push_back(
        ToUnit(static_cast<double>(p.algo_crossover), kCrossMin, kCrossMax));
  }
  if (tune_sa_) x.push_back(p.sa_enabled ? 1.0 : 0.0);
  if (tune_hier_) x.push_back(p.hier_enabled ? 1.0 : 0.0);
  if (tune_compression_) {
    // 3-way categorical {none, fp16, int8} mapped onto [0, 1] at
    // {0, 0.5, 1}; the sweep explores continuously, SetFromVector rounds.
    x.push_back(static_cast<double>(p.wire_compression) / 2.0);
  }
  return x;
}

void ParameterManager::SetFromVector(const std::vector<double>& x) {
  current_.cycle_time_ms = FromUnit(x[0], kCycleMinMs, kCycleMaxMs);
  // llround, not truncation: FromUnit(ToUnit(v)) can land at v - 1e-7 and
  // a truncating cast would log the frozen best point one byte off the
  // sampled row it was chosen from.
  current_.fusion_threshold =
      static_cast<int64_t>(std::llround(FromUnit(x[1], kFusionMin,
                                                 kFusionMax)));
  current_.cache_enabled = x[2] >= 0.5;
  size_t next = 3;
  if (tune_crossover_ && x.size() > next) {
    current_.algo_crossover = static_cast<int64_t>(
        std::llround(FromUnit(x[next], kCrossMin, kCrossMax)));
    ++next;
  }
  if (tune_sa_ && x.size() > next) {
    // Categorical like the cache switch: big-message AUTO dispatch prefers
    // scatter-allgather when on, the pipelined ring when off.
    current_.sa_enabled = x[next] >= 0.5;
    ++next;
  }
  if (tune_hier_ && x.size() > next) {
    // Categorical like the cache switch: explored continuously, thresholded
    // here (reference: CategoricalParameter, parameter_manager.h:225).
    current_.hier_enabled = x[next] >= 0.5;
    ++next;
  }
  if (tune_compression_ && x.size() > next) {
    int32_t comp = static_cast<int32_t>(std::llround(x[next] * 2.0));
    if (comp < 0) comp = 0;
    if (comp > 2) comp = 2;
    current_.wire_compression = comp;  // 0 none, 1 fp16, 2 int8
  }
}

void ParameterManager::LogSample(double score) {
  if (log_ == nullptr) return;
  fprintf(log_, "%.3f,%lld,%d,%lld,%d,%d,%d,%.1f\n", current_.cycle_time_ms,
          static_cast<long long>(current_.fusion_threshold),
          current_.cache_enabled ? 1 : 0,
          static_cast<long long>(current_.algo_crossover),
          current_.sa_enabled ? 1 : 0, current_.hier_enabled ? 1 : 0,
          static_cast<int>(current_.wire_compression), score);
  fflush(log_);
}

bool ParameterManager::Update(int64_t bytes, double now_secs) {
  if (!active_ || frozen_) return false;
  if (sample_start_ == 0.0) sample_start_ = now_secs;
  bytes_acc_ += bytes;
  if (++cycle_count_ < cycles_per_sample_) return false;

  double elapsed = now_secs - sample_start_;
  double score = elapsed > 0 ? static_cast<double>(bytes_acc_) / elapsed : 0;
  cycle_count_ = 0;
  bytes_acc_ = 0;
  sample_start_ = now_secs;

  if (warmup_left_ > 0) {
    // Reference: discard warmup samples (still-compiling / cold caches).
    --warmup_left_;
    return false;
  }

  // Median-of-N scoring per tuning step: single samples are noisy (one
  // GC pause or burst skews bytes/sec), and the GP fit amplifies outliers.
  step_scores_.push_back(score);
  if (static_cast<int>(step_scores_.size()) < kScoresPerStep) return false;
  std::sort(step_scores_.begin(), step_scores_.end());
  score = step_scores_[step_scores_.size() / 2];
  step_scores_.clear();

  LogSample(score);
  opt_.AddSample(ToVector(current_), score);
  if (static_cast<int>(opt_.num_samples()) >= max_samples_) {
    SetFromVector(opt_.BestSample());
    frozen_ = true;  // reference: SetAutoTuning(false) once tuning concludes
    LogSample(-1.0);
    return true;
  }
  SetFromVector(opt_.NextSample());
  return true;
}

}  // namespace hvdtpu
