"""Object collectives: broadcast/allgather arbitrary picklable Python objects.

Reference: ``horovod/torch/functions.py`` (``broadcast_object`` :186,
``allgather_object`` :229) and the TF twins (``horovod/tensorflow/functions.py:59/:136``)
— objects are cloudpickled into byte tensors, sizes exchanged first, then payloads.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from . import runtime
from .ops import collectives as C


def _serialize(obj: Any) -> np.ndarray:
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return np.frombuffer(buf.getvalue(), dtype=np.uint8).copy()


def _deserialize(arr: np.ndarray) -> Any:
    return pickle.loads(np.asarray(arr, dtype=np.uint8).tobytes())


def broadcast_object(obj: Any = None, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Broadcast a picklable object from ``root_rank``
    (reference: ``horovod/torch/functions.py:186``)."""
    name = name or "broadcast_object"
    if runtime.mode() == "process" and runtime.size() > 1:
        # Rides the native broadcast (PR 19 binomial tree / flat fanout).
        # The two rounds stay sequential by necessity: non-roots cannot
        # size the payload buffer until the size broadcast lands.
        payload = _serialize(obj) if runtime.rank() == root_rank else \
            np.zeros(0, dtype=np.uint8)
        sz = np.array([payload.size], dtype=np.int64)
        sz = np.asarray(C.broadcast(sz, root_rank=root_rank, name=f"{name}.sz"))
        if runtime.rank() != root_rank:
            payload = np.zeros(int(sz[0]), dtype=np.uint8)
        out = np.asarray(C.broadcast(payload, root_rank=root_rank, name=name))
        return _deserialize(out)
    # SPMD / single process: the controller already holds the object.
    return obj


def allgather_object(obj: Any, name: Optional[str] = None) -> list:
    """Gather one picklable object per rank into a list ordered by rank
    (reference: ``horovod/torch/functions.py:229``)."""
    name = name or "allgather_object"
    if runtime.mode() == "process" and runtime.size() > 1:
        # Payload and size gathers are independent — enqueue both in one
        # grouped window (PR 19) so they share a single READY/RESPONSES
        # negotiation round instead of two blocking round-trips.
        payload = _serialize(obj)
        with C.grouped_enqueue():
            h_pay = C.allgather_async(payload, name=name)
            h_sz = C.allgather_async(
                np.array([payload.size], dtype=np.int64), name=f"{name}.sz")
        gathered = np.asarray(C.synchronize(h_pay))
        sizes = np.asarray(C.synchronize(h_sz))
        out, off = [], 0
        for s in sizes.tolist():
            out.append(_deserialize(gathered[off:off + int(s)]))
            off += int(s)
        return out
    return [obj] * runtime.size() if runtime.mode() == "spmd" else [obj]
